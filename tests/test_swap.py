"""Zero-downtime weight hot-swap (horovod_tpu/serve/swap.py):
checkpoint store → serving fleet without dropping or corrupting a
single request.

The oracles (ISSUE 14 acceptance):

* **manifest diff** — a swap pulls ONLY the shards whose digests
  changed, byte-counted;
* **staged-flip token identity** — a request straddling the swap
  finishes token-identical to the PRE-swap reference (in-flight
  generations run start-to-finish on one version), and post-flip
  requests match the new-weights reference;
* **digest rejection** — a corrupt shard discards the staged pull and
  the replica keeps serving the old weights;
* **rollback** — a journaled step restores bit-identically through the
  same staged-flip path;
* **mixed-version rules** — prefix-directory hits must match the
  replica's current version, and a migrated KV payload is refused by a
  receiver on different weights (stale KV against new weights is the
  silent-wrongness bug);
* **the chaos drill** at the bottom: bursty open-loop load through >=5
  rolling hot-swaps with randomized ``swap:*`` faults — 0 dropped
  requests, every response token-identical to the fixed-weights
  reference for its version, one rollback restoring prior weights
  bit-identically (``scripts/chaos_soak.py --mode swap`` loops it).
"""

import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import faults
from horovod_tpu.ckpt import (AsyncCheckpointer, ShardStore, diff_manifest,
                              take_snapshot)
from horovod_tpu.models.transformer import GPT, GPTConfig
from horovod_tpu.serve import (
    ContinuousBatcher, FleetController, InferenceEngine, InferenceServer,
    ReplicaKilledError, ReplicaLauncher, ReplicaSpec, Router,
    SamplingParams, SwapAbandonedError, SwapRejectedError,
    WeightSubscriber,
)
from horovod_tpu.serve.swap import leaf_digests
from horovod_tpu.utils.retry import RetryPolicy

pytestmark = pytest.mark.serving

KEY = b"k" * 32
VOCAB = 97


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model_and_versions():
    """One tiny GPT plus three GENUINELY different param versions
    (independent inits — greedy outputs differ between them, so a
    token stream proves which version produced it)."""
    cfg = GPTConfig(vocab_size=VOCAB, n_layer=2, n_head=2, d_model=32,
                    d_ff=64, max_seq_len=32, dtype=jnp.float32,
                    param_dtype=jnp.float32)
    model = GPT(cfg)
    versions = {
        v: model.init(jax.random.PRNGKey(100 + v),
                      jnp.zeros((1, 8), jnp.int32))["params"]
        for v in (1, 2, 3)
    }
    return model, versions


def _host(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _write_versions(directory, versions):
    store = ShardStore(directory)
    for step, tree in sorted(versions.items()):
        store.write_step(take_snapshot(_host(tree), step=step),
                         world=1, scheme="dp")
    return store


def _ref_tokens(model, params, prompt, n):
    seq = list(prompt)
    out = []
    for _ in range(n):
        logits = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32))
        tok = int(jnp.argmax(logits[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


def _engine(model, params, version=1, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("prefill_buckets", (8, 16))
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("kv_block", 4)
    return InferenceEngine(model, params, weights_version=version, **kw)


def _replica(model, params, tmp_path, name="rep", version=1, role="unified",
             start=True, **engine_kw):
    engine = _engine(model, params, version=version, **engine_kw)
    batcher = ContinuousBatcher(engine, max_queue=32,
                                default_deadline_s=60, role=role)
    server = InferenceServer(batcher, key=KEY, name=name,
                             host="127.0.0.1", start_batcher=start,
                             swap_store=str(tmp_path), subscribe=False)
    return server


PROMPT = [3, 1, 4, 1, 5, 9, 2, 6]


class TestStoreWatchAndDiff:
    def test_newest_intact_step_skips_damaged(self, tmp_path,
                                              model_and_versions):
        model, versions = model_and_versions
        store = _write_versions(tmp_path, versions)
        assert store.newest_intact_step() == 3
        # Damage the newest step's manifest: the watch must fall back
        # to the newest INTACT step, never offer a torn upload.
        mpath = os.path.join(store.step_dir(3), "manifest.json")
        with open(mpath, "w") as f:
            f.write("{ torn json")
        assert store.newest_intact_step() == 2
        assert store.newest_intact_step(min_step=2) is None

    def test_diff_pulls_only_changed_shards_byte_counted(
            self, tmp_path, model_and_versions):
        model, versions = model_and_versions
        t1 = _host(versions[1])
        # t2 = t1 with exactly ONE leaf replaced.
        flat, treedef = jax.tree_util.tree_flatten(t1)
        changed_leaf = flat[0]
        flat2 = [np.asarray(a, np.float32) for a in flat]
        flat2[0] = flat2[0] + 1.0
        t2 = jax.tree_util.tree_unflatten(treedef, flat2)
        store = ShardStore(str(tmp_path))
        store.write_step(take_snapshot(t1, step=1), world=1, scheme="dp")
        store.write_step(take_snapshot(t2, step=2), world=1, scheme="dp")
        have = {path: digest for path, (digest, _)
                in leaf_digests(t1).items()}
        manifest = store.validate_step(2)
        by_file, changed, nbytes = diff_manifest(manifest, have)
        assert len(changed) == 1
        assert nbytes == int(changed_leaf.nbytes)
        # The unchanged version diffs as empty: nothing to move.
        m1 = store.validate_step(1)
        by_file1, changed1, nbytes1 = diff_manifest(m1, have)
        assert not by_file1 and not changed1 and nbytes1 == 0
        # An empty cache pulls everything.
        by_all, changed_all, nbytes_all = diff_manifest(manifest, {})
        assert len(changed_all) == len(manifest.entries)
        assert nbytes_all == manifest.nbytes


class TestSubscriberSwap:
    def test_poll_swaps_and_pulls_only_changed_bytes(
            self, tmp_path, model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path),
                                   deadline_s=60)
            assert sub.version == 1
            assert sub.poll_once() == 2
            assert engine.weights_version == 2
            # Independent inits share only the zero-initialized leaves;
            # the pull must have moved strictly fewer bytes than the
            # model (the diff, not a full download).
            manifest = sub.store.validate_step(2)
            assert 0 < sub.last_swap["pulled_bytes"] < manifest.nbytes
            assert sub.last_swap["pulled_leaves"] < \
                sub.last_swap["total_leaves"]
            # Nothing newer: the next poll is a no-op.
            assert sub.poll_once() is None
            # Post-flip generations run on the NEW weights.
            req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=5))
            assert req.done.wait(timeout=30)
            assert req.tokens == _ref_tokens(model, versions[2],
                                             PROMPT, 5)
        finally:
            batcher.stop()

    def test_straddling_request_matches_pre_swap_reference(
            self, tmp_path, model_and_versions):
        """THE token-identity oracle: a generation in flight when the
        swap is requested finishes on the version it started on."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path), deadline_s=60)
            n = 8
            req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=n))
            # Genuinely in flight before the swap is requested (its
            # first token emitted, generation still running).
            deadline = time.monotonic() + 30
            while req.first_token_at is None:
                assert time.monotonic() < deadline
                time.sleep(0.002)
            # Swap while the generation is in flight: the barrier holds
            # admission and flips only once the slots ran dry.
            assert sub.swap_to(2) == 2
            assert req.done.wait(timeout=30)
            assert req.error is None
            assert req.tokens == _ref_tokens(model, versions[1],
                                             PROMPT, n)
            after = batcher.submit(PROMPT,
                                   SamplingParams(max_new_tokens=n))
            assert after.done.wait(timeout=30)
            assert after.tokens == _ref_tokens(model, versions[2],
                                               PROMPT, n)
        finally:
            batcher.stop()

    def test_corrupt_shard_rejected_keeps_old_weights(
            self, tmp_path, model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path), retries=2,
                                   deadline_s=60)
            with faults.inject("swap:p=1,mode=corrupt-shard"):
                with pytest.raises(SwapRejectedError,
                                   match="digest verification"):
                    sub.swap_to(2)
                fired = [h for h in faults.history()
                         if h[0] == "swap"]
                assert len(fired) == 2   # one per retry attempt
            # Old weights still serving, nothing staged left behind.
            assert engine.weights_version == 1
            assert sub.version == 1
            assert engine.staged_version() is None
            req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=5))
            assert req.done.wait(timeout=30)
            assert req.tokens == _ref_tokens(model, versions[1],
                                             PROMPT, 5)
            # poll_once absorbs the rejection (the watch loop survives
            # a bad upload).
            with faults.inject("swap:p=1,mode=corrupt-shard"):
                assert sub.poll_once() is None
        finally:
            batcher.stop()

    def test_corrupt_shard_single_fault_retry_recovers(
            self, tmp_path, model_and_versions):
        """A one-shot corruption is absorbed by the RetryPolicy: the
        second pull attempt verifies clean and the swap completes."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path), retries=3,
                                   deadline_s=60)
            with faults.inject("swap:step=0,mode=corrupt-shard"):
                assert sub.swap_to(2) == 2
                assert len([h for h in faults.history()
                            if h[0] == "swap"]) == 1
            assert engine.weights_version == 2
        finally:
            batcher.stop()

    def test_stall_past_deadline_abandons(self, tmp_path,
                                          model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path),
                                   deadline_s=0.15)
            with faults.inject("swap:p=1,mode=stall,delay_ms=400"):
                with pytest.raises(SwapAbandonedError):
                    sub.swap_to(2)
            assert engine.weights_version == 1
            assert engine.staged_version() is None
        finally:
            batcher.stop()

    def test_rollback_restores_journaled_step_bit_identically(
            self, tmp_path, model_and_versions):
        model, versions = model_and_versions
        # The trainer's side: journaled saves through the checkpointer.
        with AsyncCheckpointer(str(tmp_path), world=1, scheme="dp",
                               async_save=False) as ckpt:
            for step in (1, 2):
                ckpt.save(step, _host(versions[step]))
                ckpt.journal_step(step)
            journaled = [e["step"] for e in ckpt.journal.read()[0]]
        assert journaled == [1, 2]
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path), deadline_s=60)
            assert sub.swap_to(2) == 2
            # Forward swaps refuse an older step; rollback is explicit.
            with pytest.raises(SwapRejectedError, match="older"):
                sub.swap_to(1)
            assert sub.swap_to(1, rollback=True) == 1
            assert sub.last_swap["rollback"] is True
            # Bit-identical restoration of the journaled step.
            want = jax.tree_util.tree_leaves(
                jax.tree_util.tree_map(
                    lambda a: np.asarray(a, np.float32),
                    _host(versions[1])))
            got = [np.asarray(leaf) for leaf in
                   jax.tree_util.tree_leaves(engine.params)]
            assert len(want) == len(got)
            for w, g in zip(want, got):
                assert w.dtype == g.dtype and np.array_equal(w, g)
            req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=5))
            assert req.done.wait(timeout=30)
            assert req.tokens == _ref_tokens(model, versions[1],
                                             PROMPT, 5)
        finally:
            batcher.stop()

    def test_rollback_pins_forward_watch(self, tmp_path,
                                         model_and_versions):
        """A subscribed replica's poller must NOT re-deploy the steps
        just rolled back from; the next explicit forward swap unpins
        the watch (review finding: the poller was silently undoing the
        operator's rollback within one poll period)."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            sub = WeightSubscriber(batcher, str(tmp_path), deadline_s=60)
            assert sub.poll_once() == 2
            assert sub.swap_to(1, rollback=True) == 1
            # Step 2 is still intact in the store, but the watch is
            # pinned — the poller must not re-deploy it.
            assert sub.poll_once() is None
            assert engine.weights_version == 1
            # Even a poll tick that slipped PAST the held-check (queued
            # on the swap lock while the rollback ran) is stopped by
            # the in-lock re-check.
            assert sub.swap_to(2, _from_poll=True) == 1
            assert engine.weights_version == 1
            # An explicit forward swap unpins and applies.
            assert sub.swap_to(2) == 2
            assert sub.poll_once() is None   # nothing newer than 2
        finally:
            batcher.stop()

    def test_noop_swap_reports_zero_pull(self, tmp_path,
                                         model_and_versions):
        """Re-rolling a step the replica already serves answers ok with
        ZERO pulled bytes — not the previous swap's pull accounting."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        server = _replica(model, versions[1], tmp_path, name="rep-0")
        try:
            router = Router([ReplicaSpec("rep-0",
                                         [("127.0.0.1", server.port)])],
                            KEY)
            first = router.swap_replica("rep-0", 2, timeout=60.0)
            assert first.error is None and first.pulled_bytes > 0
            again = router.swap_replica("rep-0", 2, timeout=60.0)
            assert again.error is None and again.weights_version == 2
            assert again.pulled_bytes == 0
        finally:
            server.shutdown()

    def test_prefix_cache_flushed_on_flip(self, tmp_path,
                                          model_and_versions):
        """Stale-KV guard: a prompt resident in the paged prefix cache
        BEFORE the swap must recompute after it — served against the
        new weights, the old blocks would emit silently wrong tokens."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        batcher.start()
        try:
            first = batcher.submit(PROMPT,
                                   SamplingParams(max_new_tokens=4))
            assert first.done.wait(timeout=30)
            # The prompt's blocks are resident now.
            assert engine.prefix_probe(PROMPT) > 0
            sub = WeightSubscriber(batcher, str(tmp_path), deadline_s=60)
            assert sub.swap_to(2) == 2
            assert engine.prefix_probe(PROMPT) == 0, \
                "flip must flush the prefix cache"
            again = batcher.submit(PROMPT,
                                   SamplingParams(max_new_tokens=4))
            assert again.done.wait(timeout=30)
            assert again.tokens == _ref_tokens(model, versions[2],
                                               PROMPT, 4)
        finally:
            batcher.stop()


class TestFlipBarrier:
    def test_flip_waits_for_inflight_and_runs_between_bursts(
            self, model_and_versions):
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=4))
        batcher.step()   # the request occupies a slot BEFORE the flip
        engine.stage_params(_host(versions[2]), 2)
        result = {}

        def flip():
            result["version"] = batcher.flip_at_barrier(
                engine.commit_staged, timeout=30.0)

        t = threading.Thread(target=flip, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while not req.done.is_set():
            assert time.monotonic() < deadline
            batcher.step()
            # While the request is in flight the version cannot move.
            if not req.done.is_set():
                assert engine.weights_version == 1
        while "version" not in result and time.monotonic() < deadline:
            batcher.step()
            time.sleep(0.01)
        t.join(timeout=10)
        assert result["version"] == 2
        assert engine.weights_version == 2
        assert req.tokens == _ref_tokens(model, versions[1], PROMPT, 4)

    def test_flip_holds_admission_until_flipped(self,
                                                model_and_versions):
        """A request QUEUED while the flip is pending waits (despite a
        free slot!) and admits only after the flip — it runs whole on
        the new weights, and was never dropped."""
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        first = batcher.submit(PROMPT, SamplingParams(max_new_tokens=6))
        batcher.step()   # first occupies slot 0; slot 1 stays free
        engine.stage_params(_host(versions[2]), 2)
        t = threading.Thread(
            target=lambda: batcher.flip_at_barrier(engine.commit_staged,
                                                   timeout=30.0),
            daemon=True)
        t.start()
        deadline = time.monotonic() + 10
        while not batcher.snapshot()["swap_pending"]:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        second = batcher.submit([2, 7, 1, 8, 2, 8],
                                SamplingParams(max_new_tokens=4))
        while not first.done.is_set():
            batcher.step()
            if not first.done.is_set():
                # Admission held: a free slot exists, yet the queued
                # request must wait out the swap window.
                snap = batcher.snapshot()
                assert snap["queue_depth"] == 1, snap
        while not second.done.is_set():
            assert time.monotonic() < deadline + 20
            batcher.step()
            time.sleep(0.002)
        t.join(timeout=10)
        assert first.tokens == _ref_tokens(model, versions[1], PROMPT, 6)
        assert second.tokens == _ref_tokens(model, versions[2],
                                            [2, 7, 1, 8, 2, 8], 4)

    def test_kill_mid_flip_fails_over_not_mixed(self,
                                                model_and_versions):
        """The flip is one atomic reference swap: a replica killed at
        the barrier dies on EXACTLY the old version, its in-flight work
        fails back to the router, and the barrier waiter learns."""
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=4))
        engine.stage_params(_host(versions[2]), 2)
        caught = {}

        def flip():
            try:
                batcher.flip_at_barrier(engine.commit_staged,
                                        timeout=30.0)
            except ReplicaKilledError as e:
                caught["err"] = e

        t = threading.Thread(target=flip, daemon=True)
        t.start()
        with faults.inject("swap:step=0,mode=kill-mid-flip"):
            with pytest.raises(ReplicaKilledError):
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    batcher.step()
                    time.sleep(0.005)
        t.join(timeout=10)
        assert "err" in caught
        assert batcher.dead
        # Dead on exactly the OLD version; the request failed over.
        assert engine.weights_version == 1
        assert req.done.is_set() and req.error == "replica_killed"

    def test_withdrawn_flip_never_commits(self, model_and_versions):
        """A barrier wait that times out WITHDRAWS the flip: later
        steps must not execute it (review finding: the step loop could
        still commit a flip its waiter had already reported abandoned
        and discarded)."""
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        req = batcher.submit(PROMPT, SamplingParams(max_new_tokens=6))
        batcher.step()   # the slot stays busy past the tiny timeout
        engine.stage_params(_host(versions[2]), 2)
        with pytest.raises(TimeoutError):
            batcher.flip_at_barrier(engine.commit_staged, timeout=0.05)
        while not req.done.is_set():
            batcher.step()
        for _ in range(3):   # idle steps after the drain
            batcher.step()
        # The withdrawn flip never ran: old version serving, the staged
        # tree untouched (its owner decides whether to discard).
        assert engine.weights_version == 1
        assert engine.staged_version() == 2
        engine.discard_staged()

    def test_die_releases_barrier_waiter(self, model_and_versions):
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        engine.stage_params(_host(versions[2]), 2)
        caught = {}

        def flip():
            try:
                batcher.flip_at_barrier(engine.commit_staged,
                                        timeout=30.0)
            except ReplicaKilledError as e:
                caught["err"] = str(e)

        t = threading.Thread(target=flip, daemon=True)
        t.start()
        time.sleep(0.05)
        batcher._die("test shutdown")
        t.join(timeout=10)
        assert not t.is_alive() and "replica_killed" in caught["err"]


class TestWireAndRouter:
    def test_swap_and_rollback_frames_over_wire(self, tmp_path,
                                                model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        server = _replica(model, versions[1], tmp_path, name="rep-0")
        try:
            router = Router([ReplicaSpec("rep-0",
                                         [("127.0.0.1", server.port)])],
                            KEY,
                            retry_policy=RetryPolicy(attempts=4,
                                                     base_delay_s=0.02,
                                                     max_delay_s=0.2))
            resp = router.swap_replica("rep-0", 2, timeout=60.0)
            assert resp.error is None and resp.weights_version == 2
            assert resp.pulled_bytes > 0 and resp.swap_ms is not None
            # Router-side version tracking + stats column.
            stats = router.replica_stats(timeout=5.0)
            assert stats["rep-0"]["weights_version"] == 2
            assert stats["rep-0"]["stats"]["weights_version"] == 2
            assert stats["rep-0"]["stats"]["swaps_completed"] == 1
            # Generations report the version that produced them.
            out = router.generate(PROMPT, max_new_tokens=4)
            assert out.error is None
            assert out.weights_version == 2
            assert out.tokens == _ref_tokens(model, versions[2],
                                             PROMPT, 4)
            # Rollback frame rides the same path.
            rb = router.rollback_replica("rep-0", 1, timeout=60.0)
            assert rb.error is None and rb.weights_version == 1
            out = router.generate(PROMPT, max_new_tokens=4,
                                  request_id="after-rollback")
            assert out.tokens == _ref_tokens(model, versions[1],
                                             PROMPT, 4)
        finally:
            server.shutdown()

    def test_swap_without_store_answers_terminal_error(
            self, model_and_versions):
        model, versions = model_and_versions
        engine = _engine(model, versions[1])
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        server = InferenceServer(batcher, key=KEY, name="bare",
                                 host="127.0.0.1")
        try:
            router = Router([ReplicaSpec("bare",
                                         [("127.0.0.1", server.port)])],
                            KEY)
            resp = router.swap_replica("bare", 2, timeout=10.0)
            assert resp.error == "no_swap_store"
            assert resp.weights_version == 1   # still the old version
            # Not a health event: the replica keeps serving.
            out = router.generate(PROMPT, max_new_tokens=3)
            assert out.error is None
        finally:
            server.shutdown()

    def test_swap_to_missing_step_rejected_old_weights_serving(
            self, tmp_path, model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1]})
        server = _replica(model, versions[1], tmp_path, name="rep-0")
        try:
            router = Router([ReplicaSpec("rep-0",
                                         [("127.0.0.1", server.port)])],
                            KEY)
            resp = router.swap_replica("rep-0", 7, timeout=30.0)
            assert resp.error is not None and "rejected" in resp.error
            assert resp.weights_version == 1
            out = router.generate(PROMPT, max_new_tokens=3)
            assert out.error is None
        finally:
            server.shutdown()

    def test_directory_hit_requires_version_match(self):
        """Mixed-version routing rule, unit level: a residency entry
        recorded under version 1 must not route once the replica
        reports version 2 — the request falls back to the spread."""
        specs = [ReplicaSpec("a", [("127.0.0.1", 1)]),
                 ReplicaSpec("b", [("127.0.0.1", 2)])]
        router = Router(specs, KEY)
        rep_a = router._find("a")
        key = tuple(range(router._affinity_block))
        router._note_version(rep_a, 1)
        router._note_affinity(key, rep_a, 1)
        with router._lock:
            fully = list(router._replicas)
            assert router._resident_locked(key, fully) is rep_a
        # The replica flips: its entries are invalidated AND any
        # survivor would fail the version tag check.
        router._note_version(rep_a, 2)
        with router._lock:
            assert router._resident_locked(key, fully) is None
        # Re-confirmed under the new version: routable again.
        router._note_affinity(key, rep_a, 2)
        with router._lock:
            assert router._resident_locked(key, fully) is rep_a

    def test_adopt_refuses_mismatched_version_kv(self,
                                                 model_and_versions):
        """A migrated KV payload computed under other weights must be
        refused at adoption (the sender falls back to its own pristine
        KV + matching weights — tokens never wrong)."""
        model, versions = model_and_versions
        engine = _engine(model, versions[1], version=2)
        batcher = ContinuousBatcher(engine, max_queue=8,
                                    default_deadline_s=60)
        manifest = {"request_id": "m-1", "prompt": PROMPT,
                    "tokens": [5], "weights_version": 1,
                    "sampling": {"max_new_tokens": 4, "temperature": 0.0,
                                 "top_k": 0, "stop_token": None,
                                 "spec": False}}
        with pytest.raises(ValueError, match="version_mismatch"):
            batcher.adopt(manifest, np.zeros((2, 2, 4, 2, 16)),
                          np.zeros((2, 2, 4, 2, 16)))


class _NullLauncher(ReplicaLauncher):
    def launch(self, role, host=None):
        raise AssertionError("the swap drill never launches replicas")

    def retire(self, name):
        pass


def _fleet(model, params, tmp_path, n=2):
    servers = [_replica(model, params, tmp_path, name=f"rep-{i}")
               for i in range(n)]
    router = Router(
        [ReplicaSpec(s.name, [("127.0.0.1", s.port)]) for s in servers],
        KEY, retry_policy=RetryPolicy(attempts=10, base_delay_s=0.02,
                                      max_delay_s=0.3))
    controller = FleetController(router, _NullLauncher(), min_per_role=1)
    return servers, router, controller


class TestRollingFleetSwap:
    def test_roll_swap_bounded_and_converges(self, tmp_path,
                                             model_and_versions):
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        servers, router, controller = _fleet(model, versions[1],
                                             tmp_path)
        try:
            outcomes = controller.roll_swap(2, max_concurrent=1,
                                            timeout=60.0)
            assert [o["replica"] for o in outcomes] == ["rep-0", "rep-1"]
            assert all(o["ok"] for o in outcomes)
            assert all(o["weights_version"] == 2 for o in outcomes)
            stats = router.replica_stats(timeout=5.0)
            assert all(e["weights_version"] == 2
                       for e in stats.values())
            out = router.generate(PROMPT, max_new_tokens=4)
            assert out.tokens == _ref_tokens(model, versions[2],
                                             PROMPT, 4)
        finally:
            for s in servers:
                s.shutdown()

    @pytest.mark.chaos
    def test_partial_fleet_mixed_versions_stay_correct(
            self, tmp_path, model_and_versions):
        """The ``partial-fleet`` drill: the roll aborts midway, the
        fleet is deliberately mixed-version, and every response is
        still token-identical to the reference for the version that
        produced it (the version-matched routing rule at work)."""
        model, versions = model_and_versions
        _write_versions(tmp_path, {1: versions[1], 2: versions[2]})
        servers, router, controller = _fleet(model, versions[1],
                                             tmp_path)
        try:
            with faults.inject("swap:step=1,mode=partial-fleet"):
                outcomes = controller.roll_swap(2, max_concurrent=1,
                                                timeout=60.0)
            assert outcomes[0]["ok"] and \
                outcomes[0]["weights_version"] == 2
            assert not outcomes[1]["ok"] and \
                outcomes[1]["error"] == "roll_aborted"
            refs = {v: _ref_tokens(model, versions[v], PROMPT, 4)
                    for v in (1, 2)}
            assert refs[1] != refs[2]   # the oracle can tell versions
            seen = set()
            for i in range(8):
                out = router.generate(PROMPT, max_new_tokens=4,
                                      request_id=f"mixed-{i}")
                assert out.error is None
                assert out.tokens == refs[out.weights_version], \
                    (i, out.weights_version, out.tokens)
                seen.add(out.weights_version)
            # Completing the roll converges the fleet.
            outcomes = controller.roll_swap(2, timeout=60.0)
            assert all(o["ok"] for o in outcomes)
            out = router.generate(PROMPT, max_new_tokens=4,
                                  request_id="converged")
            assert out.tokens == refs[2]
        finally:
            for s in servers:
                s.shutdown()


class TestChaosDrill:
    """THE acceptance drill: a bursty open-loop load hammers the router
    through >=5 rolling hot-swaps with randomized ``swap:*`` faults —
    0 dropped requests, every response token-identical to the
    fixed-weights reference for its version, corrupt-shard swaps
    rejected with the fleet still serving, one journaled rollback
    restoring prior weights bit-identically.

    ``HVD_TPU_CHAOS_STEP``/``HVD_TPU_CHAOS_SEED`` randomize the fault
    schedule (``scripts/chaos_soak.py --mode swap`` loops them)."""

    @pytest.mark.chaos
    def test_hot_swap_chaos_drill(self, tmp_path, model_and_versions):
        import random

        model, versions = model_and_versions
        chaos_step = int(os.environ.get("HVD_TPU_CHAOS_STEP", "2"))
        chaos_seed = int(os.environ.get("HVD_TPU_CHAOS_SEED", "0"))
        rng = random.Random(chaos_seed * 1000003 + chaos_step)
        n_swaps = 5
        n_tok = 4
        # Version plan: 5 forward swaps cycling through 3 genuinely
        # different param sets (written once; later steps re-save an
        # earlier set under a new step number — cheap and still a real
        # manifest diff).
        step_params = {s: versions[1 + (s - 1) % 3]
                       for s in range(1, n_swaps + 2)}
        _write_versions(tmp_path, step_params)
        refs = {s: _ref_tokens(model, p, PROMPT, n_tok)
                for s, p in step_params.items()}
        assert refs[1] != refs[2] != refs[3]

        servers, router, controller = _fleet(model, step_params[1],
                                             tmp_path)
        results, lock, threads = [], threading.Lock(), []
        stop = threading.Event()

        def fire(rid, prompt):
            try:
                resp = router.generate(prompt, max_new_tokens=n_tok,
                                       request_id=rid)
                row = {"id": rid, "error": resp.error,
                       "tokens": resp.tokens,
                       "version": resp.weights_version}
            except Exception as e:
                row = {"id": rid, "error": str(e), "tokens": None,
                       "version": None}
            with lock:
                results.append(row)

        def load_loop():
            j = 0
            while not stop.is_set():
                for _ in range(2):
                    th = threading.Thread(
                        target=fire, args=(f"drill-{j}", PROMPT),
                        daemon=True)
                    th.start()
                    threads.append(th)
                    j += 1
                stop.wait(0.15)

        try:
            # Warm every replica's compiled programs off the record.
            warm = [threading.Thread(target=fire,
                                     args=(f"warm-{i}", PROMPT),
                                     daemon=True) for i in range(4)]
            for t in warm:
                t.start()
            for t in warm:
                t.join(timeout=60)
            with lock:
                results.clear()

            loader = threading.Thread(target=load_loop, daemon=True)
            loader.start()
            corrupt_rejected = 0
            for s in range(2, n_swaps + 2):
                mode = rng.choice([None, None, "corrupt-shard", "stall",
                                   "partial-fleet"])
                spec = {
                    "corrupt-shard": "swap:p=1,mode=corrupt-shard",
                    "stall": "swap:p=1,mode=stall,delay_ms=40",
                    "partial-fleet":
                        f"swap:step={rng.randrange(3)},"
                        f"mode=partial-fleet",
                }.get(mode)
                if spec is None:
                    outcomes = controller.roll_swap(s, timeout=60.0)
                else:
                    with faults.inject(spec):
                        outcomes = controller.roll_swap(s, timeout=60.0)
                if mode == "corrupt-shard":
                    # Every pull damaged: the fleet must REJECT the
                    # version and keep serving the old weights.
                    assert not any(o["ok"] for o in outcomes), outcomes
                    corrupt_rejected += 1
                elif mode is None or mode == "stall":
                    assert all(o["ok"] for o in outcomes), outcomes
                time.sleep(0.2)
            # One journaled rollback through the same path.
            rb = controller.rollback(1, timeout=60.0)
            assert all(o["ok"] for o in rb), rb
            time.sleep(0.3)
            stop.set()
            loader.join(timeout=10)
            for th in threads:
                th.join(timeout=60)
        finally:
            stop.set()
            engines = [s._batcher.engine for s in servers]
            for s in servers:
                s.shutdown()

        with lock:
            rows = list(results)
        assert rows, "the load loop produced no requests"
        dropped = [r for r in rows if r["error"] is not None]
        assert not dropped, f"dropped {len(dropped)}: {dropped[:3]}"
        for r in rows:
            assert r["version"] in refs, r
            assert r["tokens"] == refs[r["version"]], r
        # The rollback restored step 1's weights bit-identically on
        # every replica.
        want = [np.asarray(a, np.float32) for a in
                jax.tree_util.tree_leaves(_host(step_params[1]))]
        for engine in engines:
            got = [np.asarray(leaf) for leaf in
                   jax.tree_util.tree_leaves(engine.params)]
            for w, g in zip(want, got):
                assert np.array_equal(w, g)
        assert all(e.weights_version == 1 for e in engines)
