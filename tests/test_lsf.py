"""LSF / jsrun launch path (reference: horovod/runner/util/lsf.py +
js_run.py, SURVEY.md §2.5; mount empty, unverified).  No LSF cluster
exists here, so these tests exercise the allocation parsing, the jsrun
command contract, and the CLI dispatch with a scheduler-shaped fake
environment."""

import os
import subprocess
import sys

import pytest

from horovod_tpu.runner import lsf


@pytest.fixture
def clean_lsf_env(monkeypatch):
    for var in ("LSB_JOBID", "LSB_DJOB_HOSTFILE", "LSB_MCPU_HOSTS"):
        monkeypatch.delenv(var, raising=False)
    return monkeypatch


class TestDetection:
    def test_not_in_lsf(self, clean_lsf_env):
        assert not lsf.in_lsf()
        with pytest.raises(RuntimeError, match="LSF allocation"):
            lsf.lsf_hosts()

    def test_hostfile_parsing_skips_batch_host(self, clean_lsf_env,
                                               tmp_path):
        hf = tmp_path / "hostfile"
        hf.write_text("batch01\nnode01\nnode01\nnode02\nnode02\n")
        clean_lsf_env.setenv("LSB_JOBID", "1234")
        clean_lsf_env.setenv("LSB_DJOB_HOSTFILE", str(hf))
        assert lsf.in_lsf()
        hosts = lsf.lsf_hosts()
        assert hosts == {"node01": 2, "node02": 2}
        assert list(hosts)[0] == "node01"   # rank-0 host = first compute
        assert lsf.world_size() == 4

    def test_mcpu_hosts_fallback_excludes_batch_host(self, clean_lsf_env):
        clean_lsf_env.setenv("LSB_JOBID", "1")
        clean_lsf_env.setenv("LSB_MCPU_HOSTS", "batch01 1 nodeA 2 nodeB 4")
        assert lsf.lsf_hosts() == {"nodeA": 2, "nodeB": 4}
        assert lsf.world_size() == 6

    def test_mcpu_single_host_kept(self, clean_lsf_env):
        clean_lsf_env.setenv("LSB_JOBID", "1")
        clean_lsf_env.setenv("LSB_MCPU_HOSTS", "nodeA 4")
        assert lsf.lsf_hosts() == {"nodeA": 4}


class TestJsrunCommand:
    def test_command_shape(self):
        cmd = lsf.jsrun_command(["python", "train.py"], 4, "node01:29500")
        assert cmd[0].endswith("jsrun")
        assert cmd[1:3] == ["--np", "4"]
        assert "HVD_TPU_COORDINATOR_ADDR=node01:29500" in cmd
        assert "HVD_TPU_NUM_PROCESSES=4" in cmd
        assert cmd[-2:] == ["python", "train.py"]


class TestRunLsf:
    def test_missing_jsrun_errors_cleanly(self, clean_lsf_env, tmp_path,
                                          monkeypatch):
        hf = tmp_path / "hostfile"
        hf.write_text("batch\nnode01\nnode01\n")
        clean_lsf_env.setenv("LSB_JOBID", "1")
        clean_lsf_env.setenv("LSB_DJOB_HOSTFILE", str(hf))
        monkeypatch.setattr("shutil.which", lambda name: None)
        assert lsf.run_lsf(["python", "x.py"]) == 2

    def test_dispatch_through_jsrun(self, clean_lsf_env, tmp_path,
                                    monkeypatch):
        hf = tmp_path / "hostfile"
        hf.write_text("batch\nnode01\nnode01\nnode02\n")
        clean_lsf_env.setenv("LSB_JOBID", "1")
        clean_lsf_env.setenv("LSB_DJOB_HOSTFILE", str(hf))
        monkeypatch.setattr("shutil.which", lambda name: "/usr/bin/jsrun")
        captured = {}

        def fake_call(cmd, env=None):
            captured["cmd"] = cmd
            captured["env"] = env
            return 0

        monkeypatch.setattr(subprocess, "call", fake_call)
        rc = lsf.run_lsf(["python", "train.py"])
        assert rc == 0
        assert captured["cmd"][:3] == ["/usr/bin/jsrun", "--np", "3"]
        assert "HVD_TPU_COORDINATOR_ADDR=node01:29500" in captured["cmd"]

    def test_cli_routes_to_lsf(self, clean_lsf_env, tmp_path, monkeypatch):
        from horovod_tpu.runner import launch

        hf = tmp_path / "hostfile"
        hf.write_text("batch\nnode01\n")
        clean_lsf_env.setenv("LSB_JOBID", "1")
        clean_lsf_env.setenv("LSB_DJOB_HOSTFILE", str(hf))
        called = {}

        def fake_run_lsf(command, np_=None, verbose=False):
            called["command"] = command
            called["np"] = np_
            return 0

        monkeypatch.setattr(lsf, "run_lsf", fake_run_lsf)
        rc = launch.main(["python", "train.py"])
        assert rc == 0
        assert called["command"] == ["python", "train.py"]
        assert called["np"] is None   # -np unset => whole allocation
        rc = launch.main(["-np", "1", "python", "train.py"])
        assert rc == 0
        assert called["np"] == 1      # explicit -np 1 honored exactly


class TestSchedulerRankEnv:
    def test_pmix_rank_consumed(self, monkeypatch):
        """basics._maybe_init_distributed falls back to the job-step
        manager's rank env when HVD_TPU_PROCESS_ID is absent (source
        contract check — a real jsrun world needs a cluster)."""
        import inspect

        from horovod_tpu import basics

        src = inspect.getsource(basics._maybe_init_distributed)
        for var in ("PMIX_RANK", "OMPI_COMM_WORLD_RANK", "SLURM_PROCID"):
            assert var in src, var
