"""Native XLA typed-FFI custom-call path (native/src/ffi_ops.cc).

Reference analogue being validated: the XLA custom-call adapter
(``horovod/tensorflow/xla_mpi_ops.cc``, SURVEY.md §2.3 — mount empty,
unverified) and the fusion buffer's batched scatter/gather memcpys
(``fusion_buffer_manager.cc``, §2.1).  Here: pack/unpack handlers spliced
into jitted CPU programs, plus the Adasum pairwise combine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.native import ffi


pytestmark = pytest.mark.skipif(not ffi.available(),
                                reason="native FFI library unavailable")


class TestBucketPackUnpack:
    def test_roundtrip_eager(self):
        rng = np.random.RandomState(0)
        a = jnp.asarray(rng.randn(3, 5), jnp.float32)
        b = jnp.asarray(rng.randn(3, 2), jnp.float32)
        c = jnp.asarray(rng.randn(3, 7), jnp.float32)
        flat = ffi.bucket_pack([a, b, c])
        assert flat.shape == (3, 14)
        np.testing.assert_array_equal(
            np.asarray(flat), np.concatenate([a, b, c], axis=1))
        outs = ffi.bucket_unpack(flat, [5, 2, 7])
        for got, want in zip(outs, (a, b, c)):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_roundtrip_under_jit(self):
        a = jnp.arange(12, dtype=jnp.float32).reshape(2, 6)
        b = jnp.full((2, 3), 7.0, jnp.float32)

        @jax.jit
        def f(x, y):
            return ffi.bucket_unpack(ffi.bucket_pack([x, y]), [6, 3])

        outs = f(a, b)
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(a))
        np.testing.assert_array_equal(np.asarray(outs[1]), np.asarray(b))

    @pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float16, jnp.int32,
                                       jnp.int8])
    def test_dtype_agnostic(self, dtype):
        rng = np.random.RandomState(1)
        a = jnp.asarray(rng.randn(2, 4) * 10, dtype)
        b = jnp.asarray(rng.randn(2, 2) * 10, dtype)
        outs = ffi.bucket_unpack(ffi.bucket_pack([a, b]), [4, 2])
        np.testing.assert_array_equal(np.asarray(outs[0]), np.asarray(a))
        np.testing.assert_array_equal(np.asarray(outs[1]), np.asarray(b))

    def test_single_row(self):
        a = jnp.asarray([[1.0, 2.0]], jnp.float32)
        b = jnp.asarray([[3.0]], jnp.float32)
        flat = ffi.bucket_pack([a, b])
        np.testing.assert_array_equal(np.asarray(flat), [[1.0, 2.0, 3.0]])


class TestAdasumCombine:
    def _want(self, a, b):
        from horovod_tpu.ops.adasum import _combine

        return np.asarray(_combine(jnp.asarray(a), jnp.asarray(b)))

    def test_matches_hlo_combine(self):
        rng = np.random.RandomState(2)
        a = jnp.asarray(rng.randn(4096), jnp.float32)
        b = jnp.asarray(rng.randn(4096), jnp.float32)
        got = np.asarray(ffi.adasum_combine(a, b))
        np.testing.assert_allclose(got, self._want(a, b),
                                   rtol=1e-5, atol=1e-5)

    def test_identical_inputs_idempotent(self):
        a = jnp.asarray(np.random.RandomState(3).randn(100), jnp.float32)
        np.testing.assert_allclose(np.asarray(ffi.adasum_combine(a, a)),
                                   np.asarray(a), rtol=1e-6)

    def test_orthogonal_adds(self):
        a = jnp.asarray([1.0, 0.0, 0.0, 0.0], jnp.float32)
        b = jnp.asarray([0.0, 2.0, 0.0, 0.0], jnp.float32)
        np.testing.assert_allclose(np.asarray(ffi.adasum_combine(a, b)),
                                   [1.0, 2.0, 0.0, 0.0], rtol=1e-6)

    def test_f64(self):
        # The HLO _combine computes in f32 regardless of input dtype, so
        # the f64 reference is plain numpy in double precision.
        rng = np.random.RandomState(4)
        a = rng.randn(512)
        b = rng.randn(512)
        dot, asq, bsq = a @ b, a @ a, b @ b
        want = (1.0 - dot / (2 * asq)) * a + (1.0 - dot / (2 * bsq)) * b
        from horovod_tpu._compat import enable_x64

        with enable_x64(True):
            got = np.asarray(ffi.adasum_combine(jnp.asarray(a, jnp.float64),
                                                jnp.asarray(b, jnp.float64)))
        np.testing.assert_allclose(got, want, rtol=1e-12)


class TestFusedApplyFfiPath:
    """fused_apply routes its pack/split legs through the FFI handlers
    inside manual SPMD regions on the CPU backend; results must match the
    HLO path bit-for-bit, and the auto-partitioner tier must NOT take the
    FFI route (an opaque custom call would force operand all-gathers)."""

    def _shard_map_apply(self, leaves):
        import horovod_tpu as hvd
        from horovod_tpu._compat import shard_map
        from horovod_tpu.ops import fusion
        from jax.sharding import PartitionSpec as P

        gm = hvd.global_mesh()

        def body(ls):
            return fusion.fused_apply(
                ls, lambda x: jax.lax.psum(x, gm.axis_name), 1 << 20)

        fn = shard_map(body, mesh=gm.mesh, in_specs=P(gm.axis_name),
                       out_specs=P(gm.axis_name), check=False)
        return jax.jit(fn)(leaves)

    def test_matches_hlo_path_in_manual_mode(self, monkeypatch):
        rng = np.random.RandomState(5)
        leaves = [jnp.asarray(rng.randn(8, 3), jnp.float32),
                  jnp.asarray(rng.randn(8, 5, 2), jnp.float32),
                  jnp.asarray(rng.randn(8, 1), jnp.float32)]
        with_ffi = self._shard_map_apply(leaves)
        monkeypatch.setenv("HVD_TPU_USE_NATIVE_FFI", "0")
        without = self._shard_map_apply(leaves)
        for a, b in zip(with_ffi, without):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_auto_partitioned_tier_avoids_ffi(self):
        """Slot-sharded grouped_allreduce under the auto partitioner must
        lower without the custom call (and without all-gathers of the
        operands)."""
        import horovod_tpu  # noqa: F401  (ensures core init'able)
        from horovod_tpu.ops import collectives as C
        from horovod_tpu.ops.collectives import _grouped_allreduce_fn, _lift
        from horovod_tpu.ops.compression import Compression

        rng = np.random.RandomState(7)
        vals = [rng.randn(8, 4).astype(np.float32),
                rng.randn(8, 2, 3).astype(np.float32)]
        lifted = tuple(_lift(v, "probe") for v in vals)
        fn = _grouped_allreduce_fn(C.Sum, None, 1.0, 1.0,
                                   Compression.none, 1 << 26, 2)
        txt = fn.lower(lifted).compile().as_text()
        assert "hvd_bucket_pack" not in txt
        assert "all-gather" not in txt.lower()

    def test_inside_spmd_allreduce(self):
        """The gradient hot path: fused allreduce under shard_map with the
        FFI pack/unpack inside the compiled program."""
        import horovod_tpu as hvd
        from horovod_tpu._compat import shard_map
        from horovod_tpu.ops.fusion import fused_allreduce_pytree
        from jax.sharding import PartitionSpec as P

        gm = hvd.global_mesh()
        n = hvd.size()
        rng = np.random.RandomState(6)
        tree = {"w": jnp.asarray(rng.randn(n, 4, 3), jnp.float32),
                "b": jnp.asarray(rng.randn(n, 7), jnp.float32)}

        def body(t):
            return fused_allreduce_pytree(t, axis=gm.axis_name, op="sum")

        fn = shard_map(body, mesh=gm.mesh,
                       in_specs=P(gm.axis_name), out_specs=P(gm.axis_name),
                       check=False)
        out = jax.jit(fn)(tree)
        for k in tree:
            want = np.broadcast_to(
                np.asarray(tree[k]).sum(0, keepdims=True),
                np.asarray(tree[k]).shape)
            np.testing.assert_allclose(np.asarray(out[k]), want, rtol=1e-5)
