"""ZeRO-1 sharded optimizer (beyond reference — SURVEY.md §2.9 lists
FSDP/ZeRO as absent in Horovod; built here on the reduce-scatter /
all-gather building blocks)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.optim.zero import make_zero_train_step


def _toy_problem(seed=0, d_in=6, d_out=4):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(d_in, d_out), jnp.float32),
        "b": jnp.zeros((d_out,), jnp.float32),
        "scale": jnp.ones((), jnp.float32),   # scalar leaf < mesh size
    }
    w_true = jnp.asarray(rng.randn(d_in, d_out), jnp.float32)

    def loss_fn(p, batch):
        x, y = batch
        pred = (x @ p["w"] + p["b"]) * p["scale"]
        return jnp.mean((pred - y) ** 2)

    def make_batch(n=64, seed=1):
        r = np.random.RandomState(seed)
        x = jnp.asarray(r.randn(n, d_in), jnp.float32)
        y = x @ w_true
        return x, y

    return params, loss_fn, make_batch


class TestZeroTrainStep:
    @pytest.mark.parametrize("tx_name", [
        "sgd", pytest.param("adamw", marks=pytest.mark.slow)])
    def test_matches_plain_dp(self, world_size, tx_name):
        """ZeRO-1 must be numerically equivalent to replicated DP (the
        sharding is an implementation detail of where state lives)."""
        tx = (optax.sgd(0.1, momentum=0.9) if tx_name == "sgd"
              else optax.adamw(1e-2))
        params, loss_fn, make_batch = _toy_problem()
        batch = make_batch(8 * world_size)

        init_z, step_z = make_zero_train_step(loss_fn, tx)
        ref_step = hvd.make_train_step(loss_fn, tx, distributed=True)

        # step functions donate their inputs: each loop needs its own
        # buffers
        zp = jax.tree.map(jnp.copy, params)
        rp = jax.tree.map(jnp.copy, params)
        zs = init_z(params)
        rs = tx.init(rp)
        for _ in range(4):
            zp, zs, zloss = step_z(zp, zs, batch)
            rp, rs, rloss = ref_step(rp, rs, batch)
        np.testing.assert_allclose(float(zloss), float(rloss), rtol=1e-5)
        for k in params:
            np.testing.assert_allclose(np.asarray(zp[k]), np.asarray(rp[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_state_is_sharded(self, world_size):
        """The ZeRO-1 win: per-slot optimizer-state leaves hold 1/n of
        each parameter's (padded) elements."""
        params, loss_fn, _ = _toy_problem()
        init_z, _ = make_zero_train_step(loss_fn, optax.adam(1e-3))
        zs = init_z(params)
        mu = zs[0].mu   # ScaleByAdamState.mu, stacked [n, shard]
        for k, p in params.items():
            leaf = np.asarray(mu[k])
            assert leaf.shape[0] == world_size
            padded = -(-p.size // world_size)
            assert leaf.shape[1] == padded, (k, leaf.shape, p.size)

    def test_loss_decreases(self, world_size):
        params, loss_fn, make_batch = _toy_problem()
        init_z, step_z = make_zero_train_step(loss_fn, optax.adam(5e-2))
        state = init_z(params)
        batch = make_batch()
        losses = []
        for _ in range(30):
            params, state, loss = step_z(params, state, batch)
            losses.append(float(loss))
        # Plain DP yields the same curve (equality proven above);
        # the toy problem's multiplicative scale makes adam slow.
        assert losses[-1] < losses[0] * 0.3, losses

    def test_sum_op_and_aux(self, world_size):
        params, loss_fn, make_batch = _toy_problem()

        def loss_aux(p, batch):
            loss = loss_fn(p, batch)
            return loss, {"loss_copy": loss}

        init_z, step_z = make_zero_train_step(
            loss_aux, optax.sgd(0.01), op=hvd.Sum, has_aux=True)
        state = init_z(params)
        params, state, loss, aux = step_z(params, state, make_batch())
        assert aux["loss_copy"].shape[0] == world_size

    def test_bad_op_rejected(self, world_size):
        params, loss_fn, _ = _toy_problem()
        with pytest.raises(ValueError, match="Average/Sum"):
            make_zero_train_step(loss_fn, optax.sgd(0.1), op=hvd.Adasum)

    def test_zero_size_and_mixed_dtype_leaves(self, world_size):
        """Zero-size leaves pass through untouched; mixed-precision trees
        bucket per dtype (no promotion on the wire)."""
        rng = np.random.RandomState(3)
        params = {
            "w16": jnp.asarray(rng.randn(8, 4), jnp.bfloat16),
            "w32": jnp.asarray(rng.randn(8, 4), jnp.float32),
            "empty": jnp.zeros((0,), jnp.float32),
        }
        wt = jnp.asarray(rng.randn(8, 4), jnp.float32)

        def loss_fn(p, batch):
            x, y = batch
            pred = x @ (p["w16"].astype(jnp.float32) + p["w32"])
            return jnp.mean((pred - y) ** 2) + jnp.sum(p["empty"])

        x = jnp.asarray(rng.randn(16, 8), jnp.float32)
        batch = (x, x @ wt)
        init_z, step_z = make_zero_train_step(loss_fn, optax.sgd(0.05))
        state = init_z(params)
        p1, state, l1 = step_z(params, state, batch)
        assert p1["empty"].shape == (0,)
        assert p1["w16"].dtype == jnp.bfloat16
        assert p1["w32"].dtype == jnp.float32
        p2, state, l2 = step_z(p1, state, batch)
        assert float(l2) < float(l1)


class TestZeroCompression:
    def _toy(self, seed=0):
        rng = np.random.RandomState(seed)
        d = 16
        X = jnp.asarray(rng.randn(32, d), jnp.float32)
        y = jnp.asarray(rng.randn(32), jnp.float32)
        params = {"w": jnp.asarray(rng.randn(d, d) * 0.1, jnp.float32),
                  "v": jnp.asarray(rng.randn(d) * 0.1, jnp.float32)}

        def loss_fn(p, b):
            return jnp.mean((jnp.tanh(b[0] @ p["w"]) @ p["v"] - b[1]) ** 2)

        return params, loss_fn, (X, y)

    @pytest.mark.parametrize("comp", [
        pytest.param("bf16", marks=pytest.mark.slow),
        pytest.param("fp16", marks=pytest.mark.slow), "int8"])
    def test_compressed_wire_tracks_uncompressed(self, world_size, comp):
        params, loss_fn, batch = self._toy()
        tx = optax.adamw(1e-2)
        runs = {}
        for name, compression in [("none", None),
                                  (comp, getattr(hvd.Compression, comp))]:
            init, step = make_zero_train_step(loss_fn, tx,
                                              compression=compression,
                                              donate=False)
            p, st = dict(params), init(params)
            for _ in range(15):
                p, st, loss = step(p, st, batch)
            runs[name] = (p, float(loss))
        # Both converge, and the compressed run tracks the exact one.
        assert runs[comp][1] < 1.0
        for a, b in zip(jax.tree.leaves(runs["none"][0]),
                        jax.tree.leaves(runs[comp][0])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=0.15)

    def test_int8_wire_actually_engaged(self, world_size):
        # The lowered program must carry int8 (xi8 tensors) collective
        # operands — proof the quantized transport, not the f32 HLO
        # path, is what runs.
        params, loss_fn, batch = self._toy()
        init, step = make_zero_train_step(loss_fn, optax.sgd(1e-2),
                                          compression=hvd.Compression.int8,
                                          donate=False)
        st = init(params)
        txt = step.lower(params, st, batch).as_text()
        assert "xi8" in txt, "no int8 operands in the lowered program"
        assert "all_to_all" in txt

    def test_small_updates_survive_int8_wire(self, world_size):
        # Review-r3 regression: with the param all-gather quantized,
        # updates smaller than the wire resolution of the WEIGHT were
        # rounded away every step and params froze.  With the gather
        # exact (only the gradient wire compressed), tiny-lr training
        # must still accumulate movement.
        params, loss_fn, batch = self._toy(seed=3)
        init, step = make_zero_train_step(loss_fn, optax.sgd(1e-5),
                                          compression=hvd.Compression.int8,
                                          donate=False)
        p, st = dict(params), init(params)
        w0 = np.asarray(params["w"]).copy()
        for _ in range(10):
            p, st, _ = step(p, st, batch)
        drift = np.abs(np.asarray(p["w"]) - w0).max()
        # weight scale ~0.3 -> int8 grid ~2.4e-3; per-step updates are
        # ~1e-5: movement must be far below one grid step yet nonzero.
        assert 0 < drift < 1e-3, drift
