"""Process-model tests (reference pattern: test/parallel/test_torch.py's
rank/size assertions + test/single/test_run.py unit style, SURVEY.md §4)."""

import pytest

import horovod_tpu as hvd


def test_initialized():
    assert hvd.is_initialized()


def test_size_is_device_count(world_size):
    import jax

    assert world_size == len(jax.devices()) == 8


def test_rank_in_range(world_size):
    assert 0 <= hvd.rank() < world_size


def test_local_size_single_process(world_size):
    # Single controller process owns all slots.
    assert hvd.local_size() == world_size
    assert hvd.local_rank() == 0


def test_cross_rank_single_process():
    assert hvd.cross_size() == 1
    assert hvd.cross_rank() == 0


def test_is_homogeneous():
    assert hvd.is_homogeneous()


def test_feature_matrix():
    # The reference's hvd.mpi_built()/nccl_built() introspection surface.
    assert not hvd.mpi_built()
    assert not hvd.gloo_built()
    assert hvd.nccl_built() == 0
    assert not hvd.cuda_built()
    assert not hvd.ddl_built()
    assert hvd.xla_built()
    # Honest matrix: enabled implies built everywhere.
    assert not hvd.mpi_enabled() and not hvd.gloo_enabled()
    # The reference's 'some controller is enabled' invariant lands on XLA.
    assert hvd.xla_enabled() and hvd.xla_built()


def test_double_init_is_idempotent():
    hvd.init()
    hvd.init()
    assert hvd.is_initialized()


def test_config_defaults():
    cfg = hvd.config()
    assert cfg.fusion_threshold == 64 * 1024 * 1024
    assert cfg.mesh_axis_name == "hvd"


def test_uninitialized_raises(monkeypatch):
    from horovod_tpu import basics

    monkeypatch.setattr(basics._state, "initialized", False)
    with pytest.raises(hvd.NotInitializedError):
        hvd.size()
