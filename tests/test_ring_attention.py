"""Sequence-parallel attention correctness: ring and Ulysses must equal
full attention exactly (both are exact algorithms, not approximations)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.parallel import (
    full_attention, ring_self_attention, make_mesh,
)
from horovod_tpu.parallel.ulysses import ulysses_attention


def _qkv(b=2, t=16, h=4, d=8, seed=0):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, t, h, d).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh({"sp": 8})


@pytest.fixture(scope="module")
def dp_sp_mesh():
    return make_mesh({"dp": 2, "sp": 4})


@pytest.fixture(scope="module")
def dp_sp_tp_mesh():
    return make_mesh({"dp": 2, "sp": 2, "tp": 2})


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full(self, sp_mesh, causal):
        q, k, v = _qkv()
        ref = full_attention(q, k, v, causal=causal)
        out = ring_self_attention(q, k, v, mesh=sp_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_engine_matches_full(self, sp_mesh, causal):
        # Each ring block on the Pallas kernel (interpret mode on CPU),
        # merged by logsumexp — must equal full attention.
        q, k, v = _qkv()
        ref = full_attention(q, k, v, causal=causal)
        out = ring_self_attention(q, k, v, mesh=sp_mesh, causal=causal,
                                  engine="flash")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_flash_engine_grads(self, sp_mesh):
        q, k, v = _qkv(t=16, d=8)

        def loss(engine):
            def f(q, k, v):
                o = ring_self_attention(q, k, v, mesh=sp_mesh, causal=True,
                                        engine=engine)
                return jnp.sum(o * o)
            return f

        gf = jax.grad(loss("flash"), argnums=(0, 1, 2))(q, k, v)
        gx = jax.grad(loss("xla"), argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gf, gx, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4,
                                       err_msg=f"d{name}")

    @pytest.mark.parametrize("causal", [False, True])
    def test_dp_sp_mesh(self, dp_sp_mesh, causal):
        q, k, v = _qkv(b=4, t=8)
        ref = full_attention(q, k, v, causal=causal)
        out = ring_self_attention(q, k, v, mesh=dp_sp_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_dp_sp_tp_mesh(self, dp_sp_tp_mesh):
        q, k, v = _qkv(b=2, t=8, h=4)
        ref = full_attention(q, k, v, causal=True)
        out = ring_self_attention(q, k, v, mesh=dp_sp_tp_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_under_jit(self, sp_mesh):
        q, k, v = _qkv()
        f = jax.jit(lambda q, k, v: ring_self_attention(
            q, k, v, mesh=sp_mesh, causal=True))
        np.testing.assert_allclose(
            np.asarray(f(q, k, v)),
            np.asarray(full_attention(q, k, v, causal=True)),
            rtol=1e-4, atol=1e-5)

    @pytest.mark.slow
    def test_grad_flows(self, sp_mesh):
        q, k, v = _qkv(t=8)

        def loss(q, k, v):
            return jnp.sum(ring_self_attention(q, k, v, mesh=sp_mesh,
                                               causal=True) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(full_attention(q, k, v, causal=True) ** 2)

        g = jax.grad(loss)(q, k, v)
        g_ref = jax.grad(loss_ref)(q, k, v)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   rtol=1e-3, atol=1e-4)

    def test_missing_axis_raises(self, sp_mesh):
        q, k, v = _qkv()
        with pytest.raises(ValueError, match="no axis"):
            ring_self_attention(q, k, v, mesh=sp_mesh, sp_axis="nope")


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [
        False, pytest.param(True, marks=pytest.mark.slow)])
    def test_matches_full(self, dp_sp_mesh, causal):
        q, k, v = _qkv(b=4, t=8, h=4)   # h=4 divisible by sp=4
        ref = full_attention(q, k, v, causal=causal)
        out = ulysses_attention(q, k, v, mesh=dp_sp_mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_heads_not_divisible_raises(self, sp_mesh):
        q, k, v = _qkv(h=4)  # sp=8 > h=4
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh=sp_mesh)
