"""Torch-binding tests.

Reference pattern: ``test/parallel/test_torch.py`` run under
``horovodrun -np 2`` (SURVEY.md §4) — same test body at any world size
with rank-aware asserts.  Here: single-controller semantics checked
in-process (world size 1 from the torch worker's view, real collectives
underneath on the 8-device CPU mesh), and the true multi-worker numerics
in a 2-process integration test over jax.distributed on loopback.
"""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest
import torch

import horovod_tpu.torch as hvd
from horovod_tpu.runner import run


class TestSingleWorkerOps:
    """With one controller process, torch-world size is 1: reductions are
    identities but still traverse the full slot-stack collective path."""

    def test_world(self):
        assert hvd.size() == 1
        assert hvd.rank() == 0

    @pytest.mark.parametrize("op", [hvd.Average, hvd.Sum, hvd.Min, hvd.Max,
                                    hvd.Product, hvd.Adasum])
    def test_allreduce_identity(self, op):
        t = torch.arange(6, dtype=torch.float32).reshape(2, 3) + 1
        out = hvd.allreduce(t, op=op)
        assert torch.allclose(out, t), (op, out)
        assert out.dtype == t.dtype

    @pytest.mark.parametrize("dtype", [torch.float32, torch.float64,
                                       torch.float16, torch.bfloat16,
                                       torch.int32, torch.int64])
    def test_allreduce_dtypes(self, dtype):
        t = (torch.arange(4) + 1).to(dtype)
        out = hvd.allreduce(t, op=hvd.Sum)
        assert out.dtype == dtype
        assert torch.equal(out.float(), t.float())

    def test_allreduce_scalar(self):
        # 0-dim tensors must survive the host bridge (regression: numpy
        # scalar decay broke torch.from_numpy).
        out = hvd.allreduce(torch.tensor(3.0), op=hvd.Average)
        assert out.item() == pytest.approx(3.0)
        assert out.dim() == 0

    def test_allreduce_inplace(self):
        t = torch.ones(3)
        out = hvd.allreduce_(t, op=hvd.Sum)
        assert out is t

    def test_allreduce_async_poll(self):
        t = torch.ones(4)
        h = hvd.allreduce_async(t)
        out = hvd.synchronize(h)
        assert hvd.poll(h)
        assert torch.allclose(out, t)

    def test_allreduce_scales(self):
        t = torch.full((3,), 2.0)
        out = hvd.allreduce(t, op=hvd.Sum, prescale_factor=0.5,
                            postscale_factor=10.0)
        assert torch.allclose(out, torch.full((3,), 10.0))

    def test_allreduce_fp16_compression(self):
        t = torch.full((5,), 3.0)
        out = hvd.allreduce(t, op=hvd.Sum, compression=hvd.Compression.fp16)
        assert out.dtype == torch.float32
        assert torch.allclose(out, t)

    def test_grouped_allreduce(self):
        ts = [torch.ones(3), torch.full((2, 2), 2.0)]
        outs = hvd.grouped_allreduce(ts, op=hvd.Sum)
        assert len(outs) == 2
        assert torch.allclose(outs[0], ts[0])
        assert torch.allclose(outs[1], ts[1])

    def test_allgather(self):
        t = torch.arange(6, dtype=torch.float32).reshape(3, 2)
        out = hvd.allgather(t)
        assert torch.equal(out, t)

    def test_broadcast(self):
        t = torch.arange(4, dtype=torch.float32)
        out = hvd.broadcast(t, root_rank=0)
        assert torch.equal(out, t)
        t2 = torch.zeros(4)
        hvd.broadcast_(t2, root_rank=0)
        assert torch.equal(t2, torch.zeros(4))

    def test_alltoall(self):
        t = torch.arange(4, dtype=torch.float32)
        out = hvd.alltoall(t)
        assert torch.equal(out, t)

    def test_alltoall_splits(self):
        t = torch.arange(3, dtype=torch.float32)
        out, rsplits = hvd.alltoall(t, torch.tensor([3]))
        assert torch.equal(out, t)
        assert rsplits.tolist() == [3]

    def test_reducescatter(self):
        t = torch.arange(4, dtype=torch.float32)
        out = hvd.reducescatter(t)
        assert torch.equal(out, t)

    def test_grouped_reducescatter(self):
        ts = [torch.arange(4, dtype=torch.float32),
              torch.ones(2, 3)]
        outs = hvd.grouped_reducescatter(ts)
        assert torch.equal(outs[0], ts[0])
        assert torch.equal(outs[1], ts[1])

    def test_barrier_and_join(self):
        hvd.barrier()
        assert hvd.join() >= 0

    def test_broadcast_object(self):
        assert hvd.broadcast_object({"a": 1}) == {"a": 1}
        assert hvd.allgather_object(7) == [7]


class TestBroadcastState:
    def test_broadcast_parameters_state_dict(self):
        model = torch.nn.Linear(4, 2)
        before = {k: v.clone() for k, v in model.state_dict().items()}
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        for k, v in model.state_dict().items():
            assert torch.allclose(v, before[k])

    def test_broadcast_optimizer_state(self):
        model = torch.nn.Linear(4, 2)
        opt = torch.optim.Adam(model.parameters(), lr=0.01)
        hvd.broadcast_optimizer_state(opt, root_rank=0)
        # Lazy Adam state must have been materialized for the broadcast.
        assert len(opt.state_dict()["state"]) > 0

    def test_rejects_positional_params(self):
        model = torch.nn.Linear(2, 2)
        with pytest.raises(ValueError):
            hvd.broadcast_parameters(list(model.parameters()))


class TestDistributedOptimizer:
    def _models(self):
        torch.manual_seed(0)
        model = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                                    torch.nn.Linear(8, 2))
        ref = torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Tanh(),
                                  torch.nn.Linear(8, 2))
        ref.load_state_dict(model.state_dict())
        return model, ref

    def test_matches_plain_sgd(self):
        model, ref = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model.named_parameters())
        ropt = torch.optim.SGD(ref.parameters(), lr=0.1, momentum=0.9)
        assert isinstance(opt, torch.optim.SGD)
        x = torch.randn(8, 4)
        for _ in range(3):
            opt.zero_grad()
            model(x).pow(2).sum().backward()
            opt.step()
            ropt.zero_grad()
            ref(x).pow(2).sum().backward()
            ropt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6)

    def test_num_groups_matches_per_param_path(self):
        """Reference arg num_groups: dense grads ride num_groups fused
        grouped ops instead of one per parameter — numerics identical."""
        model, ref = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9),
            named_parameters=model.named_parameters(), num_groups=2)
        ropt = hvd.DistributedOptimizer(
            torch.optim.SGD(ref.parameters(), lr=0.1, momentum=0.9),
            named_parameters=ref.named_parameters())
        x = torch.randn(8, 4)
        for _ in range(2):
            opt.zero_grad()
            model(x).pow(2).sum().backward()
            opt.step()
            ropt.zero_grad()
            ref(x).pow(2).sum().backward()
            ropt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6)

    def test_num_groups_dispatches_group_when_full(self):
        """Overlap path: a group's fused op is issued as soon as every
        member's hook fired — before synchronize()/step()."""
        model, _ = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), num_groups=1)
        model(torch.randn(2, 4)).sum().backward()
        # All params got grads, so the single group must already be
        # in-flight ("group" handles), not parked as pending.
        kinds = {h[0] for h in opt._handles.values()
                 if isinstance(h, tuple)}
        assert kinds == {"group"}, kinds
        opt.step()

    def test_num_groups_with_sparse_as_dense(self):
        """Densified sparse grads join their fused group (parity with
        the TF binding's sparse_as_dense + num_groups behavior)."""
        torch.manual_seed(0)
        emb = torch.nn.EmbeddingBag(10, 4, sparse=True, mode="sum")
        ref = torch.nn.EmbeddingBag(10, 4, sparse=True, mode="sum")
        ref.load_state_dict(emb.state_dict())
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=0.1),
            named_parameters=emb.named_parameters(),
            sparse_as_dense=True, num_groups=1)
        ropt = torch.optim.SGD(ref.parameters(), lr=0.1)
        idx = torch.tensor([1, 2, 4, 1])
        off = torch.tensor([0, 2])
        opt.zero_grad()
        emb(idx, off).sum().backward()
        opt.step()
        ref(idx, off).sum().backward()
        ref.weight.grad = ref.weight.grad.to_dense()
        ropt.step()
        assert torch.allclose(emb.weight, ref.weight, atol=1e-6)

    def test_grouped_double_backward_without_step_raises(self):
        """A parameter enqueued twice in the grouped path before step()
        would double-count inside the fused wire (silent corruption);
        mirror the reference's "gradient computed twice" assertion."""
        model, _ = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), num_groups=1)
        x = torch.randn(2, 4)
        model(x).sum().backward()
        with pytest.raises((AssertionError, RuntimeError),
                           match="computed twice"):
            model(x).sum().backward()

    def test_num_groups_caps_and_validates(self):
        model, _ = self._models()
        # More groups than params: capped, still correct.
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(), num_groups=99)
        model(torch.randn(2, 4)).sum().backward()
        opt.step()
        with pytest.raises(ValueError, match="num_groups"):
            hvd.DistributedOptimizer(
                torch.optim.SGD(model.parameters(), lr=0.1), num_groups=-1)

    def test_backward_passes_per_step(self):
        model, ref = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            backward_passes_per_step=2)
        ropt = torch.optim.SGD(ref.parameters(), lr=0.1)
        xs = [torch.randn(4, 4) for _ in range(2)]
        opt.zero_grad()
        for x in xs:
            model(x).sum().backward()
        opt.step()
        # Reference semantics: the accumulated gradient is averaged over
        # the local passes before the cross-worker average.
        ropt.zero_grad()
        for x in xs:
            (ref(x).sum() / 2).backward()
        ropt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6)

    def test_zero_grad_race_guard(self):
        model, _ = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        model(torch.randn(2, 4)).sum().backward()
        with pytest.raises(AssertionError):
            opt.zero_grad()
        opt.synchronize()
        with opt.skip_synchronize():
            opt.step()

    def test_synchronize_then_skip(self):
        model, ref = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters())
        ropt = torch.optim.SGD(ref.parameters(), lr=0.1)
        x = torch.randn(4, 4)
        opt.zero_grad()
        model(x).sum().backward()
        opt.synchronize()
        torch.nn.utils.clip_grad_norm_(model.parameters(), 1e9)
        with opt.skip_synchronize():
            opt.step()
        ropt.zero_grad()
        ref(x).sum().backward()
        ropt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6)

    def test_predivide_factor(self):
        model, ref = self._models()
        opt = hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=model.named_parameters(),
            gradient_predivide_factor=4.0)
        ropt = torch.optim.SGD(ref.parameters(), lr=0.1)
        x = torch.randn(4, 4)
        opt.zero_grad()
        model(x).sum().backward()
        opt.step()
        ropt.zero_grad()
        ref(x).sum().backward()
        ropt.step()
        for p, q in zip(model.parameters(), ref.parameters()):
            assert torch.allclose(p, q, atol=1e-6)


class TestSyncBatchNorm:
    @pytest.mark.parametrize("dims", [2, 4])
    def test_matches_batchnorm_single_worker(self, dims):
        torch.manual_seed(0)
        shape = (6, 3) if dims == 2 else (6, 3, 4, 4)
        x = torch.randn(*shape, dtype=torch.float64, requires_grad=True)
        xr = x.detach().clone().requires_grad_(True)
        sbn = hvd.SyncBatchNorm(3).double()
        bn = (torch.nn.BatchNorm1d(3) if dims == 2
              else torch.nn.BatchNorm2d(3)).double()
        bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})

        y = sbn(x)
        yr = bn(xr)
        assert torch.allclose(y, yr, atol=1e-10)
        y.pow(2).sum().backward()
        yr.pow(2).sum().backward()
        assert torch.allclose(x.grad, xr.grad, atol=1e-8)
        assert torch.allclose(sbn.weight.grad, bn.weight.grad, atol=1e-8)
        assert torch.allclose(sbn.bias.grad, bn.bias.grad, atol=1e-8)
        assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-10)
        assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-10)

    def test_eval_mode(self):
        torch.manual_seed(0)
        sbn = hvd.SyncBatchNorm(3).double()
        x = torch.randn(4, 3, dtype=torch.float64)
        sbn(x)  # one training step to move running stats
        sbn.eval()
        y = sbn(x)
        bn = torch.nn.BatchNorm1d(3).double()
        bn.load_state_dict(sbn.state_dict())
        bn.eval()
        assert torch.allclose(y, bn(x), atol=1e-12)

    def test_eval_mode_backward(self):
        sbn = hvd.SyncBatchNorm(3).double()
        sbn(torch.randn(4, 3, dtype=torch.float64))
        sbn.eval()
        x = torch.randn(4, 3, dtype=torch.float64, requires_grad=True)
        sbn(x).sum().backward()
        assert x.grad is not None

    def test_affine_false_backward(self):
        sbn = hvd.SyncBatchNorm(3, affine=False).double()
        x = torch.randn(4, 3, dtype=torch.float64, requires_grad=True)
        sbn(x).pow(2).sum().backward()
        assert x.grad is not None

    def test_no_running_stats(self):
        sbn = hvd.SyncBatchNorm(3, track_running_stats=False).double()
        x = torch.randn(4, 3, dtype=torch.float64)
        y_train = sbn(x)
        sbn.eval()
        y_eval = sbn(x)  # batch stats in eval too, like nn.BatchNorm
        assert torch.allclose(y_train, y_eval, atol=1e-12)


_WORKER = textwrap.dedent("""
    import os
    os.environ.pop('PALLAS_AXON_POOL_IPS', None)
    os.environ['JAX_PLATFORMS'] = 'cpu'
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import numpy as np
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    assert hvd.size() == 2, hvd.size()
    r = hvd.rank()

    # allreduce: average / sum / min / max, out-of-place + in-place
    t = torch.full((4,), float(r + 1))
    assert torch.allclose(hvd.allreduce(t), torch.full((4,), 1.5))
    assert torch.allclose(hvd.allreduce(t, op=hvd.Sum), torch.full((4,), 3.0))
    assert torch.allclose(hvd.allreduce(t, op=hvd.Min), torch.full((4,), 1.0))
    assert torch.allclose(hvd.allreduce(t, op=hvd.Max), torch.full((4,), 2.0))
    t2 = torch.full((3,), float(r + 1))
    hvd.allreduce_(t2)
    assert torch.allclose(t2, torch.full((3,), 1.5))

    # grouped
    outs = hvd.grouped_allreduce(
        [torch.full((2,), float(r)), torch.full((3,), 2.0 * r)], op=hvd.Sum)
    assert torch.allclose(outs[0], torch.full((2,), 1.0))
    assert torch.allclose(outs[1], torch.full((3,), 2.0))

    # allgather with ragged first dims: 2 rows from rank0, 3 from rank1
    g = hvd.allgather(torch.full((2 + r, 2), float(r)))
    assert g.shape == (5, 2), g.shape
    assert torch.allclose(g[:2], torch.zeros(2, 2))
    assert torch.allclose(g[2:], torch.ones(3, 2))

    # broadcast from rank 1
    out = hvd.broadcast(torch.full((2,), float(r)), root_rank=1)
    assert torch.allclose(out, torch.full((2,), 1.0))

    # alltoall, equal splits
    x = torch.arange(4, dtype=torch.float32) + 10 * r
    got = hvd.alltoall(x)
    exp = torch.tensor([2.0 * r, 2.0 * r + 1, 10 + 2.0 * r, 10 + 2.0 * r + 1])
    assert torch.allclose(got, exp), (got, exp)

    # alltoall, ragged splits
    x = torch.arange(3, dtype=torch.float32) + 10 * r
    splits = torch.tensor([1, 2]) if r == 0 else torch.tensor([2, 1])
    got, rsplits = hvd.alltoall(x, splits)
    if r == 0:
        assert got.tolist() == [0.0, 10.0, 11.0], got
        assert rsplits.tolist() == [1, 2]
    else:
        assert got.tolist() == [1.0, 2.0, 12.0], got
        assert rsplits.tolist() == [2, 1]

    # reducescatter
    x = torch.arange(4, dtype=torch.float32) * (r + 1)
    out = hvd.reducescatter(x)
    exp = torch.tensor([0.0, 3.0]) if r == 0 else torch.tensor([6.0, 9.0])
    assert torch.allclose(out, exp), (out, exp)

    # DistributedOptimizer: different grads per worker -> averaged update
    torch.manual_seed(r)   # deliberately different init; broadcast fixes it
    model = torch.nn.Linear(3, 2)
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    w0 = model.weight.detach().clone()
    opt = hvd.DistributedOptimizer(
        torch.optim.SGD(model.parameters(), lr=0.1),
        named_parameters=model.named_parameters())
    x = torch.ones(2, 3) * (r + 1)
    opt.zero_grad()
    model(x).sum().backward()
    opt.step()
    # dL/dW = 2*(r+1) per entry; cross-worker average = 3.0
    assert torch.allclose(model.weight.detach(),
                          w0 - 0.1 * 3.0 * torch.ones(2, 3), atol=1e-6)

    # SyncBatchNorm: half the batch on each worker == full-batch BN
    torch.manual_seed(42)
    full = torch.randn(6, 4, dtype=torch.float64)
    local = full[r * 3:(r + 1) * 3].clone().requires_grad_(True)
    fullref = full.clone().requires_grad_(True)
    sbn = hvd.SyncBatchNorm(4).double()
    bn = torch.nn.BatchNorm1d(4).double()
    bn.load_state_dict({k: v.clone() for k, v in sbn.state_dict().items()})
    y = sbn(local)
    yr = bn(fullref)
    assert torch.allclose(y, yr[r * 3:(r + 1) * 3], atol=1e-10)
    y.pow(2).sum().backward()
    yr.pow(2).sum().backward()
    assert torch.allclose(local.grad, fullref.grad[r * 3:(r + 1) * 3],
                          atol=1e-8)
    assert torch.allclose(sbn.running_mean, bn.running_mean, atol=1e-10)
    assert torch.allclose(sbn.running_var, bn.running_var, atol=1e-10)

    # object broadcast
    obj = hvd.broadcast_object({'rank': r}, root_rank=1)
    assert obj['rank'] == 1
    assert hvd.allgather_object(r) == [0, 1]

    hvd.barrier()
    print('torch worker', r, 'ok')
""")


@pytest.mark.slow
class TestTwoWorkerIntegration:
    def test_two_worker_torch_numerics(self, tmp_path):
        script = tmp_path / "torch_worker.py"
        script.write_text(_WORKER)
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = {"PYTHONPATH": repo_root + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        rc = run(2, [sys.executable, str(script)], start_timeout=240, env=env)
        assert rc == 0


class TestSparseGradients:
    def _embedding_step(self, sparse_as_dense):
        import horovod_tpu.torch as hvt

        torch.manual_seed(0)
        emb = torch.nn.Embedding(10, 4, sparse=True)
        opt = hvt.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=0.5),
            named_parameters=emb.named_parameters(),
            sparse_as_dense=sparse_as_dense)
        idx = torch.tensor([1, 3, 3])
        loss = emb(idx).sum()
        loss.backward()
        assert emb.weight.grad.is_sparse or sparse_as_dense
        opt.synchronize()
        return emb

    def test_sparse_allreduce_path(self):
        """Reference sparse path: values/indices allgather, duplicate
        indices coalesce-summed; single process -> grad unchanged."""
        emb = self._embedding_step(sparse_as_dense=False)
        g = emb.weight.grad.to_dense()
        assert torch.allclose(g[3], torch.full((4,), 2.0)), g[3]
        assert torch.allclose(g[1], torch.ones(4)), g[1]
        assert torch.allclose(g[0], torch.zeros(4))

    def test_sparse_as_dense_densifies(self):
        emb = self._embedding_step(sparse_as_dense=True)
        assert not emb.weight.grad.is_sparse
        g = emb.weight.grad
        assert torch.allclose(g[3], torch.full((4,), 2.0)), g[3]

    def test_sparse_adasum_rejected(self):
        import horovod_tpu.torch as hvt

        emb = torch.nn.Embedding(6, 2, sparse=True)
        opt = hvt.DistributedOptimizer(
            torch.optim.SGD(emb.parameters(), lr=0.1), op=hvt.Adasum)
        with pytest.raises(NotImplementedError, match="sparse"):
            # the hook fires during backward on new torch; older torch
            # defers the check to synchronize()
            emb(torch.tensor([0, 1])).sum().backward()
            opt.synchronize()
