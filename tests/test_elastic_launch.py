"""Elastic launcher supervision tests.

Reference pattern: ``test/integration/test_elastic_*`` (SURVEY.md §4) —
fake discovery scripts add/remove hosts mid-run; assert the job
survives restarts and honors reset limits.  Here the worlds are local
processes (same as the reference's single-machine elastic CI).
"""

import os
import sys
import textwrap
import threading

import pytest

from horovod_tpu.elastic.driver import FixedDiscovery, HostDiscovery
from horovod_tpu.runner import run_elastic


class MutableDiscovery(HostDiscovery):
    """Discovery whose answer the test mutates mid-run."""

    def __init__(self, slots: int):
        self._slots = slots
        self._lock = threading.Lock()

    def set_slots(self, n: int) -> None:
        with self._lock:
            self._slots = n

    def find_available_hosts_and_slots(self):
        with self._lock:
            return {"localhost": self._slots} if self._slots else {}


def _worker_script(tmp_path, body: str) -> str:
    path = tmp_path / "worker.py"
    path.write_text("import os, sys\n"
                    "os.environ.pop('PALLAS_AXON_POOL_IPS', None)\n"
                    + textwrap.dedent(body) + "\n")
    return str(path)


def _env():
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return {"PYTHONPATH": repo_root + os.pathsep
            + os.environ.get("PYTHONPATH", "")}


@pytest.mark.slow
class TestRunElastic:
    def test_completes_on_stable_membership(self, tmp_path):
        script = _worker_script(
            tmp_path,
            "print('worker', os.environ['HVD_TPU_PROCESS_ID'], 'of',"
            " os.environ['HVD_TPU_NUM_PROCESSES'])")
        rc = run_elastic([sys.executable, script],
                         min_np=1, max_np=2,
                         discovery=FixedDiscovery({"localhost": 2}),
                         env=_env(), poll_interval_s=0.2)
        assert rc == 0

    def test_world_sized_to_discovery(self, tmp_path):
        out = tmp_path / "np.txt"
        script = _worker_script(
            tmp_path,
            f"open({str(out)!r}, 'a').write("
            f"os.environ['HVD_TPU_NUM_PROCESSES'] + '\\n')")
        rc = run_elastic([sys.executable, script],
                         min_np=1, max_np=8,
                         discovery=FixedDiscovery({"localhost": 3}),
                         env=_env(), poll_interval_s=0.2)
        assert rc == 0
        assert out.read_text().splitlines() == ["3", "3", "3"]

    def test_restart_on_failure_until_reset_limit(self, tmp_path):
        script = _worker_script(tmp_path, "sys.exit(7)")
        rc = run_elastic([sys.executable, script],
                         min_np=1,
                         discovery=FixedDiscovery({"localhost": 1}),
                         env=_env(), poll_interval_s=0.1, reset_limit=2)
        assert rc == 1

    def test_restart_on_membership_change(self, tmp_path):
        # Workers sleep forever; shrinking discovery must trigger a
        # restart, and the restarted world (1 proc) exits 0 via marker.
        marker = tmp_path / "second_round"
        script = _worker_script(tmp_path, textwrap.dedent(f"""
            import time
            if os.environ['HVD_TPU_NUM_PROCESSES'] == '1':
                open({str(marker)!r}, 'w').write('ok')
                sys.exit(0)
            time.sleep(120)
        """).strip())
        disc = MutableDiscovery(2)

        def shrink_soon():
            import time
            time.sleep(2.0)
            disc.set_slots(1)

        threading.Thread(target=shrink_soon, daemon=True).start()
        rc = run_elastic([sys.executable, script], min_np=1,
                         discovery=disc, env=_env(), poll_interval_s=0.2)
        assert rc == 0
        assert marker.exists()
