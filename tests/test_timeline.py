"""Timeline + stall inspector tests (reference pattern:
test/single/test_timeline.py parses the emitted JSON; test_stall.py —
SURVEY.md §4)."""

import json
import time

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.utils.stall import StallInspector
from horovod_tpu.utils.timeline import Timeline


class TestTimeline:
    def test_emits_valid_chrome_trace(self, tmp_path, world_size):
        path = tmp_path / "timeline.json"
        hvd.start_timeline(str(path))
        x = np.ones((world_size, 4), np.float32)
        hvd.allreduce(x, name="grad/layer0")
        hvd.allgather(np.ones((world_size, 2, 2), np.float32), name="gather0")
        hvd.stop_timeline()
        events = json.load(open(path))
        assert len(events) >= 3
        phases = {e["name"] for e in events}
        assert "ENQUEUE" in phases and "EXECUTE" in phases
        tensors = {e["args"]["tensor"] for e in events if "args" in e}
        assert "grad/layer0" in tensors and "gather0" in tensors
        for e in events:
            assert e["ph"] in ("X", "i")
            assert "ts" in e and "pid" in e

    def test_disabled_timeline_is_noop(self):
        tl = Timeline(None)
        assert not tl.enabled
        with tl.activity("x", "EXECUTE"):
            pass
        tl.close()

    def test_mark_cycles(self, tmp_path):
        path = tmp_path / "t.json"
        tl = Timeline(str(path), mark_cycles=True)
        tl.mark_cycle()
        tl.record("t", "EXECUTE", 0.0, 5.0)
        tl.close()
        events = json.load(open(path))
        assert any(e["name"] == "CYCLE" and e["ph"] == "i" for e in events)

    @pytest.mark.parametrize("use_native", [True, False])
    def test_close_mid_activity_drops_event_safely(self, tmp_path,
                                                   use_native):
        """A timeline closed while an activity is open (elastic reset
        mid-step) must drop that activity's event — never write to a
        closed backend — and leave a valid JSON file."""
        path = tmp_path / f"race{use_native}.json"
        tl = Timeline(str(path), use_native=use_native)
        tl.record("kept", "EXECUTE", 0.0, 1.0)
        with tl.activity("x", "EXECUTE"):
            tl.close()          # elastic teardown racing the step
            assert not tl.enabled
        # Reopenable output: the array was finalized exactly once, and
        # the in-flight activity is absent.
        events = json.load(open(path))
        assert {e["args"]["tensor"] for e in events
                if "args" in e} == {"kept"}
        tl.close()              # idempotent

    def test_counter_events_render_as_counter_track(self, tmp_path):
        path = tmp_path / "counters.json"
        tl = Timeline(str(path))
        tl.counter("train", {"step_time_ms": 3.5, "tokens_per_s": 100.0})
        tl.close()
        events = json.load(open(path))
        (c,) = [e for e in events if e["ph"] == "C"]
        assert c["name"] == "train"
        assert c["args"] == {"step_time_ms": 3.5, "tokens_per_s": 100.0}

    @pytest.mark.parametrize("use_native", [True, False])
    def test_flow_events_bind_by_id(self, tmp_path, use_native):
        """A flow pair ("s" at the producer, "f" with bp:"e" at the
        consumer) sharing one id is how a cross-process RPC edge renders
        as a Perfetto arrow (docs/tracing.md) — both backends must emit
        the same shape."""
        path = tmp_path / f"flow{use_native}.json"
        tl = Timeline(str(path), use_native=use_native)
        tl.record("rpc", "EXECUTE", 0.0, 5.0)
        tl.flow("hvd_tpu_rpc_client", "abc123", "s", ts_us=1.0)
        tl.flow("hvd_tpu_rpc_client", "abc123", "f", ts_us=4.0)
        tl.close()
        events = json.load(open(path))
        flows = [e for e in events if e["ph"] in ("s", "f")]
        assert {e["ph"] for e in flows} == {"s", "f"}
        assert {e["id"] for e in flows} == {"abc123"}
        (fin,) = [e for e in flows if e["ph"] == "f"]
        assert fin["bp"] == "e"   # binds to the enclosing slice
        for e in flows:
            assert "ts" in e and "pid" in e

    @pytest.mark.parametrize("use_native", [True, False])
    def test_flow_after_close_is_dropped_safely(self, tmp_path,
                                                use_native):
        """Same close-race contract as activity(): a flow emitted after
        an elastic teardown closed the timeline must be dropped, not
        corrupt the finalized file."""
        path = tmp_path / f"flowrace{use_native}.json"
        tl = Timeline(str(path), use_native=use_native)
        tl.flow("kept", "id1", "s", ts_us=1.0)
        tl.close()
        tl.flow("dropped", "id2", "f", ts_us=2.0)
        events = json.load(open(path))
        assert [e["id"] for e in events if e["ph"] in ("s", "f")] == ["id1"]

    def test_flow_rejects_unknown_phase(self, tmp_path):
        tl = Timeline(str(tmp_path / "p.json"))
        with pytest.raises(ValueError, match="flow phase"):
            tl.flow("x", "id", "t")
        tl.close()


@pytest.fixture
def stall_records():
    """The horovod_tpu logger doesn't propagate to root (so caplog can't
    see it); attach a capturing handler directly."""
    import logging

    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record)

    handler = _Capture()
    logger = logging.getLogger("horovod_tpu.utils.stall")
    logger.addHandler(handler)
    yield records
    logger.removeHandler(handler)


class TestStallInspector:
    def test_warns_on_idle(self, stall_records):
        si = StallInspector(enabled=True, warn_after_s=0.05)
        si.record_activity("step")
        time.sleep(0.3)
        # watchdog thread polls at warn_after_s/4
        si.stop()
        assert any("Potential stall" in r.getMessage()
                   for r in stall_records)

    def test_heartbeat_prevents_warning(self, stall_records):
        si = StallInspector(enabled=True, warn_after_s=0.5)
        for _ in range(5):
            si.record_activity("step")
            time.sleep(0.02)
        si.stop()
        assert not any("Potential stall" in r.getMessage()
                       for r in stall_records)

    def test_shutdown_hook_fires(self):
        fired = []
        si = StallInspector(enabled=True, warn_after_s=0.02,
                            shutdown_after_s=0.05,
                            on_shutdown=lambda: fired.append(1))
        si.record_activity("step")
        time.sleep(0.4)
        si.stop()
        assert fired

    def test_pause_disarms(self, stall_records):
        si = StallInspector(enabled=True, warn_after_s=0.05)
        si.record_activity("step")
        with si.pause():
            time.sleep(0.3)
        si.stop()
        assert not any("Potential stall" in r.getMessage()
                       for r in stall_records)

    def test_disabled_never_warns(self, stall_records):
        si = StallInspector(enabled=False, warn_after_s=0.01)
        si.record_activity("step")
        time.sleep(0.1)
        si.stop()
        assert not stall_records
