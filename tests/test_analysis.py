"""hvdlint: the distributed-correctness static-analysis subsystem.

Two layers of coverage:

* **Fixture tests** — a minimal fake package per check with a good and
  a bad variant, proving each analyzer fires exactly on its violation
  class (rank-divergent collective, knob drift, lock discipline,
  lock-order cycle, registry drift, suppression lifecycle) and that a
  deliberately rank-divergent fused plan fails the jaxpr check.
* **The gate** — every analyzer over the real package asserting ZERO
  unsuppressed findings, which is what makes the invariants stick for
  every future PR (acceptance criterion of the analysis issue).
"""

from __future__ import annotations

import json
import subprocess
import sys
from pathlib import Path

import pytest

import horovod_tpu as hvd
from horovod_tpu import analysis
from horovod_tpu.analysis import jaxpr_check
from horovod_tpu.analysis.core import LintConfig, run_checks
from horovod_tpu.analysis.knobs import KnobChecker
from horovod_tpu.analysis.locks import LockChecker
from horovod_tpu.analysis.rank_divergence import RankDivergenceChecker
from horovod_tpu.analysis.registries import (FaultSiteChecker,
                                             MeshAxisChecker,
                                             MetricNameChecker,
                                             ObservabilityChecker,
                                             SpanNameChecker)

pytestmark = pytest.mark.analysis

REPO = Path(__file__).resolve().parent.parent

# Minimal config.py for fixture packages: enough surface for the knob
# and fault-site checkers to key off (they parse THIS, not the real one).
FIXTURE_CONFIG = '''
import dataclasses, os

PRE_INIT_KNOBS = ("PROCESS_ID",)
FAULT_SITES = ("collective", "rpc")
MESH_AXES = ("data", "fsdp", "hvd")
_NOOP_KNOBS = {"CYCLE_TIME": "no cycle loop here"}


def _env(name, default=None):
    for p in ("HOROVOD_", "HVD_TPU_"):
        v = os.environ.get(p + name)
        if v is not None:
            return v
    return default


def _env_int(name, default):
    v = _env(name)
    return int(v) if v is not None else default


@dataclasses.dataclass(frozen=True)
class Config:
    fusion_threshold: int = 1
    cycle_time_ms: float = 1.0

    @staticmethod
    def from_env():
        return Config(
            fusion_threshold=_env_int("FUSION_THRESHOLD", 1),
            cycle_time_ms=_env_int("CYCLE_TIME", 1),
        )
'''

FIXTURE_ENV_DOC = """
| `HOROVOD_FUSION_THRESHOLD` | 1 | bucket bytes |
| `HOROVOD_CYCLE_TIME` | 1.0 | no-op |
| `HVD_TPU_PROCESS_ID` | unset | rank wiring |
"""

FIXTURE_FAULT_DOC = """
| `collective` | dispatch | raise | boom |
| `rpc` | client | drop | gone |
"""

# Consumes Config.fusion_threshold so the fixture baseline is clean.
FIXTURE_CONSUMER = "def use(cfg):\n    return cfg.fusion_threshold\n"


def lint(tmp_path, files, checkers, docs=None, select=None):
    """Materialize a fixture package and run the given checkers."""
    pkg = tmp_path / "horovod_tpu"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "__init__.py").write_text("")
    for rel, text in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    docdir = tmp_path / "docs"
    docdir.mkdir(exist_ok=True)
    for name, text in {"env_vars.md": FIXTURE_ENV_DOC,
                       "fault_injection.md": FIXTURE_FAULT_DOC,
                       "metrics.md": "", **(docs or {})}.items():
        (docdir / name).write_text(text)
    cfg = LintConfig(root=tmp_path, select=select)
    return run_checks(cfg, checker_classes=checkers)


def checks_of(findings):
    return sorted({f.check for f in findings})


# --- rank-divergent collectives ---------------------------------------------

BAD_RANK_BRANCH = """
from . import rank, allreduce

def log_and_sync(x):
    if rank() == 0:
        x = allreduce(x)   # only rank 0 reaches the rendezvous
    return x
"""

BAD_RANK_EARLY_EXIT = """
from . import rank, barrier

def save(x):
    r = rank()
    if r != 0:
        return None
    barrier()   # only rank 0 still executing
    return x
"""

GOOD_RANK_BRANCH = """
from . import rank, allreduce

def log_and_sync(x):
    x = allreduce(x)       # every rank participates...
    if rank() == 0:
        print("synced", x)  # ...and only the log is rank-conditioned
    return x
"""


def test_rank_divergent_collective_positive(tmp_path):
    fs = lint(tmp_path, {"m.py": BAD_RANK_BRANCH},
              [RankDivergenceChecker])
    assert checks_of(fs) == ["rank-divergent-collective"]
    assert "allreduce" in fs[0].message


def test_rank_divergent_early_exit_positive(tmp_path):
    fs = lint(tmp_path, {"m.py": BAD_RANK_EARLY_EXIT},
              [RankDivergenceChecker])
    assert checks_of(fs) == ["rank-divergent-collective"]
    assert "early exit" in fs[0].message


def test_rank_conditioned_logging_negative(tmp_path):
    # The keras-callbacks pattern: rank-0 verbose print, collective
    # hoisted out — provably collective-free conditioned branch.
    fs = lint(tmp_path, {"m.py": GOOD_RANK_BRANCH},
              [RankDivergenceChecker])
    assert fs == []


def test_keras_callbacks_rank_branches_are_collective_free(tmp_path):
    """The real tensorflow/keras/callbacks.py: its rank-0-verbose
    logging (and every sibling rank-conditioned path) must stay
    provably collective-free — this pins the file specifically, beyond
    the whole-tree gate."""
    src = (REPO / "horovod_tpu" / "tensorflow" / "keras"
           / "callbacks.py").read_text()
    fs = lint(tmp_path, {"callbacks.py": src}, [RankDivergenceChecker])
    assert fs == [], "\n".join(f.format() for f in fs)


# --- knob consistency --------------------------------------------------------

def test_unknown_knob(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": 'import os\nV = os.environ.get("HVD_TPU_MYSTERY")\n'},
              [KnobChecker])
    assert "unknown-knob" in checks_of(fs)


def test_raw_env_read_of_declared_knob(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": 'import os\n'
                       'V = os.environ.get("HVD_TPU_FUSION_THRESHOLD")\n'},
              [KnobChecker])
    assert "raw-env-read" in checks_of(fs)


def test_pre_init_knob_read_is_allowed(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": 'import os\n'
                       'V = os.environ.get("HVD_TPU_PROCESS_ID")\n'},
              [KnobChecker])
    assert fs == []


def test_undocumented_knob(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER},
              [KnobChecker],
              docs={"env_vars.md": "| `HOROVOD_CYCLE_TIME` | 1.0 | x |\n"
                                   "| `HVD_TPU_PROCESS_ID` | unset | x |\n"})
    assert checks_of(fs) == ["undocumented-knob"]
    assert "FUSION_THRESHOLD" in fs[0].message


def test_unconsumed_knob(tmp_path):
    # No module reads .fusion_threshold -> dead knob.  cycle_time_ms is
    # in _NOOP_KNOBS, so it stays exempt.
    fs = lint(tmp_path, {"config.py": FIXTURE_CONFIG}, [KnobChecker])
    assert checks_of(fs) == ["unconsumed-knob"]
    assert "fusion_threshold" in fs[0].message


# --- lock discipline ---------------------------------------------------------

BAD_LOCK = """
import threading

class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []   # guarded-by: _lock

    def ok(self, x):
        with self._lock:
            self._items.append(x)

    def racy(self, x):
        self._items.append(x)   # no lock held
"""

GOOD_LOCK = BAD_LOCK.replace(
    "    def racy(self, x):\n        self._items.append(x)   # no lock held\n",
    "")

LOCK_CYCLE = """
import threading

_la = threading.Lock()
_lb = threading.Lock()

def ab():
    with _la:
        with _lb:
            pass

def ba():
    with _lb:
        with _la:
            pass
"""

CROSS_FN_CYCLE = """
import threading

_la = threading.Lock()
_lb = threading.Lock()

def inner_b():
    with _lb:
        pass

def holds_a():
    with _la:
        inner_b()

def inner_a():
    with _la:
        pass

def holds_b():
    with _lb:
        inner_a()
"""


def test_unguarded_mutation_positive(tmp_path):
    fs = lint(tmp_path, {"m.py": BAD_LOCK}, [LockChecker])
    assert checks_of(fs) == ["unguarded-mutation"]
    assert "_items" in fs[0].message


def test_guarded_mutation_negative(tmp_path):
    assert lint(tmp_path, {"m.py": GOOD_LOCK}, [LockChecker]) == []


def test_lock_order_cycle_nested(tmp_path):
    fs = lint(tmp_path, {"m.py": LOCK_CYCLE}, [LockChecker])
    assert checks_of(fs) == ["lock-order-cycle"]
    assert "_la" in fs[0].message and "_lb" in fs[0].message


def test_lock_order_cycle_one_line_with(tmp_path):
    # `with _la, _lb:` vs `with _lb, _la:` — the ABBA one-liner form
    # must edge exactly like the nested form.
    src = ("import threading\n"
           "_la = threading.Lock()\n"
           "_lb = threading.Lock()\n"
           "def ab():\n"
           "    with _la, _lb:\n"
           "        pass\n"
           "def ba():\n"
           "    with _lb, _la:\n"
           "        pass\n")
    fs = lint(tmp_path, {"m.py": src}, [LockChecker])
    assert checks_of(fs) == ["lock-order-cycle"]


def test_lock_order_cycle_through_calls(tmp_path):
    # A->B via holds_a->inner_b, B->A via holds_b->inner_a: cycle only
    # visible through the call graph.
    fs = lint(tmp_path, {"m.py": CROSS_FN_CYCLE}, [LockChecker])
    assert checks_of(fs) == ["lock-order-cycle"]


def test_lock_order_no_cycle(tmp_path):
    fs = lint(tmp_path,
              {"m.py": LOCK_CYCLE.replace(
                  "with _lb:\n        with _la:", "with _lb:\n        if 1:")},
              [LockChecker])
    assert fs == []


def test_unguarded_mutation_inside_closure(tmp_path):
    # Thread-target closures execute later, NOT under any enclosing
    # with — their mutations must stay visible to the checker.
    src = BAD_LOCK.replace(
        "    def racy(self, x):\n        self._items.append(x)   # no lock held\n",
        "    def spawn(self, x):\n"
        "        def worker():\n"
        "            self._items.append(x)   # closure, no lock held\n"
        "        return worker\n")
    fs = lint(tmp_path, {"m.py": src}, [LockChecker])
    assert checks_of(fs) == ["unguarded-mutation"]


def test_wrong_lock_does_not_satisfy_guard(tmp_path):
    # Holding a DIFFERENT object's same-named lock is the race this
    # check exists for — exact lock identity is required.
    src = """
import threading

class Box:
    def __init__(self, other):
        self._lock = threading.Lock()
        self._other = other
        self._items = []   # guarded-by: _lock

    def racy(self, x):
        with self._other._lock:
            self._items.append(x)   # wrong lock!
"""
    fs = lint(tmp_path, {"m.py": src}, [LockChecker])
    assert checks_of(fs) == ["unguarded-mutation"]


# --- suppressions ------------------------------------------------------------

def test_suppression_honored(tmp_path):
    suppressed = BAD_LOCK.replace(
        "self._items.append(x)   # no lock held",
        "self._items.append(x)   # hvdlint: disable=unguarded-mutation "
        "-- fixture: caller holds the lock")
    assert lint(tmp_path, {"m.py": suppressed}, [LockChecker]) == []


def test_suppression_expired_is_reported(tmp_path):
    # A suppression matching nothing must not rot silently.
    fs = lint(tmp_path,
              {"m.py": GOOD_LOCK + "\nX = 1  # hvdlint: "
               "disable=unguarded-mutation -- stale excuse\n"},
              [LockChecker])
    assert checks_of(fs) == ["useless-suppression"]


def test_suppression_without_justification_is_a_finding(tmp_path):
    fs = lint(tmp_path,
              {"m.py": "X = 1  # hvdlint: disable=unguarded-mutation\n"},
              [LockChecker])
    assert checks_of(fs) == ["bad-suppression"]


def test_suppression_unknown_id_is_a_finding(tmp_path):
    fs = lint(tmp_path,
              {"m.py": "X = 1  # hvdlint: disable=not-a-check -- why\n"},
              [LockChecker])
    assert checks_of(fs) == ["bad-suppression"]


def test_select_scoped_run_keeps_suppressions_matched(tmp_path):
    # A --select run that deselects the suppressed check must not
    # misread the (legitimate) suppression as useless: matching happens
    # against the full finding set, filtering after.
    suppressed = BAD_LOCK.replace(
        "self._items.append(x)   # no lock held",
        "self._items.append(x)   # hvdlint: disable=unguarded-mutation "
        "-- fixture: caller holds the lock")
    fs = lint(tmp_path, {"m.py": suppressed}, [LockChecker],
              select=["useless-suppression"])
    assert fs == []


def test_suppression_in_string_literal_is_ignored(tmp_path):
    fs = lint(tmp_path,
              {"m.py": 'DOC = "# hvdlint: disable=unguarded-mutation"\n'},
              [LockChecker])
    assert fs == []


# --- registry consistency ----------------------------------------------------

def test_unknown_fault_site(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": "from . import faults\n\n"
                       "def drill():\n"
                       '    with faults.inject("nosite:step=1"):\n'
                       "        pass\n"},
              [FaultSiteChecker])
    assert checks_of(fs) == ["unknown-fault-site"]


def test_fault_site_doc_drift(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER},
              [FaultSiteChecker],
              docs={"fault_injection.md": "| `collective` | x | raise | y |\n"})
    assert checks_of(fs) == ["fault-site-doc-drift"]
    assert "rpc" in fs[0].message


def test_unknown_mesh_axis_in_partition_spec(tmp_path):
    """ISSUE 18 satellite: a typo'd axis in a P(...) spec (including
    the multi-axis tuple form) must be flagged against the MESH_AXES
    plan catalog instead of silently diverging from the MeshPlan."""
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": "from jax.sharding import PartitionSpec as P\n\n"
                       "def specs():\n"
                       '    ok = P("data", None)\n'
                       '    ok2 = P(("data", "fsdp"))\n'
                       '    bad = P("dataa", None)\n'
                       '    bad2 = P(("data", "fspd"))\n'},
              [MeshAxisChecker])
    assert checks_of(fs) == ["unknown-mesh-axis"]
    assert len(fs) == 2
    assert "dataa" in fs[0].message and "fspd" in fs[1].message


def test_unknown_mesh_axis_in_axis_kwargs_and_defaults(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": "def reduce(x, collective):\n"
                       '    return collective(x, axis_name="hvdd")\n\n'
                       'def step(x, dp_axis="dta"):\n'
                       "    return x\n"},
              [MeshAxisChecker])
    assert checks_of(fs) == ["unknown-mesh-axis"]
    assert len(fs) == 2


def test_known_mesh_axes_are_clean(tmp_path):
    fs = lint(tmp_path,
              {"config.py": FIXTURE_CONFIG, "c.py": FIXTURE_CONSUMER,
               "m.py": "from jax.sharding import PartitionSpec as P\n\n"
                       'def step(x, collective, axis_name="hvd",\n'
                       '         dp_axis="data"):\n'
                       '    spec = P(("data", "fsdp"), None)\n'
                       "    return collective(x, axis_name=axis_name)\n"},
              [MeshAxisChecker])
    assert fs == []


def test_metric_naming_rules(tmp_path):
    src = (
        "def instrument(reg):\n"
        '    reg.counter("hvd_tpu_good_total").inc()\n'
        '    reg.counter("hvd_tpu_bad_counter").inc()\n'      # no _total
        '    reg.gauge("hvd_tpu_bad_gauge_total").set(1)\n'   # _total gauge
    )
    fs = lint(tmp_path, {"m.py": src}, [MetricNameChecker],
              docs={"metrics.md": "hvd_tpu_good_total hvd_tpu_bad_counter "
                                  "hvd_tpu_bad_gauge_total"})
    assert checks_of(fs) == ["metric-name"]
    assert len(fs) == 2


def test_metric_doc_drift(tmp_path):
    fs = lint(tmp_path,
              {"m.py": 'def f(reg):\n'
                       '    reg.counter("hvd_tpu_undocumented_total")\n'},
              [MetricNameChecker], docs={"metrics.md": "# catalog\n"})
    assert checks_of(fs) == ["metric-doc-drift"]


def test_metric_tenant_cardinality_flags_uncapped_labels(tmp_path):
    """ISSUE 15 satellite: a tenant-id label minted outside the obs
    registry (whose 64-series cap bounds it) is one series per tenant
    forever — flagged at lint time."""
    src = (
        "def instrument(reg, exporter, tenant):\n"
        # Registry-chained: rides the cap — clean.
        '    reg.counter("hvd_tpu_ok_total").labels(tenant=tenant).inc()\n'
        # One-level local family binding: also the capped idiom.
        '    fam = reg.counter("hvd_tpu_fam_total")\n'
        "    fam.labels(tenant=tenant).inc()\n"
        # Hand-rolled series object: unbounded — flagged.
        "    exporter.labels(tenant=tenant)\n"
        # tenant_id spelling is held to the same rule.
        "    exporter.labels(tenant_id=tenant)\n"
    )
    fs = lint(tmp_path, {"m.py": src}, [MetricNameChecker],
              docs={"metrics.md": "hvd_tpu_ok_total hvd_tpu_fam_total"})
    assert checks_of(fs) == ["metric-tenant-cardinality"]
    assert len(fs) == 2
    assert all("64-series" in f.message for f in fs)


def test_metric_tenant_cardinality_clean_without_tenant_labels(tmp_path):
    src = (
        "def instrument(reg, exporter):\n"
        '    reg.counter("hvd_tpu_x_total").labels(site="a").inc()\n'
        '    exporter.labels(kind="b")\n'   # no tenant label: not ours
    )
    fs = lint(tmp_path, {"m.py": src}, [MetricNameChecker],
              docs={"metrics.md": "hvd_tpu_x_total"})
    assert checks_of(fs) == []


def test_span_naming_rules(tmp_path):
    src = (
        "from ..obs import trace as trace_mod\n\n"
        "def hop():\n"
        '    with trace_mod.span("hvd_tpu_good"):\n'
        "        pass\n"
        '    trace_mod.instant("bare_name")\n'          # no prefix
        '    trace_mod.record_span("also_bare", parent=None,\n'
        "                          start_us=0.0, dur_us=1.0)\n"
    )
    fs = lint(tmp_path, {"m.py": src}, [SpanNameChecker],
              docs={"tracing.md": "hvd_tpu_good"})
    assert checks_of(fs) == ["span-name"]
    assert len(fs) == 2


def test_span_rules_cover_record_phase_forwarder(tmp_path):
    # batcher-style span-forwarding helper: the name rides in the
    # SECOND positional — self._record_phase(req, "name", t0, t1).
    src = (
        "class B:\n"
        "    def work(self, req):\n"
        '        self._record_phase(req, "bare_phase", 0.0, 1.0)\n'
        '        self._record_phase(req, "hvd_tpu_phase_ok", 0.0, 1.0)\n'
    )
    fs = lint(tmp_path, {"m.py": src}, [SpanNameChecker],
              docs={"tracing.md": "hvd_tpu_phase_ok"})
    assert checks_of(fs) == ["span-name"]
    assert "bare_phase" in fs[0].message


def test_span_doc_drift(tmp_path):
    fs = lint(tmp_path,
              {"m.py": "from ..obs import trace\n\n"
                       "def hop():\n"
                       '    with trace.span("hvd_tpu_undocumented"):\n'
                       "        pass\n"},
              [SpanNameChecker], docs={"tracing.md": "# span catalog\n"})
    assert checks_of(fs) == ["span-doc-drift"]


def test_span_rules_ignore_non_trace_receivers(tmp_path):
    # Timeline-style .span()/.record() lookalikes on other receivers
    # carry free-form names and are not held to span rules.
    fs = lint(tmp_path,
              {"m.py": "def f(timeline):\n"
                       '    timeline.span("free-form name")\n'},
              [SpanNameChecker], docs={"tracing.md": ""})
    assert fs == []


# --- wire-protocol consistency ----------------------------------------------

PROTOCOL_FIXTURE = """
class AckResponse:
    pass


class PingRequest:
    pass


class PingResponse:
    pass


class EchoRequest:
    pass


class EchoResponse:
    pass


class BasicService:
    def _handle(self, req, addr):
        if isinstance(req, PingRequest):
            return PingResponse()
        if isinstance(req, EchoRequest):
            return self._echo(req)
        return AckResponse()

    def _echo(self, req):
        return EchoResponse()
"""

PROTOCOL_DOC = "| `PingRequest` | x |\n| `EchoRequest` | x |\n" \
               "| `GhostRequest` | x |\n"


def test_protocol_clean_fixture(tmp_path):
    from horovod_tpu.analysis.protocol import ProtocolChecker

    fs = lint(tmp_path, {"net.py": PROTOCOL_FIXTURE}, [ProtocolChecker],
              docs={"serving.md": PROTOCOL_DOC})
    assert fs == [], "\n".join(f.format() for f in fs)


def test_protocol_unhandled_frame(tmp_path):
    from horovod_tpu.analysis.protocol import ProtocolChecker

    src = PROTOCOL_FIXTURE + "\n\nclass GhostRequest:\n    pass\n"
    fs = lint(tmp_path, {"net.py": src}, [ProtocolChecker],
              docs={"serving.md": PROTOCOL_DOC})
    assert checks_of(fs) == ["unhandled-request-frame"]
    assert "GhostRequest" in fs[0].message


def test_protocol_mismatched_response(tmp_path):
    from horovod_tpu.analysis.protocol import ProtocolChecker

    # The Ping branch answers Ack even though PingResponse exists:
    # pairing drift a typed client would break on.
    src = PROTOCOL_FIXTURE.replace(
        "        if isinstance(req, PingRequest):\n"
        "            return PingResponse()",
        "        if isinstance(req, PingRequest):\n"
        "            return AckResponse()")
    fs = lint(tmp_path, {"net.py": src}, [ProtocolChecker],
              docs={"serving.md": PROTOCOL_DOC})
    assert checks_of(fs) == ["mismatched-response"]
    assert "PingResponse" in fs[0].message


def test_protocol_doc_drift(tmp_path):
    from horovod_tpu.analysis.protocol import ProtocolChecker

    fs = lint(tmp_path, {"net.py": PROTOCOL_FIXTURE}, [ProtocolChecker],
              docs={"serving.md": "| `PingRequest` | x |\n"})
    assert checks_of(fs) == ["protocol-doc-drift"]
    assert "EchoRequest" in fs[0].message


def test_protocol_ignores_non_service_modules(tmp_path):
    from horovod_tpu.analysis.protocol import ProtocolChecker

    # A *Request class in a module with no BasicService is an internal
    # queue item (ServeRequest pattern), not a wire frame.
    fs = lint(tmp_path, {"m.py": "class ServeRequest:\n    pass\n"},
              [ProtocolChecker], docs={"serving.md": ""})
    assert fs == []


# --- bounded-wait discipline -------------------------------------------------

def test_unbounded_thread_join(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("import threading\n"
           "def f(fn):\n"
           "    t = threading.Thread(target=fn)\n"
           "    t.start()\n"
           "    t.join()\n")
    fs = lint(tmp_path, {"m.py": src}, [WaitChecker])
    assert checks_of(fs) == ["unbounded-wait"]
    assert "join" in fs[0].message


def test_bounded_thread_join_ok(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def f(self):\n"
           "    self._thread.join(timeout=5)\n")
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_str_join_is_not_a_thread_wait(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = 'def f(xs):\n    return ", ".join(str(x) for x in xs)\n'
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_unbounded_condition_wait(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def f(self):\n"
           "    with self._cv:\n"
           "        self._cv.wait()\n"
           "        self._cv.wait_for(lambda: True)\n")
    fs = lint(tmp_path, {"m.py": src}, [WaitChecker])
    assert checks_of(fs) == ["unbounded-wait"] and len(fs) == 2


def test_bounded_condition_wait_ok(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def f(self):\n"
           "    with self._cv:\n"
           "        self._cv.wait(timeout=1.0)\n"
           "        self._cv.wait_for(lambda: True, timeout=2.0)\n")
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_unbounded_queue_get_and_request(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def f(self, client):\n"
           "    item = self.task_queue.get()\n"
           "    resp = client.request(PingRequest())\n")
    fs = lint(tmp_path, {"m.py": src}, [WaitChecker])
    assert checks_of(fs) == ["unbounded-wait"] and len(fs) == 2


def test_bounded_request_ok(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def f(client):\n"
           "    return client.request(PingRequest(), timeout=30.0)\n")
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_handle_wait_is_not_flagged(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    # Collective Handle.wait() results are synchronous API forwarders,
    # not thread waits — receiver-name sensitivity keeps them exempt.
    src = ("def allreduce(tensor, handle):\n"
           "    return handle.wait()\n")
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_unbounded_wait_suppression(tmp_path):
    from horovod_tpu.analysis.waits import WaitChecker

    src = ("def supervise(proc_thread):\n"
           "    proc_thread.join()  # hvdlint: disable=unbounded-wait "
           "-- agent supervises the worker for the job's whole life\n")
    assert lint(tmp_path, {"m.py": src}, [WaitChecker]) == []


def test_select_group_aliases_expand():
    from horovod_tpu.analysis.core import expand_select

    assert expand_select(["protocol,waits"]) == [
        "unhandled-request-frame", "mismatched-response",
        "protocol-doc-drift", "unbounded-wait"]
    assert expand_select(None) is None
    assert expand_select(["unknown-knob"]) == ["unknown-knob"]


# --- jaxpr analyzer ----------------------------------------------------------

def _toy():
    import jax.numpy as jnp
    import optax

    def loss_fn(params, batch):
        x, y = batch
        return jnp.mean((x @ params["w"] + params["b"] - y) ** 2)

    params = {"w": jnp.zeros((64, 32)), "b": jnp.zeros((32,))}
    tx = optax.sgd(0.1)
    batch = (jnp.ones((16, 64)), jnp.ones((16, 32)))
    return loss_fn, params, tx, batch


def test_jaxpr_checks_pass_on_shipped_factories():
    assert analysis.run_jaxpr_checks() == []


def test_jaxpr_check_catches_rank_divergent_fused_plan():
    import jax

    from horovod_tpu.optim.distributed_optimizer import make_train_step

    loss_fn, params, tx, batch = _toy()

    def bad_factory():
        # Deliberately rank-divergent fused plan: rank 0 compiles the
        # overlapped RS+AG wire, every other rank the plain allreduce —
        # the schedules rendezvous differently and would deadlock.
        if jax.process_index() == 0:
            return make_train_step(loss_fn, tx, microbatches=2,
                                   overlap=True)
        return make_train_step(loss_fn, tx)

    fs = jaxpr_check.check_step_rank_consistency(
        bad_factory, lambda: (params, tx.init(params), batch))
    assert len(fs) == 1
    assert fs[0].check == "jaxpr-rank-divergence"
    assert "reduce_scatter" in fs[0].message


def test_jaxpr_extractor_sees_collectives_in_subjaxprs():
    import jax

    from horovod_tpu.optim.distributed_optimizer import make_train_step

    loss_fn, params, tx, batch = _toy()
    step = make_train_step(loss_fn, tx, microbatches=2, overlap=True)
    jaxpr = jax.make_jaxpr(lambda *a: step(*a))(params, tx.init(params),
                                                batch)
    seq = jaxpr_check.extract_collective_sequence(jaxpr)
    # 1 bucket x 2 microbatches reduce-scatter + 1 deferred all-gather
    # + the loss-mean psum, all nested under shard_map/scan/pjit.
    assert sum(1 for p in seq if "reduce_scatter" in p) == 2
    assert sum(1 for p in seq if "all_gather" in p) == 1


# --- observability tie-in ----------------------------------------------------

def test_lint_findings_metric_recorded():
    from horovod_tpu.analysis.core import Finding
    from horovod_tpu.obs import metrics as obs_metrics

    analysis.record_findings_metric([
        Finding("unknown-knob", "x.py", 1, "m"),
        Finding("unknown-knob", "y.py", 2, "m"),
        Finding("metric-name", "z.py", 3, "m"),
    ])
    snap = obs_metrics.registry().snapshot()
    series = {tuple(sorted(s["labels"].items())): s["value"]
              for s in snap["hvd_tpu_lint_findings_total"]}
    assert series[(("check", "unknown-knob"),)] >= 2
    assert series[(("check", "metric-name"),)] >= 1


# --- the gate ----------------------------------------------------------------

def test_repo_tree_is_clean():
    """THE acceptance invariant: zero unsuppressed findings over the
    shipped package.  Any future PR that introduces a rank-divergent
    collective, an undocumented knob, an unguarded mutation or catalog
    drift fails tier-1 right here."""
    findings = analysis.run(REPO)
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_repo_tree_clean_for_protocol_and_waits():
    """The two PR-13 static passes, scoped: every wire frame dispatched,
    paired, documented; every blocking call deadline-bound (or
    justified).  Group aliases exercise the --select expansion path."""
    findings = analysis.run(REPO, select=["protocol", "waits"])
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


def test_check_catalog_matches_docs():
    """docs/lint.md documents every check id (and no stale ones)."""
    doc = (REPO / "docs" / "lint.md").read_text()
    for check_id in analysis.CHECK_CATALOG:
        assert f"`{check_id}`" in doc, f"{check_id} missing from docs/lint.md"


def test_cli_exit_contract(tmp_path):
    """scripts/hvdlint.py: 0 on the clean tree + JSON artifact shape."""
    out = tmp_path / "lint.json"
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "hvdlint.py"),
         "--json", str(out)],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert payload["tool"] == "hvdlint"
    assert payload["findings"] == []
    assert payload["counts"] == {}


def test_cli_nonzero_on_findings(tmp_path):
    """A planted violation exits 1 and lands in the artifact."""
    pkg = tmp_path / "horovod_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "config.py").write_text(FIXTURE_CONFIG)
    (pkg / "c.py").write_text(FIXTURE_CONSUMER)
    (pkg / "bad.py").write_text(BAD_RANK_BRANCH)
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "env_vars.md").write_text(FIXTURE_ENV_DOC)
    (docs / "fault_injection.md").write_text(FIXTURE_FAULT_DOC)
    (docs / "metrics.md").write_text("")
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "hvdlint.py"),
         "--root", str(tmp_path), "--json", "-"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "rank-divergent-collective" in proc.stdout


# --- Pallas interpret-flag discipline ----------------------------------------

GOOD_PALLAS = """
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def copy(x, *, interpret=None):
    from horovod_tpu.ops.pallas_common import resolve_interpret
    return pl.pallas_call(_kern, out_shape=x,
                          interpret=resolve_interpret(interpret))(x)
"""

MISSING_INTERPRET = """
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def copy(x, *, interpret=None):
    return pl.pallas_call(_kern, out_shape=x)(x)
"""

HARDCODED_INTERPRET = """
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def copy(x, *, interpret=None):
    return pl.pallas_call(_kern, out_shape=x, interpret=True)(x)
"""

NO_PUBLIC_ESCAPE = """
from jax.experimental import pallas as pl

def _kern(x_ref, o_ref):
    o_ref[...] = x_ref[...]

def _copy(x, interpret):
    return pl.pallas_call(_kern, out_shape=x, interpret=interpret)(x)

def copy(x):
    return _copy(x, False)
"""


def test_pallas_interpret_threaded_ok(tmp_path):
    from horovod_tpu.analysis.pallas import PallasChecker

    assert lint(tmp_path, {"m.py": GOOD_PALLAS}, [PallasChecker]) == []


def test_pallas_interpret_missing(tmp_path):
    from horovod_tpu.analysis.pallas import PallasChecker

    fs = lint(tmp_path, {"m.py": MISSING_INTERPRET}, [PallasChecker])
    assert checks_of(fs) == ["pallas-interpret-flag"]
    assert "without interpret=" in fs[0].message


def test_pallas_interpret_hardcoded(tmp_path):
    from horovod_tpu.analysis.pallas import PallasChecker

    fs = lint(tmp_path, {"m.py": HARDCODED_INTERPRET}, [PallasChecker])
    assert checks_of(fs) == ["pallas-interpret-flag"]
    assert "hardcodes" in fs[0].message


def test_pallas_no_public_escape_hatch(tmp_path):
    from horovod_tpu.analysis.pallas import PallasChecker

    fs = lint(tmp_path, {"m.py": NO_PUBLIC_ESCAPE}, [PallasChecker])
    assert checks_of(fs) == ["pallas-interpret-flag"]
    assert "public" in fs[0].message


def test_pallas_modules_without_kernels_are_ignored(tmp_path):
    from horovod_tpu.analysis.pallas import PallasChecker

    src = "def pallas_call_lookalike(x):\n    return x\n"
    assert lint(tmp_path, {"m.py": src}, [PallasChecker]) == []


def test_pallas_check_in_default_set():
    from horovod_tpu import analysis
    from horovod_tpu.analysis.pallas import PallasChecker

    assert PallasChecker in analysis.default_checkers()


# --- the telemetry-plane alert catalog (ObservabilityChecker) ----------------

FIXTURE_DETECT = '''
DETECTORS = (
    ("never_shed_interactive", "page"),
    ("stuck_swap", "ticket"),
)
'''

FIXTURE_SLO = '''
def evaluate(clause):
    return {"alert": f"slo_burn:{clause}", "severity": "page"}
'''

FIXTURE_OBS_DOC = """
| alert | severity | meaning |
|---|---|---|
| `never_shed_interactive` | page | interactive lane starved |
| `stuck_swap` | ticket | weights roll wedged |

SLO violations page as `slo_burn:<slo>`.
"""


def test_observability_clean_fixture(tmp_path):
    fs = lint(tmp_path, {"obs/detect.py": FIXTURE_DETECT,
                         "obs/slo.py": FIXTURE_SLO},
              [ObservabilityChecker],
              docs={"observability.md": FIXTURE_OBS_DOC})
    assert checks_of(fs) == []


def test_observability_undocumented_detector(tmp_path):
    """A detector id with no row in the operator-facing catalog is a
    page nobody can act on."""
    doc = FIXTURE_OBS_DOC.replace("| `stuck_swap` | ticket |"
                                  " weights roll wedged |\n", "")
    fs = lint(tmp_path, {"obs/detect.py": FIXTURE_DETECT,
                         "obs/slo.py": FIXTURE_SLO},
              [ObservabilityChecker],
              docs={"observability.md": doc})
    assert checks_of(fs) == ["detector-doc-drift"]
    assert len(fs) == 1 and "stuck_swap" in fs[0].message


def test_observability_bad_severity(tmp_path):
    """A typo'd severity silently drops out of the paging pipeline."""
    bad = FIXTURE_DETECT.replace('"ticket"', '"warn"')
    doc = FIXTURE_OBS_DOC.replace("| ticket |", "| warn |")
    fs = lint(tmp_path, {"obs/detect.py": bad, "obs/slo.py": FIXTURE_SLO},
              [ObservabilityChecker],
              docs={"observability.md": doc})
    assert checks_of(fs) == ["alert-severity"]
    assert "warn" in fs[0].message


def test_observability_slo_burn_family_doc_drift(tmp_path):
    """obs/slo.py emits the slo_burn: family — the doc must describe
    it even though it is not a row in the DETECTORS catalog."""
    doc = FIXTURE_OBS_DOC.replace(
        "SLO violations page as `slo_burn:<slo>`.\n", "")
    fs = lint(tmp_path, {"obs/detect.py": FIXTURE_DETECT,
                         "obs/slo.py": FIXTURE_SLO},
              [ObservabilityChecker],
              docs={"observability.md": doc})
    assert checks_of(fs) == ["detector-doc-drift"]
    assert "slo_burn" in fs[0].message


def test_observability_check_in_default_set():
    assert ObservabilityChecker in analysis.default_checkers()
