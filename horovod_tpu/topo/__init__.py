"""Topology-aware collective scheduling.

The flat α–β planner in :mod:`horovod_tpu.ops.fusion` models the wire
as one link; the moment a job spans pods the wire is two — fast
intra-pod ICI and inter-pod DCN an order of magnitude slower in both
latency and bandwidth.  This subsystem owns everything the flat planner
cannot express (the GC3 "collective schedules as compiler output" and
"Collective Communication for 100k+ GPUs" directions in PAPERS.md):

* :mod:`.topology` — a declarative two-tier mesh description
  (pods × chips-per-pod, from ``HVD_TPU_TOPO_SPEC`` or inferred from
  ``jax.devices()``) with intra-/inter-tier process-set factories.
* :mod:`.costmodel` — per-tier α/β parameters with an online EWMA
  estimator fed by the ``obs/`` wire-byte and step-time signals
  (frozen under ``HVD_TPU_TOPO_COST_FREEZE``).
* :mod:`.schedule` — the compiler: per bucket, lower to flat allreduce,
  two-phase RS+AG, or hierarchical RS-intra → cross-pod exchange →
  AG-intra, chosen by modeled cost, emitted as a deterministic
  rank-invariant :class:`~horovod_tpu.topo.schedule.CollectiveSchedule`
  IR that ``ops/fusion.py`` executes (native twin:
  ``hvd_tpu_plan_hierarchical`` in ``native/src/planner.cc``).
* :mod:`.simulate` — a CPU multi-host mesh simulator (N simulated pods
  on one host via sub-axis process sets) so the equivalence and cost
  oracles run in tier-1.

See ``docs/topology.md`` for the mesh-spec grammar, the schedule IR,
the estimator, and the simulation recipe.
"""

from .topology import (MeshTopology, infer_topology, resolve_topology,
                       register_tier_process_sets)
from .costmodel import (TierParams, TopoCostParams, OnlineEstimator,
                        flat_cost_us, hierarchical_cost_us,
                        hierarchical_crossover_bytes, estimator)
from .schedule import (CollectiveSchedule, ScheduleStep, ScheduleCompiler,
                       choose_algo, compile_bucket_schedule,
                       execute_schedule, maybe_compiler)

__all__ = [
    "MeshTopology", "infer_topology", "resolve_topology",
    "register_tier_process_sets",
    "TierParams", "TopoCostParams", "OnlineEstimator", "flat_cost_us",
    "hierarchical_cost_us", "hierarchical_crossover_bytes", "estimator",
    "CollectiveSchedule", "ScheduleStep", "ScheduleCompiler",
    "choose_algo", "compile_bucket_schedule", "execute_schedule",
    "maybe_compiler",
]
