"""CPU multi-host mesh simulator: N simulated pods on one host.

Multi-pod hardware is exactly what CI doesn't have, so the equivalence
and cost oracles must run on the tier-1 CPU mesh (the 8 virtual devices
``tests/conftest.py`` forces).  A :class:`SimulatedMesh` overlays a
declared ``pods × chips`` topology on the real single-host mesh via the
sub-axis process-set partitions of
:meth:`~horovod_tpu.topo.topology.MeshTopology.intra_pod_groups` /
``cross_pod_groups`` — the collectives are the *same HLO group
partitions* a real two-tier deployment would trace, only the physical
links under them are loopback.  What the simulation therefore proves:
schedule correctness (bit-level equivalence against the flat wire,
rank-invariance, permutation inverses), never bandwidth — the cost
side is covered by the closed-form oracles of
:mod:`~horovod_tpu.topo.costmodel`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

from .costmodel import TopoCostParams, default_params
from .schedule import (ALGO_HIERARCHICAL, choose_algo,
                       compile_bucket_schedule, execute_schedule,
                       hierarchical_all_gather, hierarchical_reduce_scatter)
from .topology import MeshTopology


@dataclasses.dataclass(frozen=True)
class SimulatedMesh:
    """A two-tier topology overlaid on the live (single-host) global
    mesh; ``axis`` is the mesh axis every schedule executes over."""

    topo: MeshTopology
    axis: str


def simulated_mesh(pods: Optional[int] = None,
                   chips: Optional[int] = None) -> SimulatedMesh:
    """Build the simulation topology over the live world: ``pods ×
    chips`` must factor the world size (default: 2 pods of world/2
    chips — the smallest genuinely two-tier split)."""
    from .. import basics

    n = basics.size()
    if pods is None and chips is None:
        pods = 2 if n % 2 == 0 and n >= 4 else 1
    if pods is None:
        pods = n // int(chips)
    if chips is None:
        chips = n // int(pods)
    topo = MeshTopology(pods=int(pods), chips_per_pod=int(chips))
    if topo.size != n:
        raise ValueError(
            f"simulated topology {topo.describe()} does not factor the "
            f"{n}-slot mesh")
    return SimulatedMesh(topo=topo,
                         axis=basics.config().mesh_axis_name)


def run_allreduce(sim: SimulatedMesh, stack: np.ndarray, *,
                  algo: str = ALGO_HIERARCHICAL, op: str = "sum",
                  compression=None,
                  params: Optional[TopoCostParams] = None) -> np.ndarray:
    """Execute one compiled schedule over a per-slot data stack
    (``[size, elems]`` — slot *i* contributes row *i*) and return every
    slot's result stacked back ``[size, elems]``.  The vehicle the
    equivalence oracle and the bench share: the fused SPMD gradient
    wire (schedule execution inside ``shard_map``)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import basics
    from .._compat import shard_map
    from ..ops.compression import Compression

    compression = compression or Compression.none
    gm = basics.global_mesh()
    n = sim.topo.size
    stack = np.asarray(stack)
    if stack.shape[0] != n:
        raise ValueError(
            f"stack rows {stack.shape[0]} != mesh size {n}")
    sched = compile_bucket_schedule(
        int(stack.shape[-1] * stack.dtype.itemsize), sim.topo,
        params or default_params(), force=algo)

    def per_slot(xb):  # [1, elems] — this slot's contribution
        red = execute_schedule(xb[0], sched, axis=sim.axis, op=op,
                               compression=compression)
        return red[None].astype(xb.dtype)

    sharded = jax.device_put(
        stack, NamedSharding(gm.mesh, P(gm.axis_name)))
    out = jax.jit(shard_map(per_slot, mesh=gm.mesh,
                            in_specs=P(gm.axis_name),
                            out_specs=P(gm.axis_name)))(sharded)
    return np.asarray(out)


def run_rs_ag_roundtrip(sim: SimulatedMesh, stack: np.ndarray, *,
                        compression=None, op: str = "sum") -> np.ndarray:
    """The overlap wire's hierarchical RS → AG composition (shard
    permutation and its inverse): must equal the plain allreduce."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .. import basics
    from .._compat import shard_map
    from ..ops.compression import Compression

    compression = compression or Compression.none
    gm = basics.global_mesh()
    n = sim.topo.size
    stack = np.asarray(stack)
    elems = stack.shape[-1]
    sched = compile_bucket_schedule(int(elems * stack.dtype.itemsize),
                                    sim.topo, force=ALGO_HIERARCHICAL)

    def per_slot(xb):
        x = xb[0]
        pad = (-x.size) % n
        xp = (jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
              if pad else x)
        shard = hierarchical_reduce_scatter(
            xp, sched, axis=sim.axis, op=op, compression=compression)
        full = hierarchical_all_gather(
            shard, sched, axis=sim.axis, compression=compression)
        return full[: x.size][None].astype(xb.dtype)

    sharded = jax.device_put(
        stack, NamedSharding(gm.mesh, P(gm.axis_name)))
    out = jax.jit(shard_map(per_slot, mesh=gm.mesh,
                            in_specs=P(gm.axis_name),
                            out_specs=P(gm.axis_name)))(sharded)
    return np.asarray(out)


def cost_oracle_rows(sizes_bytes: Sequence[int], topo: MeshTopology,
                     params: Optional[TopoCostParams] = None
                     ) -> List[Dict]:
    """Modeled cost of every algorithm at every size plus the
    compiler's choice — the modeled-vs-chosen agreement surface the
    acceptance test and the ``--topology`` bench rows share."""
    from .costmodel import flat_cost_us, hierarchical_cost_us

    params = params or default_params()
    rows: List[Dict] = []
    for b in sizes_bytes:
        flat = flat_cost_us(b, topo, params)
        hier = hierarchical_cost_us(b, topo, params)
        rows.append({
            "bytes": int(b),
            "modeled_flat_us": flat,
            "modeled_hierarchical_us": hier,
            "chosen": choose_algo(int(b), topo, params),
        })
    return rows
