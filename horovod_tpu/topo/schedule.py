"""The schedule compiler: per-bucket lowering to a deterministic,
rank-invariant :class:`CollectiveSchedule` IR.

GC3 (PAPERS.md) argues collective schedules should be *compiler
output* — explicit, verifiable, chosen by a cost model — rather than
special cases inside the transport.  This module is that compiler for
the two-tier mesh: given a bucket's payload bytes, a
:class:`~horovod_tpu.topo.topology.MeshTopology` and per-tier α/β
(:mod:`~horovod_tpu.topo.costmodel`), :func:`compile_bucket_schedule`
emits one of

* ``flat`` — one allreduce over the whole mesh,
* ``two_phase`` — reduce-scatter → all-gather over the whole mesh (the
  PR-1 pipelined wire; picked for bandwidth-bound buckets on meshes
  where hierarchy doesn't pay),
* ``hierarchical`` — RS-intra (ICI) → cross-pod allreduce on only the
  sharded ``b/C`` fragment (DCN) → AG-intra (ICI),

as a tuple of ``(op, tier, groups, payload)`` :class:`ScheduleStep`\\ s.
The IR is pure bookkeeping over static values — every rank compiles the
identical schedule (asserted by hvdlint's jaxpr rank-invariance pass),
and the native twin ``hvd_tpu_plan_hierarchical`` mirrors the choice
bit-for-bit.

:func:`execute_schedule` runs a compiled schedule inside an SPMD region
on a compressor's wire; :func:`hierarchical_reduce_scatter` /
:func:`hierarchical_all_gather` are the RS/AG halves the overlap
microbatch wire composes (shards come back pod-major-permuted, and the
matching AG inverts the permutation — flat-equivalent end to end).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .costmodel import (TopoCostParams, default_params, estimator,
                        flat_cost_us, hierarchical_cost_us,
                        hierarchical_phase_costs_us)
from .topology import MeshTopology, config_topology

Groups = Optional[Tuple[Tuple[int, ...], ...]]

ALGO_FLAT, ALGO_TWO_PHASE, ALGO_HIERARCHICAL = "flat", "two_phase", \
    "hierarchical"

# Lowering backends for a schedule's steps.  ``spmd`` is the HLO wire
# (quantize / collective / dequantize as separate XLA regions);
# ``pallas`` lowers int8-compressed ICI steps to the fused kernels in
# ``ops/pallas_collectives.py`` (quantize-pack feeding the collective
# operand directly, dequantize fused into the consumer).  DCN steps
# and uncompressed wires keep the SPMD path under either backend —
# the fusion win is the HBM round-trip around the quantize math, which
# only the int8 intra-tier steps have.
KERNEL_SPMD, KERNEL_PALLAS = "spmd", "pallas"
KERNELS = (KERNEL_SPMD, KERNEL_PALLAS)


@dataclasses.dataclass(frozen=True)
class ScheduleStep:
    """One step of the IR: ``op`` ∈ {rs, ar, ag}, the tier whose wire
    it rides, the ``axis_index_groups`` partition it reduces over
    (None = whole axis), and the payload bytes it moves."""

    op: str
    tier: str
    groups: Groups
    payload_bytes: int


@dataclasses.dataclass(frozen=True)
class CollectiveSchedule:
    """A compiled per-bucket schedule: the algorithm, its steps, the
    modeled cost, and the topology it was compiled for.  Frozen and
    built from static values only — rank-invariant by construction."""

    algo: str
    steps: Tuple[ScheduleStep, ...]
    nbytes: int
    est_cost_us: float
    topo: MeshTopology
    kernel: str = KERNEL_SPMD

    def tier_bytes(self) -> Dict[str, int]:
        """Wire bytes per tier (exact dtype bytes; the executor scales
        by the compressor's wire ratio when recording)."""
        out: Dict[str, int] = {}
        for s in self.steps:
            out[s.tier] = out.get(s.tier, 0) + s.payload_bytes
        return out

    def hbm_materializations(self, compression) -> int:
        """Structural accounting for the recorded plan: standalone HBM
        intermediates the executor materializes around this schedule's
        collectives on the compressed wire.  The unfused SPMD int8 path
        writes the quantized payload before the collective and the
        dequantized buffer after it — 2 per rs/ag step, 4 per ar (the
        transport runs RS+AG internally).  The fused Pallas backend
        produces the wire operands inside the quantize kernel and
        consumes them inside the dequantize/apply kernel, so compressed
        ICI steps add none; DCN steps keep the SPMD path under either
        backend.  Uncompressed wires have no quantize stage to count.
        This is the TPU-speedup assertion the CPU bench can't measure:
        fewer HBM round-trips per step, counted in the plan itself."""
        if not _is_int8(compression):
            return 0
        total = 0
        for s in self.steps:
            if self.kernel == KERNEL_PALLAS and s.tier == "ici":
                continue
            total += 4 if s.op == "ar" else 2
        return total


def _is_int8(compression) -> bool:
    """Whether ``compression`` is the int8 transport (the only wire
    with quantize/dequantize stages the Pallas backend can fuse).
    Compressors travel as classes (``Compression.int8``), but accept
    instances too."""
    from ..ops.compression import Int8Compressor

    if compression is None:
        return False
    if isinstance(compression, type):
        return issubclass(compression, Int8Compressor)
    return isinstance(compression, Int8Compressor)


def choose_algo(nbytes: int, topo: MeshTopology,
                params: TopoCostParams) -> str:
    """The modeled-cost decision, mirrored exactly by the native
    ``hvd_tpu_plan_hierarchical`` (equivalence property-tested in
    tests/test_topo.py): hierarchical when its modeled makespan beats
    flat's on a genuinely two-tier mesh; otherwise the flat family,
    decomposed into RS+AG when the bucket clears the two-phase
    crossover at the flat wire's effective parameters (α_ici paired
    with the bottleneck β — DCN on multi-pod meshes)."""
    n = topo.size
    if n <= 1:
        return ALGO_FLAT
    if topo.two_tier and hierarchical_cost_us(nbytes, topo, params) \
            < flat_cost_us(nbytes, topo, params):
        return ALGO_HIERARCHICAL
    beta_eff = (params.dcn.beta_gbps if topo.pods > 1
                else params.ici.beta_gbps)
    crossover_d = params.ici.alpha_us * beta_eff * 1e3 * n
    if crossover_d < 9.2e18 and nbytes >= int(crossover_d):
        return ALGO_TWO_PHASE
    return ALGO_FLAT


def _dispatch_algo(nbytes: int, topo: MeshTopology,
                   params: TopoCostParams) -> str:
    """Native-planner dispatch for :func:`choose_algo` (same contract;
    mirrors ``ops.fusion.plan_buckets``' dispatch discipline)."""
    use_native = True
    from .. import basics

    if basics.is_initialized():
        use_native = basics.config().use_native_planner
    if use_native:
        try:
            from ..native import planner as _native

            if _native.available():
                return _native.plan_hierarchical(
                    [int(nbytes)], topo.pods, topo.chips_per_pod,
                    params.ici.alpha_us, params.ici.beta_gbps,
                    params.dcn.alpha_us, params.dcn.beta_gbps)[0]
        except ImportError:
            pass
    return choose_algo(nbytes, topo, params)


def compile_bucket_schedule(nbytes: int, topo: MeshTopology,
                            params: Optional[TopoCostParams] = None, *,
                            force: Optional[str] = None,
                            kernel: str = KERNEL_SPMD,
                            ) -> CollectiveSchedule:
    """Compile one bucket's schedule.  ``force`` pins the algorithm
    (the autotuner's and the bench's explicit lattice points); None
    lets the cost model choose (``auto``).  ``kernel`` selects the
    lowering backend recorded in the IR (spmd | pallas); the executor
    applies it per step — only int8-compressed ICI steps fuse."""
    if kernel not in KERNELS:
        raise ValueError(f"kernel must be one of {KERNELS}, got {kernel!r}")
    params = params or default_params()
    algo = force if force in (ALGO_FLAT, ALGO_TWO_PHASE,
                              ALGO_HIERARCHICAL) else \
        _dispatch_algo(nbytes, topo, params)
    if algo == ALGO_HIERARCHICAL and not topo.two_tier:
        algo = ALGO_FLAT   # nothing to hierarchize on a one-tier mesh
    n = topo.size
    flat_tier = "dcn" if topo.pods > 1 else "ici"
    nbytes = int(nbytes)
    if algo == ALGO_HIERARCHICAL:
        intra = tuple(tuple(g) for g in topo.intra_pod_groups())
        cross = tuple(tuple(g) for g in topo.cross_pod_groups())
        frag = nbytes // topo.chips_per_pod
        steps = (
            ScheduleStep("rs", "ici", intra, nbytes),
            ScheduleStep("ar", "dcn", cross, frag),
            ScheduleStep("ag", "ici", intra, nbytes),
        )
        cost = hierarchical_cost_us(nbytes, topo, params)
    elif algo == ALGO_TWO_PHASE:
        steps = (ScheduleStep("rs", flat_tier, None, nbytes),
                 ScheduleStep("ag", flat_tier, None, nbytes))
        cost = flat_cost_us(nbytes, topo, params)
    else:
        steps = (ScheduleStep("ar", flat_tier, None, nbytes),)
        cost = flat_cost_us(nbytes, topo, params)
    return CollectiveSchedule(algo=algo, steps=steps, nbytes=nbytes,
                              est_cost_us=cost, topo=topo, kernel=kernel)


class ScheduleCompiler:
    """A compile cache bound to one (topology, params, force) point —
    what ``fused_apply``/``fused_two_phase_apply``/the overlap wire
    accept.  Compilation happens at trace time; the cache keeps
    re-traces cheap and deterministic."""

    def __init__(self, topo: MeshTopology,
                 params: Optional[TopoCostParams] = None,
                 force: Optional[str] = None,
                 kernel: str = KERNEL_SPMD) -> None:
        self.topo = topo
        self.params = params or default_params()
        self.force = force
        self.kernel = kernel
        self._cache: Dict[int, CollectiveSchedule] = {}

    def compile(self, nbytes: int) -> CollectiveSchedule:
        nbytes = int(nbytes)
        sched = self._cache.get(nbytes)
        if sched is None:
            sched = self._cache[nbytes] = compile_bucket_schedule(
                nbytes, self.topo, self.params, force=self.force,
                kernel=self.kernel)
        return sched


def maybe_compiler(world_size: int, groups=None,
                   mode: Optional[str] = None,
                   kernel: Optional[str] = None,
                   ) -> Optional[ScheduleCompiler]:
    """Trace-time resolution of the topo scheduling gate: a compiler
    when ``HVD_TPU_TOPO_SCHEDULE`` (or an explicit ``mode``) turns it
    on AND the reduction runs over the whole mesh (process-set
    sub-reductions keep the flat wire — tier groups are defined on the
    global axis) AND the resolved topology matches the group width.
    Returns None otherwise — callers fall back to the flat planner.
    ``kernel`` overrides the lowering backend; None reads
    ``HVD_TPU_TOPO_KERNEL`` (the autotuner's ``topo_kernel`` knob
    rewrites that config field between traces)."""
    if mode is None or kernel is None:
        from .. import basics

        cfg = basics.config() if basics.is_initialized() else None
        if mode is None:
            mode = cfg.topo_schedule if cfg is not None else "off"
        if kernel is None:
            kernel = cfg.topo_kernel if cfg is not None else KERNEL_SPMD
    if mode == "off" or groups is not None or world_size <= 1:
        return None
    topo = config_topology(world_size)
    if topo.size != world_size:
        return None
    force = None if mode == "auto" else mode
    return ScheduleCompiler(topo, estimator().effective_params(),
                            force=force, kernel=kernel)


# --- execution ---------------------------------------------------------------
# Everything below runs at trace time inside an SPMD region: the spans
# wrap schedule *emission* (like the `fusion` fault site, a failure
# here surfaces while the program is being built), and the compiled
# program replays the emitted collectives every step.

def _groups_list(groups: Groups):
    return [list(g) for g in groups] if groups is not None else None


def record_plans(scheds: Sequence[CollectiveSchedule], compression,
                 itemsize: int,
                 params: Optional[TopoCostParams] = None) -> None:
    """Trace-time plan record for a set of compiled per-bucket
    schedules: per-tier wire bytes, per-tier modeled cost, per-kernel
    backend counts and the plan's structural HBM-materialization count
    into the obs registry (``hvd_tpu_topo_*``; docs/metrics.md), plus
    the per-tier byte note the online estimator refines β from.  Bytes are
    scaled by the compressor's wire ratio, like every fusion-tier
    record.  ``params`` must be the point the schedules were compiled
    with (the caller's ``ScheduleCompiler.params``) so the published
    per-tier costs stay consistent with each schedule's own
    ``est_cost_us`` once the estimator has refined."""
    from ..obs import instrument as _obs
    from ..ops.fusion import wire_ratio

    scheds = list(scheds)
    if not scheds:
        return
    ratio = wire_ratio(compression, max(itemsize, 1))
    params = params or default_params()
    tier_bytes: Dict[str, int] = {}
    tier_cost: Dict[str, float] = {}
    by_algo: Dict[str, int] = {}
    by_kernel: Dict[str, int] = {}
    hbm_mats = 0
    for sched in scheds:
        by_algo[sched.algo] = by_algo.get(sched.algo, 0) + 1
        by_kernel[sched.kernel] = by_kernel.get(sched.kernel, 0) + 1
        hbm_mats += sched.hbm_materializations(compression)
        for t, b in sched.tier_bytes().items():
            tier_bytes[t] = tier_bytes.get(t, 0) + int(b * ratio)
        if sched.algo == ALGO_HIERARCHICAL:
            phase = hierarchical_phase_costs_us(sched.nbytes, sched.topo,
                                                params)
            tier_cost["ici"] = tier_cost.get("ici", 0.0) \
                + phase["rs_intra"] + phase["ag_intra"]
            tier_cost["dcn"] = tier_cost.get("dcn", 0.0) + phase["xpod"]
        else:
            t = "dcn" if sched.topo.pods > 1 else "ici"
            tier_cost[t] = tier_cost.get(t, 0.0) + sched.est_cost_us
    if _obs.enabled():
        _obs.on_topo_plan(by_algo, tier_bytes=tier_bytes,
                          est_cost_us=tier_cost, kernels=by_kernel,
                          hbm_materializations=hbm_mats)
    estimator().note_plan(tier_bytes)


def _on_dcn_step(stage: str) -> None:
    from .. import faults as _faults

    if _faults._active is not None:
        _faults.on_dcn(stage)


def _step_fused(sched: CollectiveSchedule, kernel: Optional[str],
                compression, tier: str) -> bool:
    """Per-step backend selection: a step lowers to the fused Pallas
    kernels only when the pallas backend is active (explicit override
    wins, else the schedule's recorded ``kernel``), the step rides the
    intra tier (DCN steps keep the SPMD path), and the wire is the int8
    transport (the only one with quantize stages to fuse).  The fused
    kernels are bit-identical to the SPMD wire, so mixing backends
    across steps cannot change the result."""
    k = kernel if kernel is not None else sched.kernel
    return k == KERNEL_PALLAS and tier == "ici" and _is_int8(compression)


def execute_schedule(x, sched: CollectiveSchedule, *, axis: str, op: str,
                     compression, kernel: Optional[str] = None,
                     ) -> "jax.Array":
    """Run one compiled schedule over a flat 1-D buffer inside an SPMD
    region: allreduce semantics (every slot returns the full reduction
    over the whole mesh), on the compressor's wire.  ``op`` is
    sum/average.  ``kernel`` overrides the schedule's recorded lowering
    backend (the bench's explicit axis); None honors the IR."""
    import jax.numpy as jnp

    from ..obs import trace as _trace

    if op not in ("sum", "average"):
        raise ValueError(
            f"topo schedules support op=sum/average, got {op!r}")
    n = sched.topo.size
    if n <= 1 or sched.algo == ALGO_FLAT:
        if _step_fused(sched, kernel, compression, sched.steps[0].tier) \
                and n > 1:
            from ..ops import pallas_collectives as _pc

            return _pc.fused_allreduce(x, op=op, axis=axis, groups=None)
        return compression.spmd_allreduce(x, op=op, axis=axis, groups=None)
    if sched.algo == ALGO_TWO_PHASE:
        pad = (-x.size) % n
        xp = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)]) if pad else x
        if _step_fused(sched, kernel, compression, sched.steps[0].tier):
            from ..ops import pallas_collectives as _pc

            shard = _pc.fused_quantize_reducescatter(xp, op=op, axis=axis,
                                                     groups=None)
            full = _pc.fused_quantize_allgather(shard, axis=axis,
                                                groups=None)
        else:
            shard = compression.spmd_reducescatter(xp, op=op, axis=axis,
                                                   groups=None)
            full = compression.spmd_allgather(shard, axis=axis, groups=None)
        return full[: x.size]
    # hierarchical: RS-intra (ICI) -> cross-pod exchange on the sharded
    # fragment (DCN) -> AG-intra (ICI).  Internal reductions run op=sum;
    # one exact division by the full mesh width lands at the end so the
    # result matches the flat wire's average bit-for-bit on exact data.
    # Under kernel=pallas the two ICI steps lower to the fused kernels
    # (bit-identical wire); the DCN step keeps the SPMD path.
    intra = _groups_list(sched.steps[0].groups)
    cross = _groups_list(sched.steps[1].groups)
    fuse_intra = _step_fused(sched, kernel, compression, "ici")
    if fuse_intra:
        from ..ops import pallas_collectives as _pc
    pad = (-x.size) % n
    xp = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)]) if pad else x
    with _trace.span("hvd_tpu_topo_rs_intra",
                     args={"bytes": sched.steps[0].payload_bytes,
                           "kernel": "pallas" if fuse_intra else "spmd"}):
        if fuse_intra:
            frag = _pc.fused_quantize_reducescatter(xp, op="sum", axis=axis,
                                                    groups=intra)
        else:
            frag = compression.spmd_reducescatter(xp, op="sum", axis=axis,
                                                  groups=intra)
    _on_dcn_step("xpod")
    with _trace.span("hvd_tpu_topo_xpod",
                     args={"bytes": sched.steps[1].payload_bytes}):
        frag = compression.spmd_allreduce(frag, op="sum", axis=axis,
                                          groups=cross)
    with _trace.span("hvd_tpu_topo_ag_intra",
                     args={"bytes": sched.steps[2].payload_bytes,
                           "kernel": "pallas" if fuse_intra else "spmd"}):
        if fuse_intra:
            full = _pc.fused_quantize_allgather(frag, axis=axis,
                                                groups=intra)
        else:
            full = compression.spmd_allgather(frag, axis=axis, groups=intra)
    out = full[: x.size]
    if op == "average":
        out = out / n
    return out


def hierarchical_reduce_scatter(x, sched: CollectiveSchedule, *,
                                axis: str, op: str, compression,
                                kernel: Optional[str] = None):
    """The RS half for the overlap microbatch wire: RS-intra (ICI) then
    RS across pods (DCN) on the fragment.  ``x`` must already be padded
    to the mesh width; returns this slot's ``x.size/n`` shard.  Shards
    come back in (chip, pod)-major order — a fixed permutation of the
    flat RS layout that :func:`hierarchical_all_gather` inverts, so
    accumulate-then-gather is flat-equivalent.  Under ``kernel=pallas``
    (explicit, or the schedule's recorded backend) the ICI step lowers
    to the fused quantize→RS kernel; the DCN step keeps SPMD."""
    from ..obs import trace as _trace

    n = sched.topo.size
    intra = _groups_list(sched.steps[0].groups)
    cross = _groups_list(sched.steps[1].groups)
    fuse_intra = _step_fused(sched, kernel, compression, "ici")
    with _trace.span("hvd_tpu_topo_rs_intra",
                     args={"bytes": sched.steps[0].payload_bytes,
                           "kernel": "pallas" if fuse_intra else "spmd"}):
        if fuse_intra:
            from ..ops import pallas_collectives as _pc

            frag = _pc.fused_quantize_reducescatter(x, op="sum", axis=axis,
                                                    groups=intra)
        else:
            frag = compression.spmd_reducescatter(x, op="sum", axis=axis,
                                                  groups=intra)
    _on_dcn_step("xpod_rs")
    with _trace.span("hvd_tpu_topo_xpod",
                     args={"bytes": sched.steps[1].payload_bytes}):
        shard = compression.spmd_reducescatter(frag, op="sum", axis=axis,
                                               groups=cross)
    if op == "average":
        shard = shard / n
    return shard


def hierarchical_all_gather(shard, sched: CollectiveSchedule, *,
                            axis: str, compression,
                            kernel: Optional[str] = None):
    """The AG half: gather across pods (DCN) to rebuild the fragment,
    then AG-intra (ICI) to rebuild the full padded buffer — the exact
    inverse of :func:`hierarchical_reduce_scatter`'s permutation.
    Under ``kernel=pallas`` the ICI gather lowers to the fused
    AG→dequantize kernel; the DCN step keeps SPMD."""
    from ..obs import trace as _trace

    intra = _groups_list(sched.steps[0].groups)
    cross = _groups_list(sched.steps[1].groups)
    fuse_intra = _step_fused(sched, kernel, compression, "ici")
    _on_dcn_step("xpod_ag")
    with _trace.span("hvd_tpu_topo_xpod",
                     args={"bytes": sched.steps[1].payload_bytes}):
        frag = compression.spmd_allgather(shard, axis=axis, groups=cross)
    with _trace.span("hvd_tpu_topo_ag_intra",
                     args={"bytes": sched.steps[2].payload_bytes,
                           "kernel": "pallas" if fuse_intra else "spmd"}):
        if fuse_intra:
            from ..ops import pallas_collectives as _pc

            full = _pc.fused_quantize_allgather(frag, axis=axis,
                                                groups=intra)
        else:
            full = compression.spmd_allgather(frag, axis=axis, groups=intra)
    return full
