"""Declarative two-tier mesh description (pods × chips-per-pod).

The reference stack discovers topology implicitly — NCCL rings within a
node, MPI across nodes, glued by ``HOROVOD_HIERARCHICAL_ALLREDUCE``.
Here the topology is a *value*: a :class:`MeshTopology` either declared
via ``HVD_TPU_TOPO_SPEC=PODSxCHIPS`` or inferred from the slice/process
structure of ``jax.devices()``, consumed by the cost model and the
schedule compiler.  Pods are contiguous ranges of the 1-D mesh axis
(slot ``r`` lives in pod ``r // chips_per_pod`` at chip ``r %
chips_per_pod``) — the layout :mod:`horovod_tpu.mesh` builds, where
devices enumerate process-major.

The tier *process sets* are plain ``axis_index_groups`` partitions
(the same mechanism :mod:`horovod_tpu.process_sets` uses): the
intra-pod tier partitions the axis into ``pods`` groups of
``chips_per_pod`` slots (ICI-local reductions), the cross-pod tier into
``chips_per_pod`` groups of ``pods`` slots — one group per chip index,
so each group's collective moves only the fragment that chip owns
across DCN.  Both are full partitions, so XLA accepts them directly.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from ..config import parse_topo_spec
from ..utils.logging import get_logger

logger = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class MeshTopology:
    """A two-tier mesh: ``pods`` × ``chips_per_pod`` slots, pods laid
    out contiguously along the 1-D mesh axis.  ``pods == 1`` is the
    flat (single-tier) degenerate every single-pod job resolves to."""

    pods: int
    chips_per_pod: int

    def __post_init__(self) -> None:
        if self.pods < 1 or self.chips_per_pod < 1:
            raise ValueError(
                f"MeshTopology factors must be >= 1, got "
                f"{self.pods}x{self.chips_per_pod}")

    @property
    def size(self) -> int:
        return self.pods * self.chips_per_pod

    @property
    def two_tier(self) -> bool:
        """Does a hierarchical schedule even exist on this mesh?  Needs
        both tiers to be non-trivial."""
        return self.pods > 1 and self.chips_per_pod > 1

    def pod_of(self, rank: int) -> int:
        return rank // self.chips_per_pod

    def chip_of(self, rank: int) -> int:
        return rank % self.chips_per_pod

    def intra_pod_groups(self) -> List[List[int]]:
        """ICI tier: one group per pod — a full partition of the axis,
        directly usable as ``axis_index_groups``."""
        c = self.chips_per_pod
        return [list(range(p * c, (p + 1) * c)) for p in range(self.pods)]

    def cross_pod_groups(self) -> List[List[int]]:
        """DCN tier: one group per chip index — slot ``p·C + c`` talks
        to its peers at the same chip index ``c`` in every other pod,
        so each group's collective carries only that chip's fragment."""
        c = self.chips_per_pod
        return [[p * c + i for p in range(self.pods)] for i in range(c)]

    def describe(self) -> str:
        return f"{self.pods}x{self.chips_per_pod}"


def infer_topology(devices=None) -> MeshTopology:
    """Infer the two-tier structure from the device list: group devices
    (in mesh order) by their slice — ``slice_index`` where the backend
    exposes it (multi-slice TPU), else ``process_index`` (one pod per
    host process, the DCN boundary in multi-controller worlds).  Groups
    must be contiguous and uniform to be a topology; anything else
    falls back to the flat 1×N degenerate."""
    import jax

    devices = list(devices) if devices is not None else list(jax.devices())
    n = len(devices)
    if n <= 1:
        return MeshTopology(pods=1, chips_per_pod=max(1, n))

    def slice_of(d) -> int:
        s = getattr(d, "slice_index", None)
        if s is None:
            s = getattr(d, "process_index", 0)
        return int(s)

    # Contiguous runs of equal slice id, in mesh (device-list) order.
    runs: List[Tuple[int, int]] = []   # (slice_id, run_length)
    for d in devices:
        s = slice_of(d)
        if runs and runs[-1][0] == s:
            runs[-1] = (s, runs[-1][1] + 1)
        else:
            runs.append((s, 1))
    lengths = {length for _, length in runs}
    ids = [s for s, _ in runs]
    if (len(runs) > 1 and len(lengths) == 1 and len(set(ids)) == len(ids)
            and next(iter(lengths)) > 1):
        return MeshTopology(pods=len(runs), chips_per_pod=runs[0][1])
    return MeshTopology(pods=1, chips_per_pod=n)


def resolve_topology(world_size: int,
                     spec: Optional[str] = None) -> MeshTopology:
    """The topology for a ``world_size``-slot mesh: a declared spec wins
    (validated against the world — a spec that doesn't factor the mesh
    is a deployment error, not something to guess around), otherwise
    inference, otherwise flat."""
    if spec:
        pods, chips = parse_topo_spec(spec)
        if pods * chips != world_size:
            raise ValueError(
                f"topo spec {spec!r} declares {pods * chips} slots but "
                f"the mesh has {world_size}")
        return MeshTopology(pods=pods, chips_per_pod=chips)
    topo = infer_topology()
    if topo.size != world_size:
        # The device list the inference saw is not this reduction's
        # group (e.g. a process-set sub-world): stay flat.
        return MeshTopology(pods=1, chips_per_pod=world_size)
    return topo


def config_topology(world_size: int) -> MeshTopology:
    """Trace-time resolution from the live config (``HVD_TPU_TOPO_SPEC``),
    falling back to flat on a spec/world mismatch with a warning —
    a bad spec must not crash a training step that can run flat.

    Between the declared spec and inference sits the session
    :class:`~horovod_tpu.plan.MeshPlan`: a 2-D reduce layout
    (``data=P,fsdp=C``) *is* a tier declaration — outer axis = pod
    (DCN) tier, inner = chip (ICI) tier — so the schedule compiler's
    partitions derive from the plan without a separate topo spec."""
    from .. import basics

    spec = basics.config().topo_spec if basics.is_initialized() else None
    if not spec:
        plan = basics.peek("mesh_plan")
        if plan is not None:
            tiers = plan.topo_tiers()
            if tiers is not None and tiers.size == world_size:
                return tiers
    try:
        return resolve_topology(world_size, spec)
    except ValueError as e:
        logger.warning("ignoring HVD_TPU_TOPO_SPEC (%s); running flat", e)
        return MeshTopology(pods=1, chips_per_pod=world_size)


def register_tier_process_sets(topo: MeshTopology):
    """Register (or find — idempotent) one :class:`ProcessSet` per
    intra-pod group and per cross-pod group on the live table, layered
    on :mod:`horovod_tpu.process_sets`.  Returns ``(intra_sets,
    cross_sets)``.  The schedule executor itself passes raw
    ``axis_index_groups`` (no registration needed inside jit); these
    sets are for callers that want the reference-parity API surface —
    ``ps.rank()``/``ps.size()``/host-tier collectives over one tier."""
    from ..process_sets import ProcessSet, add_process_set, _table

    def _ensure(ranks) -> ProcessSet:
        existing = _table().find(ranks)
        return existing if existing is not None \
            else add_process_set(ProcessSet(ranks))

    intra = [_ensure(g) for g in topo.intra_pod_groups()]
    cross = [_ensure(g) for g in topo.cross_pod_groups()]
    return intra, cross
