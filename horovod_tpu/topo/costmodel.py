"""Per-tier α–β cost model for two-tier ICI×DCN meshes.

Extends the flat α–β model of :mod:`horovod_tpu.ops.fusion` (per-hop
launch latency α, per-hop bandwidth β) to two tiers with separate
parameters.  The structural claims, with ``n = P·C`` slots in ``P``
pods of ``C`` chips:

* **Flat allreduce** is ONE compiled collective whose ring steps
  pipeline neighbor-to-neighbor: per-hop launch stays at the fast
  tier's α, but every ring step moves payload through the pod-boundary
  links, so the transfer term runs at the DCN β —
  ``2(n−1)·(α_ici + (b/n)/β_dcn)`` (single-pod meshes degrade to the
  familiar all-ICI form).
* **Hierarchical** (RS-intra → cross-pod exchange on the sharded
  fragment → AG-intra) pays two ICI phases on the full payload plus a
  DCN allreduce on only the ``b/C`` fragment — but its cross-pod stage
  is a separate collective whose every hop spans DCN, so each of its
  ``2(P−1)`` hops costs the full α_dcn.

Small buckets are therefore latency-bound and stay flat whenever
``C·α_ici < α_dcn`` (the extra DCN launches outweigh the saved ICI
hops); large buckets go hierarchical because DCN moves ``C×`` fewer
bytes.  The crossover is closed-form
(:func:`hierarchical_crossover_bytes`) and oracle-tested.

The **online estimator** refines the per-tier β from the signals the
``obs/`` layer already publishes: each compiled schedule notes its
per-tier planned wire bytes (trace time), each finished step
contributes ``bytes/µs`` per tier, EWMA'd into an achieved-bandwidth
floor.  ``HVD_TPU_TOPO_COST_FREEZE=1`` pins the parameters (a tuned
fleet must not drift mid-run).  Refined parameters feed the compiler
only on single-controller worlds — per-process estimators see
different wall clocks, and divergent parameters would compile
divergent collective programs (the deadlock hvdlint exists to catch);
multi-controller refinement publishes gauges for operators but the
compiler stays on the declared priors.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, Optional

from ..config import DEFAULT_COST_ALPHA_US, DEFAULT_COST_BETA_GBPS
from .topology import MeshTopology

TIERS = ("ici", "dcn")


@dataclasses.dataclass(frozen=True)
class TierParams:
    """One tier's α–β point: per-hop launch latency (µs) and per-hop
    bandwidth (GB/s)."""

    alpha_us: float
    beta_gbps: float

    @property
    def beta_bytes_per_us(self) -> float:
        return self.beta_gbps * 1e3  # GB/s == 10^3 B/µs


@dataclasses.dataclass(frozen=True)
class TopoCostParams:
    """The model: one :class:`TierParams` per tier."""

    ici: TierParams
    dcn: TierParams

    def tier(self, name: str) -> TierParams:
        if name == "ici":
            return self.ici
        if name == "dcn":
            return self.dcn
        raise ValueError(f"unknown tier {name!r}; expected one of {TIERS}")


def default_params() -> TopoCostParams:
    """Priors from the live config: the ICI tier reuses the flat
    model's ``HVD_TPU_COST_ALPHA_US``/``COST_BETA_GBPS`` (they were
    always intra-slice numbers), the DCN tier gets its own
    ``HVD_TPU_TOPO_ALPHA_DCN_US``/``TOPO_BETA_DCN_GBPS`` — an order of
    magnitude worse by default, matching the ICI/DCN gap."""
    from .. import basics

    if basics.is_initialized():
        cfg = basics.config()
        return TopoCostParams(
            ici=TierParams(cfg.cost_alpha_us, cfg.cost_beta_gbps),
            dcn=TierParams(cfg.topo_alpha_dcn_us, cfg.topo_beta_dcn_gbps))
    return TopoCostParams(
        ici=TierParams(DEFAULT_COST_ALPHA_US, DEFAULT_COST_BETA_GBPS),
        dcn=TierParams(DEFAULT_COST_ALPHA_US * 10.0,
                       DEFAULT_COST_BETA_GBPS / 10.0))


def tier_phase_cost_us(nbytes: float, n: int, p: TierParams) -> float:
    """One RS/AG phase of a ring collective over ``n`` participants on
    one tier — the per-tier form of ``fusion.phase_cost_us``.  Written
    to be bit-reproducible against the native twin
    (``hvd_tpu_plan_hierarchical``): same operation order, double
    arithmetic throughout."""
    if n <= 1:
        return 0.0
    return (n - 1) * (p.alpha_us + (nbytes / n) / (p.beta_gbps * 1e3))


def flat_cost_us(nbytes: float, topo: MeshTopology,
                 params: TopoCostParams) -> float:
    """Modeled makespan of one flat allreduce over the whole mesh (see
    module docstring for the launch-vs-transfer split)."""
    n = topo.size
    if n <= 1:
        return 0.0
    if topo.pods > 1:
        return 2.0 * (n - 1) * (
            params.ici.alpha_us
            + (nbytes / n) / (params.dcn.beta_gbps * 1e3))
    return 2.0 * tier_phase_cost_us(nbytes, n, params.ici)


def hierarchical_cost_us(nbytes: float, topo: MeshTopology,
                         params: TopoCostParams) -> float:
    """Modeled makespan of the hierarchical schedule: RS-intra +
    AG-intra on the full payload over ICI, one allreduce on the ``b/C``
    fragment over DCN."""
    if not topo.two_tier:
        return flat_cost_us(nbytes, topo, params)
    intra = 2.0 * tier_phase_cost_us(nbytes, topo.chips_per_pod,
                                     params.ici)
    frag = nbytes / topo.chips_per_pod
    cross = 2.0 * tier_phase_cost_us(frag, topo.pods, params.dcn)
    return intra + cross


def hierarchical_phase_costs_us(nbytes: float, topo: MeshTopology,
                                params: TopoCostParams
                                ) -> Dict[str, float]:
    """Per-phase breakdown ``{rs_intra, xpod, ag_intra}`` — the numbers
    the obs layer publishes per tier and the bench rows carry."""
    if not topo.two_tier:
        return {"rs_intra": 0.0,
                "xpod": flat_cost_us(nbytes, topo, params),
                "ag_intra": 0.0}
    intra = tier_phase_cost_us(nbytes, topo.chips_per_pod, params.ici)
    frag = nbytes / topo.chips_per_pod
    return {"rs_intra": intra,
            "xpod": 2.0 * tier_phase_cost_us(frag, topo.pods, params.dcn),
            "ag_intra": intra}


def hierarchical_crossover_bytes(topo: MeshTopology,
                                 params: TopoCostParams) -> int:
    """Bucket payload above which the hierarchical schedule beats flat,
    in closed form.  Setting ``flat(b) = hier(b)`` and solving:

    * latency gap at b→0: ``2(P−1)·(C·α_ici − α_dcn)`` (flat − hier)
    * slope gap: ``2·(C−1)/C · (1/β'_dcn − 1/β'_ici)`` per byte

    The contract is "the payload at and above which hierarchical wins":
    0 when it wins at every size (``C·α_ici ≥ α_dcn`` with DCN the
    per-byte bottleneck), ``1 << 62`` when no such payload exists —
    including the inverted-tier corner (``β_dcn ≥ β_ici``) where
    hierarchy can only win *below* a boundary; ``choose_algo`` compares
    the costs directly and stays correct there, this closed form just
    declines to report a threshold that isn't one."""
    if not topo.two_tier:
        return 1 << 62
    P, C = topo.pods, topo.chips_per_pod
    lat_gap = 2.0 * (P - 1) * (C * params.ici.alpha_us
                               - params.dcn.alpha_us)
    slope_gap = 2.0 * ((C - 1) / C) * (
        1.0 / params.dcn.beta_bytes_per_us
        - 1.0 / params.ici.beta_bytes_per_us)
    if slope_gap <= 0:
        # DCN not the per-byte bottleneck: flat wins (or ties) ever
        # more as payload grows, so there is no "above" threshold.
        return 1 << 62
    if lat_gap >= 0:
        return 0            # hier already wins on latency alone
    return int(-lat_gap / slope_gap) + 1


# --- online estimator --------------------------------------------------------

class OnlineEstimator:
    """EWMA refinement of the per-tier β from observed bytes/µs.

    ``note_plan`` records a compiled schedule's per-tier planned wire
    bytes (called at trace time by the schedule executor);
    ``refine_from_step`` converts each finished step's wall time into
    per-tier achieved bytes/µs samples and EWMAs them into the β
    estimate.  Step time includes compute, so the sample is a *floor*
    on achievable bandwidth — the estimate converges from below and is
    exact on pure-wire workloads (the convergence oracle in
    tests/test_topo.py feeds synthetic pure-wire signals).  α samples
    arrive via :meth:`observe_alpha` from latency-dominated probes.
    """

    def __init__(self, prior: Optional[TopoCostParams] = None,
                 decay: float = 0.2) -> None:
        self._lock = threading.Lock()
        self.prior = prior or default_params()
        self.decay = float(decay)
        self._beta: Dict[str, float] = {}     # bytes/µs EWMA; guarded-by: _lock
        self._alpha: Dict[str, float] = {}    # µs EWMA; guarded-by: _lock
        self._plan_bytes: Dict[str, float] = {}  # guarded-by: _lock
        self._samples = 0                     # guarded-by: _lock
        self._frozen: Optional[bool] = None   # guarded-by: _lock

    def frozen(self) -> bool:
        with self._lock:
            if self._frozen is not None:
                return self._frozen
        from .. import basics

        return (basics.config().topo_cost_freeze
                if basics.is_initialized() else False)

    def freeze(self, frozen: bool = True) -> None:
        with self._lock:
            self._frozen = bool(frozen)

    @property
    def samples(self) -> int:
        with self._lock:
            return self._samples

    def note_plan(self, tier_bytes: Dict[str, float]) -> None:
        """Latest compiled schedule's per-tier wire bytes per step."""
        with self._lock:
            self._plan_bytes = {t: float(b) for t, b in tier_bytes.items()
                                if b > 0}

    def observe(self, tier: str, nbytes: float, elapsed_us: float) -> None:
        """One achieved-bandwidth sample for a tier."""
        if self.frozen() or nbytes <= 0 or elapsed_us <= 0:
            return
        rate = float(nbytes) / float(elapsed_us)
        with self._lock:
            prev = self._beta.get(tier)
            self._beta[tier] = (rate if prev is None
                                else (1 - self.decay) * prev
                                + self.decay * rate)
            self._samples += 1
        self._publish()

    def observe_alpha(self, tier: str, elapsed_us: float,
                      hops: int) -> None:
        """One latency-dominated sample (near-zero payload): per-hop
        launch latency."""
        if self.frozen() or hops <= 0 or elapsed_us <= 0:
            return
        a = float(elapsed_us) / float(hops)
        with self._lock:
            prev = self._alpha.get(tier)
            self._alpha[tier] = (a if prev is None
                                 else (1 - self.decay) * prev
                                 + self.decay * a)
            self._samples += 1
        self._publish()

    def refine_from_step(self, step_time_s: float) -> None:
        """Feed one finished step: the per-tier bytes of the latest
        compiled plan rode the wire inside this wall time.  Called from
        ``obs/instrument.wrap_step``; cheap no-op when no plan was
        noted or the estimator is frozen."""
        with self._lock:
            plan = dict(self._plan_bytes)
        if not plan or step_time_s <= 0:
            return
        for tier, nbytes in plan.items():
            self.observe(tier, nbytes, step_time_s * 1e6)

    def params(self) -> TopoCostParams:
        """Current estimate: prior with EWMA'd tiers swapped in."""
        with self._lock:
            beta = dict(self._beta)
            alpha = dict(self._alpha)

        def tier(name: str, prior: TierParams) -> TierParams:
            return TierParams(
                alpha_us=alpha.get(name, prior.alpha_us),
                beta_gbps=(beta[name] / 1e3) if name in beta
                else prior.beta_gbps)

        return TopoCostParams(ici=tier("ici", self.prior.ici),
                              dcn=tier("dcn", self.prior.dcn))

    def effective_params(self) -> TopoCostParams:
        """What the schedule compiler should use: refined values on a
        single-controller world, declared priors everywhere else (see
        module docstring — per-process refinement must not diverge the
        compiled collective programs across ranks).

        Refinement feeds the compiler only once EVERY tier has a β
        sample: the flat-vs-hierarchical decision rides the cross-tier
        ratio, and a one-sided floor (e.g. a flat plan notes bytes only
        on the DCN tier, so step time collapses β_dcn while β_ici keeps
        its fast prior) would distort that ratio and flip schedules for
        reasons that have nothing to do with link speeds.  Shared-step
        samples refine both tiers against the same denominator, which
        keeps the decision stable under the floor's pessimism."""
        with self._lock:
            refined_tiers = set(self._beta)
        if not refined_tiers.issuperset(TIERS):
            return self.prior
        import jax

        if jax.process_count() > 1:
            return self.prior
        return self.params()

    def _publish(self) -> None:
        from ..obs import instrument as _obs

        if not _obs.enabled():
            return
        p = self.params()
        for name in TIERS:
            t = p.tier(name)
            _obs.on_topo_estimator(name, t.alpha_us, t.beta_gbps)


_estimator: Optional[OnlineEstimator] = None   # guarded-by: _est_lock
_est_lock = threading.Lock()


def estimator() -> OnlineEstimator:
    """The process-wide estimator (lazy; priors resolve from the live
    config at first use).  Never reset across elastic re-inits — like
    the metrics registry, learned bandwidth spans recoveries."""
    global _estimator
    with _est_lock:
        if _estimator is None:
            _estimator = OnlineEstimator()
        return _estimator


def reset_estimator() -> None:
    """Drop the process estimator (tests only)."""
    global _estimator
    with _est_lock:
        _estimator = None
