"""horovod_tpu.keras — standalone-Keras alias of the TF/Keras binding.

Reference: ``horovod/keras/`` (SURVEY.md §2.4, mount empty, unverified)
— upstream keeps a standalone-keras package mirroring
``horovod.tensorflow.keras``; with Keras 3 both are the same optimizer
and callback implementations, so this package re-exports them.
"""

from ..tensorflow.keras import (  # noqa: F401
    Compression, DistributedOptimizer, broadcast_model, broadcast_variables,
    callbacks, elastic,
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
)
