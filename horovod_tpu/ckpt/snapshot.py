"""Snapshot-and-offload: the one device→host copy durability costs.

The synchronous checkpoint path bills the step loop for everything —
device→host copy, serialization, digesting, the filesystem round trip.
The async design ("Check-N-Run" line in PAPERS.md) splits it: at the
step boundary the caller pays exactly ONE ``jax.device_get`` into
host-owned buffers (a :class:`Snapshot`), and everything downstream —
the orbax/shard write, the sha256 digest, the fsync — happens on a
background writer thread against those frozen buffers.

Two properties matter:

* **Ownership.** On CPU backends ``np.asarray(jax.Array)`` can alias
  the live device buffer, which the next step mutates (donation).  A
  snapshot therefore always COPIES into buffers it owns.
* **Bounded allocation.**  Re-allocating model-sized host buffers per
  save fragments the host heap exactly when the allocator is busiest.
  :class:`BufferPool` keeps one reusable buffer set per in-flight
  snapshot (``HVD_TPU_CKPT_INFLIGHT`` + 1), so steady-state saving
  allocates nothing.

Digest compatibility: :meth:`Snapshot.digest` reproduces
:func:`pytree_digest` bit-for-bit from the snapshot buffers — the
sidecar a sync save wrote yesterday verifies a snapshot-offloaded save
written today, and vice versa.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "Snapshot", "SnapshotLeaf", "BufferPool", "take_snapshot",
    "is_snapshotable", "pytree_digest", "leaf_record_digest",
]


def _key_token(entry) -> str:
    """One path entry as a container-agnostic token: a save/restore
    round trip normalizes containers (namedtuples/custom nodes → dicts,
    tuples → lists), which swaps GetAttrKey('x') for DictKey('x') — the
    *name* is the stable coordinate, not the keystr formatting."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return repr(getattr(entry, attr))
    return repr(entry)


def path_string(path: Tuple[Any, ...]) -> str:
    return "/".join(_key_token(e) for e in path)


def leaf_record_digest(path_str: str, arr: np.ndarray) -> bytes:
    """The per-leaf record the tree digest is built from: sha256 over
    (key path, dtype, shape, raw bytes).  Per-leaf digests also land in
    the shard manifest, so restore can verify exactly the leaves it
    moves instead of the whole tree."""
    r = hashlib.sha256()
    r.update(path_str.encode())
    r.update(arr.dtype.str.encode())
    r.update(repr(arr.shape).encode())
    r.update(np.ascontiguousarray(arr).tobytes())
    return r.digest()


def combine_leaf_digests(records: List[bytes]) -> str:
    """Order-insensitive combination (sorted), matching the original
    ``checkpoint.pytree_digest`` contract: container normalization
    reorders leaves, which is not a content change."""
    h = hashlib.sha256()
    for record in sorted(records):
        h.update(record)
    return h.hexdigest()


def pytree_digest(tree: Any) -> str:
    """Content digest of a pytree: sha256 over per-leaf records of
    (key path, dtype, shape, raw bytes), combined order-insensitively.
    Key paths (not treedef identity, not flatten order) are the stable
    coordinate across the container-type normalization a save/restore
    round trip applies."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    records = []
    for path, leaf in flat:
        arr = np.asarray(jax.device_get(leaf))
        records.append(leaf_record_digest(path_string(path), arr))
    return combine_leaf_digests(records)


def is_snapshotable(tree: Any) -> bool:
    """A snapshot needs every leaf's bytes on this host; arrays spanning
    non-addressable devices (multi-host shardings) can't be pulled —
    callers degrade to the direct orbax path (which coordinates the
    distributed write itself) for such trees."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


class SnapshotLeaf:
    """One offloaded leaf: its typed key path (skeleton reconstruction),
    the stable path string (digests/manifests), and the host buffer."""

    __slots__ = ("path", "path_str", "array")

    def __init__(self, path: Tuple[Any, ...], path_str: str,
                 array: np.ndarray) -> None:
        self.path = path
        self.path_str = path_str
        self.array = array


class Snapshot:
    """A frozen host copy of one pytree at one step.  The writer thread
    reads it; nothing mutates it after :func:`take_snapshot` returns."""

    def __init__(self, step: int, leaves: List[SnapshotLeaf],
                 treedef, buffers: Optional[Dict[str, np.ndarray]],
                 pool: Optional["BufferPool"]) -> None:
        self.step = int(step)
        self.leaves = leaves
        self.treedef = treedef
        self._buffers = buffers
        self._pool = pool

    @property
    def nbytes(self) -> int:
        return sum(int(leaf.array.nbytes) for leaf in self.leaves)

    def tree(self) -> Any:
        """Rebuild the (numpy) pytree with the original container
        structure — what the compat tier hands to orbax."""
        import jax

        return jax.tree_util.tree_unflatten(
            self.treedef, [leaf.array for leaf in self.leaves])

    def digest(self) -> str:
        """Tree digest from the snapshot buffers — identical to
        ``pytree_digest(tree)``, computed without touching the device
        again (the whole point: digesting never bills the step loop)."""
        return combine_leaf_digests(
            [leaf_record_digest(leaf.path_str, leaf.array)
             for leaf in self.leaves])

    def leaf_digests(self) -> Dict[str, str]:
        """Per-leaf hex digests keyed by path string (manifest rows)."""
        return {
            leaf.path_str: leaf_record_digest(leaf.path_str,
                                              leaf.array).hex()
            for leaf in self.leaves
        }

    def release(self) -> None:
        """Return pooled buffers (write finished, or the snapshot was
        coalesced away).  Idempotent."""
        if self._pool is not None and self._buffers is not None:
            self._pool.release(self._buffers)
        self._buffers = None
        self._pool = None


class BufferPool:
    """Reusable host buffer sets — one per concurrently-live snapshot.

    ``acquire`` hands out a dict keyed by leaf path; ``take_snapshot``
    copies into matching (dtype, shape) buffers and replaces mismatched
    ones (a resize/new-leaf re-trace is rare).  An exhausted pool falls
    back to fresh allocation rather than blocking the step loop —
    memory pressure is the writer's problem, latency is the caller's.
    """

    def __init__(self, depth: int) -> None:
        self._lock = threading.Lock()
        self._free: List[Dict[str, np.ndarray]] = [
            {} for _ in range(max(1, int(depth)))]
        self._outstanding = 0   # guarded-by: _lock (acquired - released)
        from ..analysis import sanitizer as _san

        _san.maybe_register("buffer_pool", self)

    def acquire(self) -> Optional[Dict[str, np.ndarray]]:
        with self._lock:
            if self._free:
                self._outstanding += 1
                return self._free.pop()
        return None

    def release(self, buffers: Dict[str, np.ndarray]) -> None:
        with self._lock:
            self._free.append(buffers)
            self._outstanding -= 1

    def outstanding(self) -> int:
        """Acquired-but-unreleased buffer sets — the hvdsan teardown
        audit's leak probe (a `Snapshot` nobody released)."""
        with self._lock:
            return self._outstanding


def take_snapshot(tree: Any, *, step: int = 0,
                  pool: Optional[BufferPool] = None) -> Snapshot:
    """Device→host copy ``tree`` into owned (pooled when possible)
    buffers.  This is the entirety of what a save costs the step loop.
    Raises ``ValueError`` for trees spanning non-addressable devices —
    gate on :func:`is_snapshotable` first."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    buffers = pool.acquire() if pool is not None else None
    if buffers:
        # Evict buffers for leaves that no longer exist (an elastic
        # re-trace restructuring opt_state) — stale entries would pin
        # old-model-sized host memory for the rest of the run.
        live = {path_string(p) for p, _ in flat}
        for key in [k for k in buffers if k not in live]:
            del buffers[key]
    leaves: List[SnapshotLeaf] = []
    host = jax.device_get([leaf for _, leaf in flat])
    for (path, _), got in zip(flat, host):
        arr = np.asarray(got)
        pstr = path_string(path)
        buf = buffers.get(pstr) if buffers is not None else None
        if buf is not None and buf.dtype == arr.dtype \
                and buf.shape == arr.shape:
            np.copyto(buf, arr)
            arr = buf
        else:
            # np.asarray may alias the live device buffer on CPU
            # backends — the snapshot must own its bytes.
            arr = np.array(arr, copy=True)
            if buffers is not None:
                buffers[pstr] = arr
        leaves.append(SnapshotLeaf(path, pstr, arr))
    return Snapshot(step, leaves, treedef, buffers, pool)
