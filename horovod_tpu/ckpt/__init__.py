"""Async sharded durable state (ROADMAP item 5 / ISSUE 9).

The checkpoint subsystem, in four pieces:

* :mod:`.snapshot` — snapshot-and-offload: durability costs the step
  loop ONE device→host copy into pooled host buffers; digests are
  computed from those buffers, never from the device again.
* :mod:`.store` + :mod:`.manifest` — per-step shard files with a JSON
  manifest mapping key-path → {shard file, owner ranks, digest,
  nbytes}, committed by one atomic rename; an elastic resize N→N′
  restores exactly the bytes each new rank owns, and damage is
  detected at manifest granularity.
* :mod:`.journal` — an append-only fsync'd JSONL of per-step replay
  metadata (rng key, sampler cursor, knobs), so recovery restores the
  last snapshot and replays to the EXACT failed step.
* :mod:`.writer` + :mod:`.checkpointer` — the bounded background
  writer (``HVD_TPU_CKPT_ASYNC``/``HVD_TPU_CKPT_INFLIGHT``) and the
  :class:`AsyncCheckpointer` facade.

:mod:`.compat` keeps the pre-existing orbax whole-tree tier; the
``horovod_tpu.checkpoint`` module is a thin shim over it.  See
docs/checkpointing.md.
"""

from .checkpointer import AsyncCheckpointer, ResumeInfo  # noqa: F401
from .errors import CheckpointCorruptionError  # noqa: F401
from .journal import StepJournal  # noqa: F401
from .manifest import (  # noqa: F401
    Manifest, ManifestError, RestorePlan, assign_owners, diff_manifest,
    plan_restore, shard_filename,
)
from .snapshot import (  # noqa: F401
    BufferPool, Snapshot, is_snapshotable, pytree_digest, take_snapshot,
)
from .store import ShardStore  # noqa: F401
from .writer import AsyncWriter  # noqa: F401

__all__ = [
    "AsyncCheckpointer", "ResumeInfo", "CheckpointCorruptionError",
    "StepJournal", "Manifest", "ManifestError", "RestorePlan",
    "assign_owners", "diff_manifest", "plan_restore",
    "shard_filename", "BufferPool",
    "Snapshot", "is_snapshotable", "pytree_digest", "take_snapshot",
    "ShardStore", "AsyncWriter",
]
