"""Step-metadata journal: replay to the exact failed step, never rewind.

A snapshot cadence of every-K-steps means a crash loses up to K−1 steps
of progress — unless the metadata needed to *re-run* those steps
deterministically is durable at every step.  That metadata is tiny
(step number, RNG key, the elastic sampler's cursor, the autotune knob
snapshot, wall clock), so an append-only fsync'd JSONL line per step is
~free next to the step itself.  Recovery then restores the last full
snapshot and replays journal entries forward to the exact step that
failed: zero lost steps, no silent rewind.

Durability/corruption model (what the tests pin):

* every ``append`` is flushed and fsync'd before returning — a
  journaled step survives a process kill;
* a torn final line (the fsync the crash interrupted) is tolerated:
  reads stop at the last intact line and report the tail as corrupt;
* corruption mid-file also stops the read there (entries past garbage
  can't be trusted to be ordered) — deterministically, with a
  flight-recorder event so the postmortem says the journal was cut;
* re-run steps after an elastic rollback append duplicate step
  numbers; the LAST occurrence wins on replay (it is the one whose
  effects the newest snapshot may contain).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["StepJournal"]


def _jsonable(value: Any):
    """Journal entries carry rng keys / cursors that arrive as arrays;
    the journal is JSON so a human (and ``jq``) can read it mid-incident."""
    if isinstance(value, np.ndarray):
        return value.tolist()
    if isinstance(value, np.generic):
        return value.item()
    tolist = getattr(value, "tolist", None)
    if callable(tolist):  # jax.Array without importing jax here
        return tolist()
    return str(value)


class StepJournal:
    """Append-only fsync'd JSONL of per-step metadata.

    One writer (the training loop / ``AsyncCheckpointer.journal_step``),
    many readers (recovery, tests); a lock serializes appends so the
    elastic driver's threads can journal too.
    """

    def __init__(self, path: str, *, fsync: bool = True) -> None:
        self.path = os.path.abspath(path)
        self._fsync = bool(fsync)
        self._lock = threading.Lock()
        self._f = None                      # guarded-by: _lock
        self._corrupt_reported = False      # guarded-by: _lock

    # --- write ---------------------------------------------------------------

    def append(self, step: int, **meta: Any) -> int:
        """Durably append one entry; returns its byte length.  The
        entry is on disk (flushed + fsync'd) when this returns — that
        is the contract replay correctness rests on."""
        entry: Dict[str, Any] = {"step": int(step), "t_unix": time.time()}
        entry.update(meta)
        data = (json.dumps(entry, separators=(",", ":"),
                           default=_jsonable) + "\n").encode()
        with self._lock:
            if self._f is None:
                os.makedirs(os.path.dirname(self.path) or ".",
                            exist_ok=True)
                self._repair_torn_tail_locked()
                self._f = open(self.path, "ab")
            self._f.write(data)
            self._f.flush()
            if self._fsync:
                os.fsync(self._f.fileno())
        from ..obs import instrument as _obs

        _obs.on_ckpt_journal(len(data))
        return len(data)

    def _repair_torn_tail_locked(self) -> None:
        """Before the first append of a resumed process: truncate a
        torn final line (the fsync the previous crash interrupted) back
        to the last newline.  Appending onto the partial record would
        merge it with the new entry into one garbage line, and a later
        read would stop THERE — losing every post-restart entry in
        exactly the double-crash scenario the journal exists for.  The
        torn record itself was never acknowledged durable (its append
        never returned), so dropping it loses nothing."""
        try:
            with open(self.path, "rb+") as f:
                raw = f.read()
                if not raw or raw.endswith(b"\n"):
                    return
                cut = raw.rfind(b"\n") + 1
                f.truncate(cut)
        except FileNotFoundError:
            return
        from ..obs import flight as _flight

        _flight.record("ckpt_journal_repaired", path=self.path,
                       dropped_bytes=len(raw) - cut)
        logger.warning(
            "step journal %s: dropped a torn %d-byte tail record "
            "before resuming appends (it was never acknowledged "
            "durable)", self.path, len(raw) - cut)

    # --- read ----------------------------------------------------------------

    def read(self) -> Tuple[List[Dict[str, Any]], bool]:
        """``(entries, intact)`` — entries up to the first damage point,
        ``intact=False`` when a torn/corrupt line cut the read short.
        Missing file reads as ``([], True)``: an empty journal is a
        fresh run, not damage."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return [], True
        entries: List[Dict[str, Any]] = []
        intact = True
        lines = raw.split(b"\n")
        # A properly-terminated file ends with one empty split tail; a
        # torn final fsync leaves a partial line there instead.
        terminated = lines and lines[-1] == b""
        body = lines[:-1] if terminated else lines
        for i, line in enumerate(body):
            if not line.strip():
                continue
            try:
                entry = json.loads(line)
                if not isinstance(entry, dict) or "step" not in entry:
                    raise ValueError("journal line without a step")
            except (ValueError, UnicodeDecodeError):
                intact = False
                self._report_corrupt(i, len(body))
                break
            if not terminated and i == len(body) - 1:
                # Parsed, but the line the crash tore could be a prefix
                # of a longer record that happens to parse — only a
                # newline-terminated line is known complete.
                intact = False
                self._report_corrupt(i, len(body))
                break
            entries.append(entry)
        return entries, intact

    def _report_corrupt(self, line_no: int, total: int) -> None:
        with self._lock:
            first = not self._corrupt_reported
            self._corrupt_reported = True
        from ..obs import flight as _flight

        _flight.record("ckpt_journal_corrupt", path=self.path,
                       line=line_no, lines=total)
        if first:
            logger.warning(
                "step journal %s cut at line %d/%d (torn or corrupt "
                "record); replay stops at the last intact entry",
                self.path, line_no, total)

    def entries_after(self, step: int,
                      entries: Optional[List[Dict[str, Any]]] = None
                      ) -> List[Dict[str, Any]]:
        """Replay tail: intact entries with ``step > step``, dedup'd so
        the LAST occurrence of a step wins (rollback re-runs append
        duplicates), ordered by step.  Pass ``entries`` from an earlier
        :meth:`read` to avoid re-reading an O(run-length) file."""
        if entries is None:
            entries, _ = self.read()
        by_step: Dict[int, Dict[str, Any]] = {}
        for e in entries:
            by_step[int(e["step"])] = e
        return [by_step[s] for s in sorted(by_step) if s > int(step)]

    def last_step(self) -> Optional[int]:
        entries, _ = self.read()
        if not entries:
            return None
        return max(int(e["step"]) for e in entries)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "StepJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
