"""Sharded on-disk step store: atomic commits, member-level reads.

Layout (one directory per checkpointed run)::

    <dir>/steps/<N>/manifest.json        # the shard map (manifest.py)
    <dir>/steps/<N>/shard_r00000.npz     # rank 0's leaves, one zip member per leaf
    <dir>/steps/<N>/shard_r00001.npz
    <dir>/.tmp-<N>-<pid>-<k>/            # in-progress write (never read)

Write protocol: everything lands in a tmp directory, every file (and
the directory) is fsync'd, then ONE atomic ``os.replace`` commits the
step.  A crash at any earlier point leaves only an ignorable tmp dir —
the "crash-before-rename" fault mode is exactly that cut.

Storage is uncompressed ``.npz`` (zip-of-arrays) rather than orbax for
the sharded tier deliberately: zip members are independently readable,
so a restore plan that needs 3 leaves out of a 40-leaf shard moves ~3
leaves of bytes (``np.load`` is lazy per member).  orbax 0.7 has no
subset restore — it stays the engine of the monolithic compat tier
(``horovod_tpu.checkpoint``), where whole-tree semantics are the point.

Integrity: per-leaf sha256 digests live in the manifest (computed from
the snapshot buffers on the writer thread — never billed to the step
loop) and are verified on read; a step is *intact* when its manifest
parses and every referenced shard file exists with a plausible size.
"""

from __future__ import annotations

import os
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .errors import CheckpointCorruptionError
from .manifest import (Manifest, ManifestError, RestorePlan, assign_owners,
                       build_skeleton, plan_restore, shard_filename,
                       skeleton_fill)
from .snapshot import Snapshot, leaf_record_digest
from ..utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["ShardStore"]


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def bitflip_middle(victim: str, nbytes: int = 64) -> int:
    """XOR-flip ``nbytes`` at the middle of ``victim`` — THE simulated
    flipped-disk-block damage, shared by both storage tiers' fault
    application so the chaos model cannot drift between them.  Returns
    the number of bytes flipped."""
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(nbytes) or b"\0"
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))
    return len(chunk)


class ShardStore:
    def __init__(self, directory: str, *, fsync: bool = True) -> None:
        self._dir = os.path.abspath(directory)
        self._fsync = bool(fsync)
        self._tmp_seq = 0

    @property
    def directory(self) -> str:
        return self._dir

    def _steps_dir(self) -> str:
        return os.path.join(self._dir, "steps")

    def step_dir(self, step: int) -> str:
        return os.path.join(self._steps_dir(), str(int(step)))

    def steps(self) -> List[int]:
        """Committed steps, ascending.  Only a directory whose atomic
        rename happened is listed — tmp dirs are invisible by
        construction."""
        try:
            names = os.listdir(self._steps_dir())
        except OSError:
            return []
        return sorted(int(n) for n in names if n.isdigit())

    def newest_intact_step(self,
                           min_step: Optional[int] = None) -> Optional[int]:
        """Newest committed step that passes manifest-granularity
        validation — the weight-hot-swap subscriber's watch primitive
        (serve/swap.py polls this; a damaged newest step is skipped, so
        a torn upload never becomes a serving version).  ``min_step``
        short-circuits the scan: steps at or below it are not even
        validated (the subscriber already runs one of them)."""
        for step in reversed(self.steps()):
            if min_step is not None and step <= min_step:
                return None
            try:
                self.validate_step(step)
                return step
            except ManifestError:
                continue
        return None

    # --- write ---------------------------------------------------------------

    def write_step(self, snapshot: Snapshot, *, world: int, scheme: str,
                   force: bool = False) -> Optional[Manifest]:
        """Write one step from a snapshot; returns its manifest, or
        None when the step already exists (and ``force`` is off).

        This process writes EVERY rank's shard file: the single-rename
        commit protocol has exactly one writer per step.  (A true
        multi-writer deployment needs a different protocol — per-rank
        commits with the manifest written last — and would live behind
        a new method, not a flag on this one.)
        """
        from .. import faults as faults_mod

        step = int(snapshot.step)
        target = self.step_dir(step)
        if os.path.isdir(target) and not force:
            return None

        mode = None
        if faults_mod._active is not None:
            # One event per save attempt; ``stall`` sleeps inside the
            # hook (a slow filesystem), damage modes come back for the
            # store to apply at the right point in the protocol.
            mode = faults_mod.on_checkpoint_save(step)

        leaf_ids = [f"l{i:05d}" for i in range(len(snapshot.leaves))]
        by_path = {leaf.path_str: (leaf_id, leaf)
                   for leaf_id, leaf in zip(leaf_ids, snapshot.leaves)}
        owners = assign_owners(
            [(leaf.path_str, int(leaf.array.nbytes))
             for leaf in snapshot.leaves], world, scheme)

        entries: Dict[str, Dict[str, Any]] = {}
        per_rank: Dict[int, Dict[str, np.ndarray]] = {}
        for path_str, owner in owners.items():
            leaf_id, leaf = by_path[path_str]
            arr = leaf.array
            if arr.dtype == object:
                raise TypeError(
                    f"checkpoint leaf {path_str!r} has object dtype — "
                    f"only array-convertible leaves are storable")
            entries[leaf_id] = {
                "path": path_str,
                "file": shard_filename(owner),
                "owners": [owner],
                "digest": leaf_record_digest(path_str, arr).hex(),
                "nbytes": int(arr.nbytes),
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
            }
            per_rank.setdefault(owner, {})[leaf_id] = arr

        manifest = Manifest(
            step=step, world=int(world), scheme=scheme, entries=entries,
            skeleton=build_skeleton([leaf.path for leaf in snapshot.leaves],
                                    leaf_ids),
            tree_digest=snapshot.digest(), created_unix=time.time())

        self._tmp_seq += 1
        tmp = os.path.join(
            self._dir, f".tmp-{step}-{os.getpid()}-{self._tmp_seq}")
        os.makedirs(tmp, exist_ok=True)
        for rank, arrays in sorted(per_rank.items()):
            path = os.path.join(tmp, shard_filename(rank))
            with open(path, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                if self._fsync:
                    os.fsync(f.fileno())
        mpath = os.path.join(tmp, Manifest.FILENAME)
        with open(mpath, "w") as f:
            f.write(manifest.to_json())
            f.flush()
            if self._fsync:
                os.fsync(f.fileno())
        if self._fsync:
            _fsync_path(tmp)

        if mode == "crash-before-rename":
            # Everything written, nothing committed: the exact cut a
            # process death between the last fsync and the rename
            # leaves behind.  The tmp dir stays on disk (as a real
            # crash's would); restore never looks at it.
            from ..elastic.state import HorovodInternalError

            raise HorovodInternalError(
                f"injected checkpoint crash-before-rename at step {step}"
                f" (data written to {tmp}, commit never happened)")

        os.makedirs(self._steps_dir(), exist_ok=True)
        if force and os.path.isdir(target):
            # Deferred until the replacement is fully written and
            # fsync'd: a crash during the (long) write must leave the
            # OLD step intact, not neither.
            shutil.rmtree(target)
        os.replace(tmp, target)
        if self._fsync:
            _fsync_path(self._steps_dir())

        if mode in ("corrupt", "partial", "partial-manifest") \
                and _damage_host():
            self._apply_damage(target, manifest, mode)
        return manifest

    def _apply_damage(self, step_dir: str, manifest: Manifest,
                      mode: str) -> None:
        shards = [os.path.join(step_dir, f) for f in manifest.files()]
        shards = [p for p in shards if os.path.exists(p)]
        if not shards:
            logger.warning("fault: no shard files to damage under %s",
                           step_dir)
            return
        if mode == "partial-manifest":
            # The manifest stays intact but references a shard that is
            # not there — the metadata/data split failure mode the
            # manifest-granularity intact check exists for.
            victim = min(shards, key=os.path.getsize)
            os.unlink(victim)
            logger.warning("fault: deleted %s (manifest now dangling)",
                           victim)
            return
        victim = max(shards, key=os.path.getsize)
        if mode == "partial":
            os.unlink(victim)
            logger.warning("fault: deleted %s (partial write)", victim)
            return
        flipped = bitflip_middle(victim)
        logger.warning("fault: corrupted %d bytes of %s", flipped,
                       victim)

    def delete_step(self, step: int) -> None:
        shutil.rmtree(self.step_dir(step), ignore_errors=True)

    # --- read ----------------------------------------------------------------

    def read_manifest(self, step: int) -> Manifest:
        return Manifest.read(os.path.join(self.step_dir(step),
                                          Manifest.FILENAME))

    def validate_step(self, step: int) -> Manifest:
        """Manifest-granularity intactness: the manifest parses and
        every referenced shard file exists and is at least as large as
        the payload it claims.  Raises ``ManifestError`` otherwise —
        no array data is deserialized."""
        manifest = self.read_manifest(step)
        step_dir = self.step_dir(step)
        need: Dict[str, int] = {}
        try:
            # Structural validation: a torn write can leave JSON that
            # parses but is mangled (entry missing 'file'/'nbytes',
            # nbytes='garbage', a non-dict entry).  That is manifest
            # damage — it must feed the fallback scan, never escape it
            # as a raw KeyError/TypeError.
            for entry in manifest.entries.values():
                if not isinstance(entry.get("path"), str) \
                        or not isinstance(entry.get("digest"), str):
                    raise ValueError("entry missing path/digest")
                need[str(entry["file"])] = need.get(
                    str(entry["file"]), 0) + int(entry["nbytes"])
        except (KeyError, TypeError, ValueError, AttributeError) as e:
            raise ManifestError(
                f"step {step}: structurally damaged manifest entry: "
                f"{type(e).__name__}: {e}") from e
        for fname, nbytes in sorted(need.items()):
            path = os.path.join(step_dir, fname)
            try:
                size = os.path.getsize(path)
            except OSError as e:
                raise ManifestError(
                    f"step {step}: manifest references missing shard "
                    f"{fname}: {e}") from e
            if size < nbytes:
                raise ManifestError(
                    f"step {step}: shard {fname} holds {size} bytes but "
                    f"the manifest claims {nbytes} of payload")
        return manifest

    def read_leaves(self, step: int, by_file: Dict[str, List[str]],
                    manifest: Manifest, *,
                    verify: bool = True) -> Dict[str, np.ndarray]:
        """Read exactly the requested leaf ids (grouped by shard file,
        as a :class:`RestorePlan` yields them); ``np.load`` is lazy per
        zip member, so bytes moved ≈ bytes requested.  With ``verify``,
        each leaf is checked against its manifest digest."""
        import zipfile

        step_dir = self.step_dir(step)
        out: Dict[str, np.ndarray] = {}
        for fname, leaf_ids in sorted(by_file.items()):
            path = os.path.join(step_dir, fname)
            try:
                with np.load(path, allow_pickle=False) as z:
                    for leaf_id in leaf_ids:
                        out[leaf_id] = z[leaf_id]
            except (OSError, ValueError, KeyError, EOFError,
                    zipfile.BadZipFile) as e:
                # Bit-flipped members fail the zip CRC before our
                # digest even runs — same verdict either way.
                raise CheckpointCorruptionError(
                    f"step {step}: shard {fname} unreadable: {e}") from e
        if verify:
            for leaf_id, arr in out.items():
                entry = manifest.entries[leaf_id]
                got = leaf_record_digest(entry["path"], arr).hex()
                if got != entry["digest"]:
                    raise CheckpointCorruptionError(
                        f"step {step}: leaf {entry['path']} failed "
                        f"digest verification")
        return out

    def read_tree(self, step: int, *, verify: bool = True) -> Any:
        """Full-tree restore: every leaf, rebuilt into the manifest's
        container skeleton (tuples→lists / namedtuples→dicts
        normalization, same as the orbax tier)."""
        manifest = self.validate_step(step)
        by_file: Dict[str, List[str]] = {}
        for leaf_id, entry in manifest.entries.items():
            by_file.setdefault(entry["file"], []).append(leaf_id)
        leaves = self.read_leaves(step, by_file, manifest, verify=verify)
        try:
            return skeleton_fill(manifest.skeleton, leaves)
        except (KeyError, TypeError) as e:
            # A skeleton referencing a leaf id with no entry is the
            # same torn-manifest class as above.
            raise ManifestError(
                f"step {step}: skeleton/entries mismatch: "
                f"{type(e).__name__}: {e}") from e

    def read_shard(self, step: int, plan: RestorePlan, *,
                   verify: bool = True) -> Dict[str, np.ndarray]:
        """One rank's restore: only the plan's leaves move.  Returns
        ``{path_str: array}`` (the caller scatter/gathers them into its
        partition)."""
        manifest = self.validate_step(step)
        leaves = self.read_leaves(step, plan.by_file, manifest,
                                  verify=verify)
        return {manifest.entries[leaf_id]["path"]: arr
                for leaf_id, arr in leaves.items()}


def _damage_host() -> bool:
    """Apply injected damage on exactly one host (two ranks XOR-flipping
    the same bytes would cancel out — a false-green chaos run)."""
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True
