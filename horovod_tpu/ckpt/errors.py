"""Shared checkpoint error types (one home, no import cycles)."""

from __future__ import annotations

__all__ = ["CheckpointCorruptionError"]


class CheckpointCorruptionError(RuntimeError):
    """No step restored AND verified (raised only after the fallback
    scan exhausted every retained step)."""
