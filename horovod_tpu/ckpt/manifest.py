"""Per-step shard manifests: who owns which leaves, and where they are.

A monolithic checkpoint makes every restore move every byte to every
rank.  The manifest makes ownership explicit: each step's save records,
per leaf, the shard file holding it, the rank set that owns it, its
sha256 digest and byte size — so an elastic resize N→N′ computes, from
metadata alone, exactly which bytes each NEW rank must read, and a
damaged step is detected at manifest granularity (a referenced file
missing or the wrong size) without deserializing anything.

Ownership schemes mirror the optimizer partitions:

* ``dp`` — replicated data parallelism: rank 0 owns everything (only
  rank 0 writes, exactly like the reference examples' rank-0 gating);
  restore loads on rank 0 and broadcasts.
* ``zero`` / ``fsdp`` — leaf-granularity partition of the state across
  ranks (DeepSpeed-stage-1 style): leaves are assigned greedily,
  biggest first, to the least-loaded rank — deterministic, and within
  ~max-leaf of byte-balanced.  A width change just recomputes the
  assignment over the same leaf set; the manifest maps each needed
  leaf back to the old shard file that holds it.

The container *skeleton* (dicts/lists with leaves replaced by ids) is
stored alongside, so a fresh process can rebuild the tree without a
template — with the same normalization orbax applies (tuples → lists,
namedtuples/custom nodes → dicts), which the digest is already
invariant to.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "SCHEMES", "Manifest", "ManifestError", "assign_owners",
    "shard_filename", "plan_restore", "RestorePlan", "diff_manifest",
]

SCHEMES = ("dp", "zero", "fsdp")

_LEAF_MARK = "__leaf__"


class ManifestError(ValueError):
    """A manifest that cannot be trusted: unparseable, missing fields,
    or referencing shard content that is not there."""


def shard_filename(rank: int) -> str:
    return f"shard_r{int(rank):05d}.npz"


def assign_owners(leaves: Sequence[Tuple[str, int]], world: int,
                  scheme: str) -> Dict[str, int]:
    """``{path_str: owner_rank}`` for every leaf.  ``dp`` pins all to
    rank 0; ``zero``/``fsdp`` balance bytes greedily (stable: sorted by
    (-nbytes, path), ties to the lowest-loaded, lowest-numbered rank) —
    every rank computes the identical assignment from the identical
    leaf set, no coordination needed."""
    if scheme not in SCHEMES:
        raise ValueError(f"unknown ownership scheme {scheme!r}; expected "
                         f"one of {SCHEMES}")
    world = max(1, int(world))
    if scheme == "dp":
        return {path: 0 for path, _ in leaves}
    load = [0] * world
    owners: Dict[str, int] = {}
    for path, nbytes in sorted(leaves, key=lambda x: (-int(x[1]), x[0])):
        rank = min(range(world), key=lambda r: (load[r], r))
        owners[path] = rank
        load[rank] += int(nbytes)
    return owners


# --- container skeleton ------------------------------------------------------

def build_skeleton(paths: Sequence[Tuple[Any, ...]],
                   leaf_ids: Sequence[str]) -> Any:
    """Nested dict/list skeleton from typed key paths, leaves replaced
    by ``{"__leaf__": id}`` markers.  Dict keys and attribute names
    become string keys; sequence positions become list slots — the
    orbax-compatible normalization the digest already tolerates."""
    if not paths:
        return {}
    if len(paths) == 1 and len(paths[0]) == 0:
        return {_LEAF_MARK: leaf_ids[0]}   # bare-leaf tree

    root: Dict[Any, Any] = {}
    for path, leaf_id in zip(paths, leaf_ids):
        node = root
        for i, entry in enumerate(path):
            key = _entry_key(entry)
            if i == len(path) - 1:
                node[key] = {_LEAF_MARK: leaf_id}
            else:
                node = node.setdefault(key, {})
    return _listify(root)


def _entry_key(entry) -> Any:
    if hasattr(entry, "idx"):          # SequenceKey / FlattenedIndexKey
        return int(entry.idx)
    for attr in ("key", "name"):       # DictKey / GetAttrKey
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _listify(node: Any) -> Any:
    """Dicts whose keys are exactly 0..n-1 ints came from sequences —
    rebuild them as lists (tuples normalize to lists, like orbax)."""
    if isinstance(node, dict):
        if _LEAF_MARK in node and len(node) == 1:
            return node
        rebuilt = {k: _listify(v) for k, v in node.items()}
        if rebuilt and all(isinstance(k, int) for k in rebuilt):
            idxs = sorted(rebuilt)
            if idxs == list(range(len(idxs))):
                return [rebuilt[i] for i in idxs]
        return {str(k): v for k, v in rebuilt.items()}
    return node


def skeleton_fill(skeleton: Any, lookup: Dict[str, Any]) -> Any:
    """Rebuild a tree from the skeleton and ``{leaf_id: array}``."""
    if isinstance(skeleton, dict):
        if set(skeleton) == {_LEAF_MARK}:
            return lookup[skeleton[_LEAF_MARK]]
        return {k: skeleton_fill(v, lookup) for k, v in skeleton.items()}
    if isinstance(skeleton, list):
        return [skeleton_fill(v, lookup) for v in skeleton]
    return skeleton


# --- the manifest ------------------------------------------------------------

class Manifest:
    """One step's shard map: ``entries[leaf_id] = {path, file, owners,
    digest, nbytes, dtype, shape}`` plus the skeleton and the combined
    tree digest.  JSON on disk, one per step directory."""

    FILENAME = "manifest.json"

    def __init__(self, *, step: int, world: int, scheme: str,
                 entries: Dict[str, Dict[str, Any]], skeleton: Any,
                 tree_digest: str, created_unix: float = 0.0) -> None:
        self.step = int(step)
        self.world = int(world)
        self.scheme = scheme
        self.entries = entries
        self.skeleton = skeleton
        self.tree_digest = tree_digest
        self.created_unix = created_unix

    @property
    def nbytes(self) -> int:
        return sum(int(e["nbytes"]) for e in self.entries.values())

    def files(self) -> List[str]:
        return sorted({e["file"] for e in self.entries.values()})

    def to_json(self) -> str:
        return json.dumps({
            "format": "hvd_tpu_ckpt_manifest_v1",
            "step": self.step,
            "world": self.world,
            "scheme": self.scheme,
            "created_unix": self.created_unix,
            "tree_digest": self.tree_digest,
            "skeleton": self.skeleton,
            "entries": self.entries,
        }, indent=1, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Manifest":
        try:
            doc = json.loads(text)
            return cls(step=doc["step"], world=doc["world"],
                       scheme=doc["scheme"], entries=doc["entries"],
                       skeleton=doc["skeleton"],
                       tree_digest=doc["tree_digest"],
                       created_unix=doc.get("created_unix", 0.0))
        except (ValueError, KeyError, TypeError) as e:
            raise ManifestError(f"unreadable manifest: {e}") from e

    @classmethod
    def read(cls, path: str) -> "Manifest":
        try:
            with open(path) as f:
                text = f.read()
        except OSError as e:
            raise ManifestError(f"manifest missing/unreadable: {e}") from e
        return cls.from_json(text)


class RestorePlan:
    """What one rank must read to restore at a (possibly new) world
    size: leaf ids grouped by shard file, and the byte total — computed
    from metadata only, before any data moves."""

    def __init__(self, *, rank: int, world: int,
                 by_file: Dict[str, List[str]], nbytes: int,
                 leaf_ids: List[str]) -> None:
        self.rank = rank
        self.world = world
        self.by_file = by_file
        self.nbytes = nbytes
        self.leaf_ids = leaf_ids


def diff_manifest(manifest: Manifest,
                  have: Dict[str, str]) -> Tuple[Dict[str, List[str]],
                                                 Dict[str, str], int]:
    """Pull plan for a weight hot-swap (serve/swap.py): which of
    ``manifest``'s leaves differ from the running version.

    ``have`` maps key-path → leaf digest of the version currently
    serving.  Returns ``(by_file, changed, nbytes)``: changed leaf ids
    grouped by shard file (the shape :meth:`ShardStore.read_leaves`
    takes), ``{leaf_id: path}`` for the changed set, and the byte total
    the pull will move — a fine-tune step that touched 2 of 40 leaves
    pulls 2 leaves of bytes, decided from metadata alone."""
    by_file: Dict[str, List[str]] = {}
    changed: Dict[str, str] = {}
    nbytes = 0
    for leaf_id, entry in manifest.entries.items():
        path = entry["path"]
        if have.get(path) == entry["digest"]:
            continue
        changed[leaf_id] = path
        by_file.setdefault(entry["file"], []).append(leaf_id)
        nbytes += int(entry["nbytes"])
    for ids in by_file.values():
        ids.sort()
    return by_file, changed, nbytes


def plan_restore(manifest: Manifest, *, rank: int,
                 world: Optional[int] = None,
                 scheme: Optional[str] = None) -> RestorePlan:
    """Re-derive ownership at the NEW world size over the manifest's
    leaf set and map this rank's leaves back to the shard files that
    hold them.  ``world``/``scheme`` default to the manifest's own (the
    no-resize restore); a width change re-shards — leaves migrate
    between ranks purely by reading different manifest rows."""
    world = manifest.world if world is None else int(world)
    scheme = manifest.scheme if scheme is None else scheme
    if not 0 <= rank < max(1, world):
        raise ValueError(f"rank {rank} outside world {world}")
    leaves = [(e["path"], int(e["nbytes"]))
              for e in manifest.entries.values()]
    owners = assign_owners(leaves, world, scheme)
    by_path = {e["path"]: (leaf_id, e)
               for leaf_id, e in manifest.entries.items()}
    by_file: Dict[str, List[str]] = {}
    leaf_ids: List[str] = []
    nbytes = 0
    for path, owner in owners.items():
        if owner != rank:
            continue
        leaf_id, entry = by_path[path]
        by_file.setdefault(entry["file"], []).append(leaf_id)
        leaf_ids.append(leaf_id)
        nbytes += int(entry["nbytes"])
    for ids in by_file.values():
        ids.sort()
    return RestorePlan(rank=rank, world=world, by_file=by_file,
                       nbytes=nbytes, leaf_ids=sorted(leaf_ids))
