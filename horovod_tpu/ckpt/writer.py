"""Bounded background writer: the thread that owns the filesystem.

The step loop's entire durability cost is the snapshot; everything
slower lands here.  Contracts (each pinned by tests):

* **Bounded in-flight queue** (``HVD_TPU_CKPT_INFLIGHT``): at most N
  snapshots wait for the disk.  Holding unbounded snapshots would turn
  a slow filesystem into a host-OOM.
* **Coalescing, drop-oldest-unwritten**: when the queue is full, the
  OLDEST queued (not-yet-started) item is dropped to admit the new one
  — back-to-back saves against a stalled disk keep the newest state
  durable-bound instead of queueing history.  Dropped items are
  released via ``on_drop`` (buffer-pool return) and counted.
* **Exceptions surface on the caller**: a writer-thread failure is
  stored and re-raised from the next ``submit`` / ``wait_until_finished``
  / ``close`` — an async save must never fail silently.
* ``wait_until_finished`` / ``close`` are the barriers: when they
  return (without raising), everything submitted is on disk.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Callable, List, Optional

from ..utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["AsyncWriter"]


class AsyncWriter:
    def __init__(self, write_fn: Callable[[Any], None], *,
                 inflight: int = 2,
                 on_drop: Optional[Callable[[Any], None]] = None,
                 coalesce: bool = True,
                 name: str = "hvd-tpu-ckpt-writer") -> None:
        self._write_fn = write_fn
        self._inflight = max(1, int(inflight))
        self._on_drop = on_drop
        # coalesce=False: a full queue BLOCKS submit (backpressure)
        # instead of dropping the oldest item — for queues where every
        # item matters (the compat tier's digest sidecars: a dropped
        # job would silently skip verification for that step).
        self._coalesce = bool(coalesce)
        self._name = name
        self._cv = threading.Condition()
        self._pending: "deque" = deque()      # guarded-by: _cv
        self._busy = False                    # guarded-by: _cv
        self._error: Optional[BaseException] = None  # guarded-by: _cv
        self._closed = False                  # guarded-by: _cv
        self._dropped = 0                     # guarded-by: _cv
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cv

    # --- caller side ---------------------------------------------------------

    def submit(self, item: Any) -> None:
        """Enqueue one write.  Raises a stored writer-thread exception
        first (the failure of an EARLIER save surfaces here), then a
        ``RuntimeError`` if closed."""
        dropped: List[Any] = []
        with self._cv:
            self._raise_pending_locked()
            if self._closed:
                raise RuntimeError(f"{self._name}: submit after close()")
            if self._coalesce:
                while len(self._pending) >= self._inflight:
                    dropped.append(self._pending.popleft())
                    self._dropped += 1
            else:
                # Backpressure must never drop data, but a silent
                # forever-block against a wedged disk is the hang class
                # hvdlint's unbounded-wait check exists for: wait in
                # bounded slices and leave a flight-recorder trail each
                # time one expires, so a stuck submit ships evidence.
                while not self._cv.wait_for(
                        lambda: len(self._pending) < self._inflight
                        or self._error is not None or self._closed,
                        timeout=60.0):
                    logger.warning(
                        "%s: submit backpressured >60s (writer stuck "
                        "against a slow filesystem?)", self._name)
                    from ..obs import flight as _flight

                    _flight.record("ckpt_backpressure", writer=self._name,
                                   depth=len(self._pending))
                self._raise_pending_locked()
                if self._closed:
                    # close() won the race while we were blocked: the
                    # writer may already have exited — accepting the
                    # item would silently lose it.
                    raise RuntimeError(
                        f"{self._name}: closed while submit was "
                        f"backpressured")
            self._pending.append(item)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name=self._name, daemon=True)
                self._thread.start()
            self._cv.notify_all()
        for old in dropped:
            logger.warning("%s: coalesced a queued save (disk slower "
                           "than the save cadence); newest state wins",
                           self._name)
            from ..obs import instrument as _obs

            _obs.on_ckpt_coalesced()
            if self._on_drop is not None:
                self._on_drop(old)

    def depth(self) -> int:
        """Queued + in-progress writes right now (the in-flight gauge)."""
        with self._cv:
            return len(self._pending) + (1 if self._busy else 0)

    def dropped(self) -> int:
        with self._cv:
            return self._dropped

    def wait_until_finished(self, timeout: Optional[float] = None) -> None:
        """Block until the queue is drained and the writer is idle,
        then surface any stored exception.  With a ``timeout``, an
        expiry with writes still in flight raises ``TimeoutError`` —
        this is a durability barrier and must never silently return
        with data not yet on disk."""
        with self._cv:
            drained = self._cv.wait_for(
                lambda: (not self._pending and not self._busy)
                or self._error is not None,
                timeout=timeout)
            # Let a failure that happened while OTHER items were still
            # queued drain them first only if no error: an error stops
            # the wait immediately (the caller must learn now).
            self._raise_pending_locked()
            if not drained:
                raise TimeoutError(
                    f"{self._name}: writes still in flight after "
                    f"{timeout}s — data is NOT yet durable")

    def discard_pending(self) -> int:
        """Drop every queued-but-unstarted write and clear any stored
        error (the elastic rollback path: queued snapshots are
        pre-rollback state, and a poisoned error must not resurface
        mid-recovery).  Returns the number discarded."""
        with self._cv:
            dropped = list(self._pending)
            self._pending.clear()
            self._error = None
            self._cv.notify_all()
        if self._on_drop is not None:
            for old in dropped:
                self._on_drop(old)
        return len(dropped)

    def close(self, *, drain: bool = True) -> None:
        """Stop the writer.  ``drain=True`` (default) finishes queued
        writes first; surfaces any stored exception either way.  If the
        thread cannot drain within the timeout (a filesystem stalled
        for minutes), raises rather than returning with writes still in
        flight — close() is a durability barrier and must never lie."""
        dropped: List[Any] = []
        with self._cv:
            if not drain:
                dropped = list(self._pending)
                self._pending.clear()
            self._closed = True
            self._cv.notify_all()
            thread = self._thread
        if self._on_drop is not None:
            for old in dropped:
                self._on_drop(old)
        if thread is not None:
            thread.join(timeout=60.0)
            if thread.is_alive():
                raise RuntimeError(
                    f"{self._name}: writer failed to drain within 60s "
                    f"(a write is still in flight — data may not be "
                    f"durable)")
        with self._cv:
            self._raise_pending_locked()

    # --- writer thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._closed:
                    # Bounded idle tick (not a deadline): a missed
                    # notify can only cost one slice, never a wedge.
                    self._cv.wait(timeout=1.0)
                if not self._pending and self._closed:
                    return
                item = self._pending.popleft()
                self._busy = True
                self._cv.notify_all()   # unblock a backpressured submit
            try:
                self._write_fn(item)
            except BaseException as e:   # surfaced on the caller
                with self._cv:
                    if self._error is None:
                        self._error = e
                    else:
                        logger.warning("%s: additional write failure "
                                       "suppressed behind the first: %s",
                                       self._name, e)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _raise_pending_locked(self) -> None:
        if self._error is not None:
            err, self._error = self._error, None  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _cv
            raise err
