"""AsyncCheckpointer: snapshot-and-offload durable state, end to end.

The user-facing class of :mod:`horovod_tpu.ckpt` — what the elastic
tier and the training loop talk to:

* ``save(step, tree)`` costs the caller ONE device→host snapshot
  (:mod:`.snapshot`) and returns; a bounded background writer
  (:mod:`.writer`, ``HVD_TPU_CKPT_ASYNC`` / ``HVD_TPU_CKPT_INFLIGHT``)
  does the sharded write + digests + fsync (:mod:`.store`), coalescing
  back-to-back saves (drop-oldest-unwritten) when the disk is slower
  than the save cadence.  Writer failures surface on the next
  ``save``/``wait_until_finished``/``close``.
* ``journal_step(step, rng=…, sampler=…, knobs=…)`` appends one fsync'd
  line of step metadata (:mod:`.journal`) — cheap enough for every
  step, so recovery replays to the exact failed step.
* ``restore``/``restore_shard`` read the newest *intact* step (intact
  decided at manifest granularity), falling back deterministically and
  leaving a flight-recorder event when a newer step is damaged.
* ``resume()`` is the recovery entry point: newest intact snapshot +
  the journal tail past it + the exact step to end up at.

Save/restore stall and write time land in the obs registry
(``hvd_tpu_ckpt_save_stall_us`` / ``_write_us`` / ``_bytes_total`` /
``_inflight``), and the ``hvd_tpu_ckpt_save``/``_restore`` spans gain
``offload``/``write`` children (docs/tracing.md).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .errors import CheckpointCorruptionError
from .journal import StepJournal
from .manifest import ManifestError, RestorePlan, plan_restore
from .snapshot import BufferPool, Snapshot, is_snapshotable, take_snapshot
from .store import ShardStore
from .writer import AsyncWriter
from ..obs import trace as trace_mod
from ..utils.logging import get_logger

logger = get_logger(__name__)

__all__ = ["AsyncCheckpointer", "ResumeInfo"]


class ResumeInfo:
    """Everything recovery needs: the restored tree at
    ``snapshot_step``, the journal entries to replay (ordered, past the
    snapshot), and ``exact_step`` — where the run actually was when it
    died.  ``replay`` empty means the snapshot IS the exact step."""

    def __init__(self, *, tree: Any, snapshot_step: Optional[int],
                 replay: List[Dict[str, Any]], exact_step: int,
                 journal_intact: bool) -> None:
        self.tree = tree
        self.snapshot_step = snapshot_step
        self.replay = replay
        self.exact_step = exact_step
        self.journal_intact = journal_intact


def _resolved_config():
    from .. import basics
    from ..config import Config

    return basics.config() if basics.is_initialized() else Config.from_env()


class AsyncCheckpointer:
    """Async sharded durable state under ``directory``.

    ``world``/``rank``/``scheme`` declare the ownership partition the
    manifests record (``dp``: rank-0-only, as the reference examples
    gate it; ``zero``/``fsdp``: leaves byte-balanced across ranks).
    They default to the live world (or 1×``dp`` pre-init) and exist as
    parameters so elastic drills and benchmarks can simulate N→N′
    resizes on one controller.
    """

    def __init__(self, directory: str, *,
                 world: Optional[int] = None,
                 rank: Optional[int] = None,
                 scheme: str = "dp",
                 async_save: Optional[bool] = None,
                 inflight: Optional[int] = None,
                 verify: Optional[bool] = None,
                 max_to_keep: int = 3,
                 journal: bool = True,
                 fsync: bool = True) -> None:
        cfg = _resolved_config()
        if async_save is None:
            async_save = cfg.ckpt_async
        if inflight is None:
            inflight = cfg.ckpt_inflight
        if verify is None:
            verify = cfg.checkpoint_digest
        if world is None or rank is None:
            world = world if world is not None else self._live_world()
            rank = rank if rank is not None else self._live_rank(world)
        self._world = max(1, int(world))
        self._rank = int(rank)
        # The shard store's single-rename commit protocol has exactly
        # ONE writer per step, and the journal is one shared file: in
        # a real multi-controller world only the primary process
        # writes (every process may restore).  Simulated worlds
        # (world=N on one controller) are unaffected — there is one
        # process.
        self._is_writer = self._primary_process()
        self._scheme = scheme
        self._verify = bool(verify)
        self._max_to_keep = max(1, int(max_to_keep))
        self._store = ShardStore(directory, fsync=fsync)
        self._pool = BufferPool(int(inflight) + 1)
        self._writer = AsyncWriter(
            self._write_one, inflight=int(inflight),
            on_drop=self._drop) if async_save else None
        self._journal = StepJournal(
            os.path.join(self._store.directory, "journal.jsonl"),
            fsync=fsync) if journal else None
        import threading

        self._pending_lock = threading.Lock()
        self._pending_steps: set = set()   # guarded-by: _pending_lock

    @staticmethod
    def _live_world() -> int:
        try:
            from .. import basics

            if basics.is_initialized():
                from ..basics import size

                return int(size())
        except Exception:
            pass
        return 1

    @staticmethod
    def _primary_process() -> bool:
        try:
            import jax

            return int(jax.process_index()) == 0
        except Exception:
            return True

    @staticmethod
    def _live_rank(world: int) -> int:
        try:
            import jax

            return int(jax.process_index()) % max(1, world)
        except Exception:
            return 0

    # --- properties ----------------------------------------------------------

    @property
    def directory(self) -> str:
        return self._store.directory

    @property
    def journal(self) -> Optional[StepJournal]:
        return self._journal

    @property
    def async_save(self) -> bool:
        return self._writer is not None

    def latest_step(self) -> Optional[int]:
        steps = self._store.steps()
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        return self._store.steps()

    # --- save ----------------------------------------------------------------

    def save(self, step: int, tree: Any, *, force: bool = False) -> bool:
        """Snapshot ``tree`` and hand it to the writer; returns as soon
        as the host copy exists.  False when ``step`` is already
        committed (and ``force`` is off).  A writer failure from an
        EARLIER save raises here — async saves never fail silently."""
        from ..obs import instrument as _obs

        if not self._is_writer:
            # Non-primary controllers must not race the single-writer
            # commit (the losing os.replace would raise ENOTEMPTY and
            # poison the writer) nor N-fold-amplify the write.
            return False
        with trace_mod.span("hvd_tpu_ckpt_save",
                            args={"step": int(step), "async":
                                  self._writer is not None}):
            with self._pending_lock:
                queued = int(step) in self._pending_steps
            if not force and (queued
                              or int(step) in self._store.steps()):
                # Also catches a step still in the writer queue: its
                # eventual commit would make the store skip THIS tree
                # silently while we had returned True for it.
                return False
            if not is_snapshotable(tree):
                raise ValueError(
                    "tree spans non-addressable devices; the sharded "
                    "tier needs host-addressable leaves (use the "
                    "orbax-backed horovod_tpu.checkpoint tier for "
                    "multi-host shardings)")
            t0 = time.perf_counter()
            with trace_mod.span("hvd_tpu_ckpt_offload",
                                args={"step": int(step)}):
                snap = take_snapshot(tree, step=int(step),
                                     pool=self._pool)
            with self._pending_lock:
                self._pending_steps.add(int(step))
            try:
                if self._writer is not None:
                    self._writer.submit((snap, force))
                else:
                    self._write_one((snap, force))
            except BaseException:
                # An EARLIER save's failure surfacing here must not
                # leak this snapshot's pooled buffers.
                snap.release()
                self._unqueue(int(step))
                raise
            stall_us = (time.perf_counter() - t0) * 1e6
            _obs.on_ckpt_save(stall_us, snap.nbytes, self._inflight())
        return True

    def _inflight(self) -> int:
        return self._writer.depth() if self._writer is not None else 0

    def _unqueue(self, step: int) -> None:
        with self._pending_lock:
            self._pending_steps.discard(int(step))

    def _drop(self, item: Tuple[Snapshot, bool]) -> None:
        item[0].release()
        self._unqueue(item[0].step)

    def _write_one(self, item: Tuple[Snapshot, bool]) -> None:
        from ..obs import instrument as _obs

        snap, force = item
        try:
            t0 = time.perf_counter()
            with trace_mod.span("hvd_tpu_ckpt_write",
                                args={"step": snap.step,
                                      "nbytes": snap.nbytes}):
                manifest = self._store.write_step(
                    snap, world=self._world, scheme=self._scheme,
                    force=force)
                if manifest is not None:
                    self._prune()
            _obs.on_ckpt_write((time.perf_counter() - t0) * 1e6,
                               snap.nbytes)
        finally:
            snap.release()
            self._unqueue(snap.step)
            _obs.on_ckpt_inflight(self._inflight())

    def _prune(self) -> None:
        steps = self._store.steps()
        for old in steps[:-self._max_to_keep]:
            self._store.delete_step(old)

    # --- journal -------------------------------------------------------------

    def journal_step(self, step: int, *, rng: Any = None,
                     sampler: Any = None,
                     knobs: Optional[Dict[str, Any]] = None,
                     **extra: Any) -> None:
        """Append one step's replay metadata (no-op when the journal is
        disabled).  ``rng`` is any array-like key; ``sampler`` anything
        with a ``state_dict()`` (the elastic sampler's cursor) or an
        already-plain dict; ``knobs`` the autotune snapshot."""
        if self._journal is None or not self._is_writer:
            return
        meta: Dict[str, Any] = dict(extra)
        if rng is not None:
            meta["rng"] = np.asarray(rng).tolist()
        if sampler is not None:
            state_dict = getattr(sampler, "state_dict", None)
            sd = state_dict() if callable(state_dict) else sampler
            if isinstance(sd, dict) and "processed_indices" in sd:
                # The full index list grows by batch-size EVERY step —
                # journaling it raw would make the fsync'd line (and
                # the file) quadratic in run length.  The compact
                # cursor is sufficient for replay: the snapshot's
                # durable save carries the full cursor, and replay
                # re-steps the sampler deterministically from there.
                compact = {k: v for k, v in sd.items()
                           if k != "processed_indices"}
                compact["num_processed"] = len(sd["processed_indices"])
                sd = compact
            meta["sampler"] = sd
        if knobs is not None:
            meta["knobs"] = dict(knobs)
        self._journal.append(int(step), **meta)

    # --- restore -------------------------------------------------------------

    def _drain_for_read(self) -> None:
        """Land pending writes before reading; a writer failure here is
        recorded, not raised — restore IS the recovery path and must
        work with whatever is intact on disk."""
        if self._writer is None:
            return
        try:
            self._writer.wait_until_finished()
        except BaseException as e:
            from ..obs import flight as _flight

            _flight.record("ckpt_async_save_failed", error=str(e)[:300])
            logger.warning("pending async save failed (%s); restoring "
                           "from what is on disk", e)

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None,
                fallback: Optional[bool] = None) -> Any:
        """Restore the full tree at ``step`` (default: newest intact).
        An explicitly-requested step never falls back; the latest-step
        path degrades through older steps at manifest granularity,
        leaving a flight-recorder event per damaged step.  ``template``
        is accepted for API parity and used only to cast leaf dtypes."""
        from ..obs import instrument as _obs

        self._drain_for_read()
        with trace_mod.span("hvd_tpu_ckpt_restore",
                            args={"step": -1 if step is None
                                  else int(step)}):
            if fallback is None:
                fallback = step is None
            if step is not None and not fallback:
                tree = self._store.read_tree(int(step),
                                             verify=self._verify)
                return self._apply_template(tree, template)
            candidates = sorted(self._store.steps(), reverse=True)
            if step is not None:
                candidates = [s for s in candidates if s <= int(step)]
            if not candidates:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
            if not fallback:
                # The caller explicitly disabled degradation (fail fast
                # and alert): a damaged newest step must raise, never
                # silently hand back stale state.
                tree = self._store.read_tree(candidates[0],
                                             verify=self._verify)
                return self._apply_template(tree, template)
            errors: List[str] = []
            for s in candidates:
                try:
                    tree = self._store.read_tree(s, verify=self._verify)
                except (ManifestError, CheckpointCorruptionError,
                        OSError) as e:
                    errors.append(f"step {s}: {type(e).__name__}: {e}")
                    self._record_damage(s, e)
                    continue
                if errors:
                    logger.warning(
                        "restored checkpoint step %d after newer "
                        "step(s) failed: %s", s, "; ".join(errors))
                _obs.on_ckpt_restore(
                    sum(int(leaf.nbytes) for leaf in
                        _np_leaves(tree)))
                return self._apply_template(tree, template)
            raise CheckpointCorruptionError(
                f"no intact checkpoint under {self.directory}: "
                f"{'; '.join(errors)}")

    @staticmethod
    def _apply_template(tree: Any, template: Optional[Any]) -> Any:
        """Cast restored leaves into the template's structure/dtypes,
        matched BY KEY PATH — the restored tree is container-normalized
        (dicts flatten in sorted-key order) while a namedtuple template
        flattens in field order, so positional pairing would silently
        swap fields."""
        if template is None:
            return tree
        import jax

        from .snapshot import path_string

        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        by_path = {path_string(p): leaf for p, leaf in flat}
        t_flat, t_def = jax.tree_util.tree_flatten_with_path(template)
        if len(by_path) != len(t_flat):
            raise ValueError(
                f"template/checkpoint key mismatch: {len(t_flat)} "
                f"template leaves vs {len(by_path)} restored")
        cast = []
        for path, t_leaf in t_flat:
            key = path_string(path)
            if key not in by_path:
                raise ValueError(
                    f"template/checkpoint key mismatch: template leaf "
                    f"{key} not in the restored tree")
            cast.append(np.asarray(by_path[key],
                                   dtype=np.asarray(t_leaf).dtype))
        return jax.tree_util.tree_unflatten(
            t_def, cast)

    def _record_damage(self, step: int, err: BaseException) -> None:
        from ..obs import flight as _flight

        _flight.record("ckpt_step_damaged", step=int(step),
                       error=f"{type(err).__name__}: {str(err)[:200]}")
        logger.warning("checkpoint step %d unusable (%s); trying older "
                       "step", step, err)

    def restore_shard(self, *, rank: int, world: Optional[int] = None,
                      scheme: Optional[str] = None,
                      step: Optional[int] = None
                      ) -> Tuple[RestorePlan, Dict[str, np.ndarray]]:
        """One (possibly resized) rank's restore: re-derive ownership
        at the new ``world`` and move only this rank's bytes.  Returns
        the plan (metadata: files touched, bytes moved) and the
        ``{key-path: array}`` payload.  Same latest-intact fallback as
        :meth:`restore`."""
        self._drain_for_read()
        with trace_mod.span("hvd_tpu_ckpt_restore",
                            args={"rank": int(rank),
                                  "world": int(world or 0)}):
            from ..obs import instrument as _obs

            candidates = ([int(step)] if step is not None
                          else sorted(self._store.steps(), reverse=True))
            if not candidates:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
            errors: List[str] = []
            for s in candidates:
                try:
                    manifest = self._store.validate_step(s)
                    plan = plan_restore(manifest, rank=int(rank),
                                        world=world, scheme=scheme)
                    payload = self._store.read_shard(
                        s, plan, verify=self._verify)
                except (ManifestError, CheckpointCorruptionError,
                        OSError) as e:
                    if step is not None:
                        raise
                    errors.append(f"step {s}: {e}")
                    self._record_damage(s, e)
                    continue
                _obs.on_ckpt_restore(plan.nbytes)
                return plan, payload
            raise CheckpointCorruptionError(
                f"no intact checkpoint under {self.directory}: "
                f"{'; '.join(errors)}")

    # --- recovery ------------------------------------------------------------

    def resume(self) -> ResumeInfo:
        """Recovery entry point: restore the newest intact snapshot,
        then hand back the journal tail past it — the caller replays
        those steps (same rng keys, same sampler cursors) to land on
        ``exact_step`` with zero lost steps instead of silently
        rewinding to the snapshot."""
        from ..obs import flight as _flight

        tree = None
        snap_step: Optional[int] = None
        self._drain_for_read()
        candidates = sorted(self._store.steps(), reverse=True)
        errors: List[str] = []
        for s in candidates:
            try:
                tree = self._store.read_tree(s, verify=self._verify)
                snap_step = s
                break
            except (ManifestError, CheckpointCorruptionError,
                    OSError) as e:
                errors.append(f"step {s}: {e}")
                self._record_damage(s, e)
        replay: List[Dict[str, Any]] = []
        intact = True
        if self._journal is not None:
            entries, intact = self._journal.read()
            replay = self._journal.entries_after(
                snap_step if snap_step is not None else -1,
                entries=entries)
        if snap_step is None and not replay:
            raise FileNotFoundError(
                f"no intact checkpoint under {self.directory}"
                + (f" ({'; '.join(errors)})" if errors else ""))
        if snap_step is None:
            # Every snapshot is gone/damaged but the journal survived:
            # recovery starts from scratch and replays the WHOLE run's
            # metadata — still lands on the exact step, still no
            # silent rewind.
            logger.warning(
                "no intact snapshot under %s; journal alone drives "
                "recovery (%d steps to replay)", self.directory,
                len(replay))
        exact = (int(replay[-1]["step"]) if replay
                 else int(snap_step))
        _flight.record("ckpt_resume",
                       snapshot_step=-1 if snap_step is None
                       else int(snap_step),
                       exact_step=exact, replay=len(replay),
                       journal_intact=intact,
                       fallbacks=len(errors))
        logger.info("resume: snapshot step %s + %d journaled step(s) "
                    "→ exact step %d%s", snap_step, len(replay), exact,
                    "" if intact else " (journal tail torn)")
        return ResumeInfo(tree=tree, snapshot_step=snap_step,
                          replay=replay, exact_step=exact,
                          journal_intact=intact)

    # --- lifecycle -----------------------------------------------------------

    def wait_until_finished(self,
                            timeout: Optional[float] = None) -> None:
        """Barrier: every submitted save is on disk when this returns;
        raises the first writer failure otherwise."""
        if self._writer is not None:
            self._writer.wait_until_finished(timeout=timeout)

    def discard_pending(self) -> int:
        """Elastic-rollback hook: queued (unstarted) saves hold
        pre-rollback state — drop them and clear any stored writer
        error so recovery starts clean.  Returns the count dropped."""
        if self._writer is None:
            return 0
        return self._writer.discard_pending()

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close(drain=True)
        if self._journal is not None:
            self._journal.close()

    def __enter__(self) -> "AsyncCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        # On a clean exit surface writer errors; while an exception is
        # already unwinding, don't replace it with a secondary failure.
        if exc and exc[0] is not None:
            try:
                self.close()
            except BaseException:
                logger.warning("checkpoint close failed during "
                               "exception unwind (original error wins)")
            return
        self.close()


def _np_leaves(tree: Any) -> List[np.ndarray]:
    import jax

    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(tree)]
