"""Monolithic orbax tier (the ``horovod_tpu.checkpoint`` compat API).

This is the pre-``ckpt/`` checkpointer — orbax ``CheckpointManager``
whole-tree saves with digest sidecars and fallback-to-newest-intact —
kept as the compatibility surface (``horovod_tpu.checkpoint`` re-exports
it) and as the tier for trees the sharded store cannot hold (arrays
spanning non-addressable devices: orbax coordinates the distributed
write itself).

What changed from the monolithic era (ROADMAP item 5 / ISSUE 9):

* **Digesting never bills the step loop.**  ``save`` takes ONE host
  snapshot (:mod:`.snapshot`) and computes the sha256 sidecar from
  those buffers on a background digest thread — previously the digest
  re-pulled the full tree on the caller between steps.
* The ``hvd_tpu_ckpt_save`` span gains ``offload``/``write`` children,
  and save stall/bytes land in the obs registry.
* The ``checkpoint`` fault site's new modes map onto this layout:
  ``stall`` sleeps in the hook (a slow filesystem), ``crash-before-
  rename`` removes the step directory (a commit that never happened),
  ``partial-manifest`` deletes the step's smallest file (metadata/data
  split damage).  New-code paths should prefer
  :class:`horovod_tpu.ckpt.AsyncCheckpointer` (sharded manifests, step
  journal, bounded async writer).
"""

from __future__ import annotations

import os
from typing import Any, List, Optional

import jax

from . import snapshot as snapshot_mod
from .errors import CheckpointCorruptionError
from .snapshot import pytree_digest
from .writer import AsyncWriter
from .. import faults as faults_mod
from .._compat import sanitize_checkpoint_tree
from ..obs import trace as trace_mod
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy, retry_call

logger = get_logger(__name__)

__all__ = [
    "Checkpointer", "CheckpointCorruptionError", "pytree_digest",
    "save", "restore", "latest_step", "should_save_on_this_host",
]


def should_save_on_this_host() -> bool:
    """True on the process that should write host-local artifacts
    (reference examples: ``if hvd.rank() == 0: save_checkpoint()``)."""
    return jax.process_index() == 0


def _key_token(entry) -> str:
    return snapshot_mod._key_token(entry)


def _digestable(tree: Any) -> bool:
    """Digesting needs every leaf's bytes on this host — degrade to off
    for multi-host trees rather than crashing the save."""
    return snapshot_mod.is_snapshotable(tree)


class Checkpointer:
    """Async, step-numbered whole-tree pytree checkpoints in
    ``directory``.

    Wraps ``orbax.checkpoint.CheckpointManager`` with the framework's
    defaults: async writes (training continues while the previous step
    flushes), bounded retention, optional ``keep_period`` for
    long-horizon runs, and (``verify=True``) the digest-sidecar
    integrity tier — the digest computed ONCE from an offloaded host
    snapshot, on a background thread.  The managed pytree is whatever
    the caller passes — canonically ``{"params": ..., "opt_state": ...,
    "step": N}`` or an elastic ``TpuState``'s trees.
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 keep_period: Optional[int] = None,
                 async_save: bool = True,
                 verify: Optional[bool] = None,
                 restore_retries: int = 2):
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            keep_period=keep_period,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self._dir, options=options)
        if verify is None:
            from .. import basics

            verify = (basics.config().checkpoint_digest
                      if basics.is_initialized() else True)
        self._verify = bool(verify)
        self._restore_policy = RetryPolicy(attempts=max(1, restore_retries),
                                           base_delay_s=0.5, max_delay_s=5.0)
        self._digest_writer: Optional[AsyncWriter] = None
        # Pooled snapshot buffers for the digest path: without a pool,
        # hashing lagging the save cadence would hold one fresh
        # model-sized host copy per queued job.
        self._digest_pool = snapshot_mod.BufferPool(3)

    @property
    def directory(self) -> str:
        return self._dir

    # --- digest sidecars ----------------------------------------------------

    def _digest_dir(self) -> str:
        return os.path.join(self._dir, "digests")

    def _digest_path(self, step: int) -> str:
        return os.path.join(self._digest_dir(), f"{int(step)}.json")

    def _write_digest(self, step: int, digest: str, nleaves: int) -> None:
        # Tiny host-local JSON: the writer is the rank-0 controller (the
        # same host that gates every other host-local artifact).
        if not should_save_on_this_host():
            return
        import json

        os.makedirs(self._digest_dir(), exist_ok=True)
        tmp = self._digest_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": int(step), "digest": digest,
                       "nleaves": int(nleaves)}, f)
        os.replace(tmp, self._digest_path(step))

    # Sentinel returned by _read_digest for a sidecar whose real hash
    # never landed (the digest thread died with the process).
    _PENDING = "__pending__"

    def _write_pending_digest(self, step: int) -> None:
        """Synchronous, tiny marker written BEFORE the digest job is
        queued: if the process dies in the gap, restore sees "pending"
        and treats the step as unverifiable (falls back) instead of
        silently skipping verification for exactly the crash-recovery
        case the integrity tier exists for."""
        if not should_save_on_this_host():
            return
        import json

        os.makedirs(self._digest_dir(), exist_ok=True)
        tmp = self._digest_path(step) + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"step": int(step), "pending": True}, f)
        os.replace(tmp, self._digest_path(step))

    def _read_digest(self, step: int) -> Optional[str]:
        import json

        try:
            with open(self._digest_path(step)) as f:
                doc = json.load(f)
            if doc.get("pending"):
                return self._PENDING
            return doc["digest"]
        except (OSError, ValueError, KeyError):
            return None

    def _prune_digests(self) -> None:
        """Drop sidecars for steps retention already deleted."""
        if not should_save_on_this_host():
            return
        keep = {int(s) for s in self.all_steps()}
        try:
            names = os.listdir(self._digest_dir())
        except OSError:
            return
        for name in names:
            stem = name.partition(".")[0]
            if stem.isdigit() and int(stem) not in keep:
                try:
                    os.unlink(os.path.join(self._digest_dir(), name))
                except OSError:
                    pass

    def _digest_one(self, item) -> None:
        """Digest worker: sha256 from the snapshot's host buffers —
        the step loop never pays for hashing (ISSUE 9 satellite)."""
        step, snap = item
        try:
            self._write_digest(step, snap.digest(), len(snap.leaves))
            self._prune_digests()
        finally:
            snap.release()

    def _submit_digest(self, step: int, snap) -> None:
        if self._digest_writer is None:
            # coalesce=False: unlike checkpoint saves (newest wins), a
            # dropped digest job would silently skip verification for
            # its step — under load the queue backpressures instead.
            self._digest_writer = AsyncWriter(
                self._digest_one, inflight=2, coalesce=False,
                on_drop=lambda item: item[1].release(),
                name="hvd-tpu-ckpt-digest")
        self._digest_writer.submit((int(step), snap))

    # --- save / restore -----------------------------------------------------

    def save(self, step: int, tree: Any, *, force: bool = False) -> bool:
        """Write ``tree`` as checkpoint ``step`` (async by default) plus
        its digest sidecar.  Returns False if the manager's save policy
        skipped it."""
        with trace_mod.span("hvd_tpu_ckpt_save", args={"step": int(step)}):
            return self._traced_save(step, tree, force=force)

    def _traced_save(self, step: int, tree: Any, *, force: bool) -> bool:
        import time

        import orbax.checkpoint as ocp

        from ..obs import instrument as _obs

        t0 = time.perf_counter()
        tree = sanitize_checkpoint_tree(tree)
        # One host snapshot, taken only when a digest will be written
        # (every controller hashing would be O(model bytes) of wasted
        # device->host traffic per save) and only for host-addressable
        # trees.  The digest itself runs on the background worker.
        try:
            # Don't pay the O(model bytes) host copy for a save the
            # manager's policy will skip anyway (already-saved step,
            # save-interval miss); force bypasses the policy.
            will_save = force or bool(self._mgr.should_save(step))
        except Exception:
            will_save = True   # orbax API drift: fail open
        snap = None
        if will_save and self._verify and should_save_on_this_host():
            if _digestable(tree):
                with trace_mod.span("hvd_tpu_ckpt_offload",
                                    args={"step": int(step)}):
                    snap = snapshot_mod.take_snapshot(
                        tree, step=int(step), pool=self._digest_pool)
            else:
                logger.debug("checkpoint step %d: digest skipped (tree "
                             "spans non-addressable devices)", step)
        with trace_mod.span("hvd_tpu_ckpt_write",
                            args={"step": int(step)}):
            saved = self._mgr.save(step, args=ocp.args.StandardSave(tree),
                                   force=force)
        if saved and snap is not None:
            self._write_pending_digest(int(step))
            self._submit_digest(step, snap)
        elif snap is not None:
            snap.release()
        if saved and faults_mod._active is not None:
            # Every rank ticks its plan (site counters stay in lockstep)
            # but only ONE applies the damage: two ranks XOR-flipping
            # the same bytes would cancel out (a false-green chaos run),
            # and two unlinks of the same victim would crash the second.
            mode = faults_mod.on_checkpoint_save(int(step))
            if mode is not None and should_save_on_this_host():
                # The injected damage targets the *stored* artifact, so
                # the async write must land before we vandalize it.
                self._mgr.wait_until_finished()
                _damage_step_dir(self._dir, int(step), mode)
        _obs.on_ckpt_save((time.perf_counter() - t0) * 1e6,
                          snap.nbytes if snap is not None else 0,
                          self._digest_writer.depth()
                          if self._digest_writer is not None else 0)
        return saved

    def _restore_step(self, step: int, template: Optional[Any]) -> Any:
        import orbax.checkpoint as ocp

        # StandardRestore (with or without template) — a bare
        # ``mgr.restore(step)`` needs a handler registry on orbax >= 0.7
        # when the manager didn't perform the save itself (the
        # fresh-process resume path).
        return retry_call(
            lambda: self._mgr.restore(
                step, args=ocp.args.StandardRestore(template)),
            policy=self._restore_policy,
            retry_on=(OSError,),
            # A missing file (torn/partial write) is deterministic —
            # retrying it just delays the fallback scan.
            give_up_on=(FileNotFoundError,),
            describe=f"checkpoint restore step {step}",
        )

    def _verified_restore(self, step: int, template: Optional[Any]) -> Any:
        with trace_mod.span("hvd_tpu_ckpt_restore",
                            args={"step": int(step)}):
            got = self._restore_step(step, template)
            # Digest verification is byte-exact, so it only applies to
            # as-saved restores: a template legitimately *transforms* the
            # content (dtype casts, shardings — orbax restores into the
            # template's spec), which is not corruption.
            if self._verify and template is None:
                want = self._read_digest(step)
                if want == self._PENDING:
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} has a pending digest "
                        f"sidecar (a crash cut the digest write) — it "
                        f"cannot be verified; restore an older "
                        f"verified step or pass verify=False")
                if want is not None and _digestable(got) \
                        and pytree_digest(got) != want:
                    raise CheckpointCorruptionError(
                        f"checkpoint step {step} failed digest "
                        f"verification under {self._dir}")
            return got

    def restore(self, step: Optional[int] = None,
                template: Optional[Any] = None,
                fallback: Optional[bool] = None) -> Any:
        """Restore checkpoint ``step`` (default: latest).  ``template``
        (a matching pytree of arrays/shape-dtype structs) restores with
        the template's shardings — pass it in multi-chip runs so params
        land sharded instead of replicated on host.

        With ``fallback`` (default: on when ``step`` is None), a step
        that fails to restore or fails digest verification degrades to
        the newest older step that passes — a corrupted latest save must
        not brick the job.  An explicitly-requested step never falls
        back: the caller asked for *that* state.
        """
        # Land pending writes first, but never let a stored digest-
        # worker failure (disk full — plausibly the same incident
        # forcing this restore) brick the recovery path: record it and
        # read what is intact on disk.
        try:
            self.wait_until_finished()
        except BaseException as e:
            from ..obs import flight as _flight

            _flight.record("ckpt_async_save_failed", error=str(e)[:300])
            logger.warning("pending digest/save work failed (%s); "
                           "restoring from what is on disk", e)
        if fallback is None:
            fallback = step is None
        if step is not None:
            return self._verified_restore(step, template)
        candidates = sorted((int(s) for s in self.all_steps()), reverse=True)
        if not candidates:
            raise FileNotFoundError(f"no checkpoint found under {self._dir}")
        if not fallback:
            return self._verified_restore(candidates[0], template)
        # What counts as "this step is damaged, try an older one": digest
        # mismatch, I/O errors, and the decode/structure errors orbax
        # raises on torn files.  With a template, a ValueError is most
        # likely a template/checkpoint mismatch — a caller bug that would
        # fail identically on every step — so it propagates as itself.
        damage = (CheckpointCorruptionError, OSError, UnicodeDecodeError,
                  KeyError)
        if template is None:
            damage = damage + (ValueError,)
        errors: List[str] = []
        for s in candidates:
            try:
                got = self._verified_restore(s, template)
                if errors:
                    logger.warning(
                        "restored checkpoint step %d after newer step(s) "
                        "failed: %s", s, "; ".join(errors))
                return got
            except damage as e:
                errors.append(f"step {s}: {type(e).__name__}: {e}")
                from ..obs import flight as _flight

                _flight.record("ckpt_step_damaged", step=int(s),
                               error=f"{type(e).__name__}: {str(e)[:200]}")
                logger.warning("checkpoint step %d unusable (%s); trying "
                               "older step", s, e)
        raise CheckpointCorruptionError(
            f"no intact checkpoint under {self._dir}: {'; '.join(errors)}")

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        """Block until pending async saves AND digest sidecars hit
        storage (call before exiting, or before deleting the job's
        scratch space)."""
        self._mgr.wait_until_finished()
        if self._digest_writer is not None:
            self._digest_writer.wait_until_finished()

    def close(self) -> None:
        if self._digest_writer is not None:
            self._digest_writer.close(drain=True)
            self._digest_writer = None
        self._mgr.close()

    def __enter__(self) -> "Checkpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.wait_until_finished()
        self.close()


def _damage_step_dir(directory: str, step: int, mode: str) -> None:
    """Apply the fault plan's checkpoint damage (site ``checkpoint``) to
    the orbax layout: ``corrupt`` bit-flips the largest data file of the
    step; ``partial`` deletes it (a write that never finished);
    ``partial-manifest`` deletes the smallest file (the metadata/data
    split — orbax's per-step metadata dangling); ``crash-before-rename``
    removes the whole step directory (the atomic commit that never
    happened).  ``stall`` never reaches here — the fault hook sleeps."""
    import shutil

    step_dir = os.path.join(directory, str(step))
    if mode == "crash-before-rename":
        shutil.rmtree(step_dir, ignore_errors=True)
        logger.warning("fault: removed %s (commit never happened)",
                       step_dir)
        return
    victims: List[str] = []
    for root, _, files in os.walk(step_dir):
        for name in files:
            path = os.path.join(root, name)
            try:
                if os.path.getsize(path) > 0:
                    victims.append(path)
            except OSError:
                pass
    if not victims:
        logger.warning("fault: no files to damage under %s", step_dir)
        return
    if mode == "partial-manifest":
        victim = min(victims, key=os.path.getsize)
        try:
            os.unlink(victim)
        except FileNotFoundError:
            pass
        logger.warning("fault: deleted %s (metadata dangling)", victim)
        return
    victim = max(victims, key=os.path.getsize)
    if mode == "partial":
        try:
            os.unlink(victim)
        except FileNotFoundError:
            pass  # already damaged (e.g. a prior run of the plan)
        logger.warning("fault: deleted %s (partial write)", victim)
        return
    from .store import bitflip_middle

    flipped = bitflip_middle(victim)
    logger.warning("fault: corrupted %d bytes of %s", flipped, victim)


def save(directory: str, step: int, tree: Any) -> None:
    """One-shot synchronous save (convenience for scripts/tests)."""
    with Checkpointer(directory, async_save=False) as ckpt:
        ckpt.save(step, tree)


def restore(directory: str, step: Optional[int] = None,
            template: Optional[Any] = None) -> Any:
    """One-shot restore (convenience for scripts/tests)."""
    with Checkpointer(directory, async_save=False) as ckpt:
        return ckpt.restore(step, template)


def latest_step(directory: str) -> Optional[int]:
    with Checkpointer(directory, async_save=False) as ckpt:
        return ckpt.latest_step()
