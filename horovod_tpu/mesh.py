"""Global device mesh — the TPU-native replacement for the reference's
MPI/Gloo/NCCL communicator contexts (``horovod/common/mpi/mpi_context.cc``,
``gloo/gloo_context.cc``, ``nccl_operations.cc`` communicator bootstrap —
paths per SURVEY.md, reference mount empty, unverified).

Where the reference builds an ``MPI_COMM_WORLD`` plus per-process-set
sub-communicators and distributes ``ncclUniqueId``s, we build a single 1-D
:class:`jax.sharding.Mesh` over all addressable devices; process sets are
sub-meshes (see :mod:`horovod_tpu.process_sets`).  XLA then lowers
``psum``/``all_gather``/… over the mesh axis to ICI collectives within a
slice and DCN collectives across slices — the analogue of the reference's
hierarchical NCCL+MPI allreduce, chosen by the compiler instead of the
``HOROVOD_HIERARCHICAL_ALLREDUCE`` env var.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class GlobalMesh:
    """A 1-D mesh over every slot (device) plus host-side bookkeeping."""

    mesh: Mesh
    axis_name: str
    devices: Tuple[jax.Device, ...]

    @staticmethod
    def build(axis_name: str = "hvd") -> "GlobalMesh":
        devices = tuple(jax.devices())
        mesh = Mesh(np.asarray(devices, dtype=object), (axis_name,))
        return GlobalMesh(mesh=mesh, axis_name=axis_name, devices=devices)

    # --- slot arithmetic ---------------------------------------------------

    @property
    def size(self) -> int:
        return len(self.devices)

    @property
    def local_devices(self) -> List[jax.Device]:
        return [d for d in self.devices if d.process_index == jax.process_index()]

    @property
    def local_size(self) -> int:
        return len(self.local_devices)

    @property
    def process_first_slot(self) -> int:
        """Global index of this process's first device — the process's
        "rank" in the reference's one-slot-per-process worldview."""
        pid = jax.process_index()
        for i, d in enumerate(self.devices):
            if d.process_index == pid:
                return i
        return 0

    @property
    def local_rank(self) -> int:
        """Index of this process's first device among devices on the same
        host (≠0 only when several processes share a host)."""
        pid = jax.process_index()
        first = self.local_devices[0] if self.local_devices else None
        if first is None:
            return 0
        # Devices on this physical host, across processes, ordered by id.
        host_devices = [d for d in self.devices if getattr(d, "host_id", d.process_index) == getattr(first, "host_id", pid)]
        host_devices.sort(key=lambda d: d.id)
        return host_devices.index(first)

    @property
    def slots_per_process(self) -> List[int]:
        counts = [0] * jax.process_count()
        for d in self.devices:
            counts[d.process_index] += 1
        return counts

    # --- sharding helpers --------------------------------------------------

    def sharding(self, *spec) -> NamedSharding:
        """NamedSharding over this mesh: ``mesh.sharding('hvd')`` shards the
        leading axis across slots; ``mesh.sharding()`` replicates."""
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_leading(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(self.axis_name))

    def device_put_sharded(self, x) -> jax.Array:
        """Place a host array with leading dim == size so slot *i* holds
        slice ``x[i]`` — the canonical way tests materialise "each rank has
        its own tensor" in a single controller."""
        x = np.asarray(x)
        if x.shape[0] != self.size:
            raise ValueError(
                f"Leading dim {x.shape[0]} must equal world size {self.size}"
            )
        return jax.device_put(x, self.shard_leading())
