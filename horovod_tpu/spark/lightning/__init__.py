"""Lightning Spark estimator.

Reference: ``horovod/spark/lightning/`` (``TorchEstimator`` over a
``LightningModule`` — SURVEY.md §2.6, mount empty, unverified): the
module self-describes its optimization (``configure_optimizers``) and
step math (``training_step``/``validation_step``); the estimator
supplies data, the distributed world, and the fit loop.

TPU-native redesign: the estimator drives the **LightningModule
protocol**, not the pytorch-lightning package — ``training_step``,
``validation_step``, ``configure_optimizers`` are called duck-typed, so
any real ``pl.LightningModule`` works when lightning is installed AND
the whole pipeline is exercisable without it (same waiver pattern as
the mxnet binding; pytorch-lightning is not in this image).  The world,
data, and fit scaffolding are shared with the torch estimator
(``spark/common/backend.py``, ``spark/common/datamodule.py``).
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Dict, Optional

from ..common.backend import dispatch_fit
from ..common.params import EstimatorParams
from ..common.store import Store
from ..torch import TorchModel


def _resolve_optimizer(module):
    """``configure_optimizers`` contract forms (lightning docs): a bare
    optimizer, a list/tuple of optimizers, ([optimizers], [schedulers]),
    or {'optimizer': opt, ...}.  Single-optimizer training uses the
    first; anything unresolvable raises with the contract named."""
    cfg = module.configure_optimizers()
    if isinstance(cfg, dict):
        cfg = cfg.get("optimizer")
    if isinstance(cfg, (list, tuple)):
        if not cfg:
            raise ValueError("configure_optimizers returned no optimizer")
        first = cfg[0]
        if isinstance(first, (list, tuple)):   # ([opts], [scheds])
            if not first:
                raise ValueError("configure_optimizers returned no optimizer")
            first = first[0]
        cfg = first
    if cfg is None or not hasattr(cfg, "step"):
        raise ValueError(
            "configure_optimizers must yield a torch optimizer (got "
            f"{type(cfg).__name__}); supported forms: optimizer, "
            "[optimizers], ([optimizers], [schedulers]), "
            "{'optimizer': ...}")
    return cfg


def _train_fn(blob: bytes, train_path: str, val_path: Optional[str],
              spec: Dict[str, Any]):
    """Per-worker body (reference: ``lightning/remote.py``): the shared
    torch fit loop driven by the module's own step math."""
    from ..common.backend import torch_fit_loop

    module = pickle.loads(blob)
    optimizer = _resolve_optimizer(module)

    def train_step(m, batch, batch_idx):
        loss = m.training_step(batch, batch_idx)
        if isinstance(loss, dict):           # lightning allows {'loss': ...}
            loss = loss["loss"]
        return loss

    def val_step(m, val):
        if not callable(getattr(m, "validation_step", None)):
            return None
        vloss = m.validation_step(val, 0)
        if isinstance(vloss, dict):
            vloss = vloss.get("val_loss", vloss.get("loss"))
        # modules logging via self.log return None: skip the entry
        return None if vloss is None else float(vloss)

    return torch_fit_loop(module, optimizer, train_step=train_step,
                          val_step=val_step, train_path=train_path,
                          val_path=val_path, spec=spec)


class LightningEstimator(EstimatorParams):
    """Reference API shape: ``LightningEstimator(model=lightning_module,
    store=..., num_proc=N).fit(df) -> LightningModel``."""

    def __init__(self, model=None, input_shapes=None, **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.input_shapes = input_shapes

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("LightningEstimator requires model=")
        for hook in ("training_step", "configure_optimizers"):
            if not callable(getattr(self.model, hook, None)):
                raise TypeError(
                    f"model must implement the LightningModule protocol "
                    f"(missing {hook})")
        store = self._get("store")
        if store is None:
            raise ValueError("LightningEstimator requires store=")
        if not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "LightningModel":
        """Materialize ``df`` to the store, train with the module's own
        step math, return the fitted :class:`LightningModel`."""
        self._validate()
        for k, v in (params or {}).items():
            self._set(k, v)
        store: Store = self._get("store")
        run_id = self._get("run_id") or f"lightning-{uuid.uuid4().hex[:8]}"
        import cloudpickle   # local/duck classes travel by value

        blob = cloudpickle.dumps(self.model)
        history, state_dict = dispatch_fit(self, df, blob, _train_fn, run_id)

        trained = pickle.loads(blob)
        trained.load_state_dict(state_dict)
        store.write_serialized(
            os.path.join(store.get_checkpoint_path(run_id), "model.pt"),
            {k: v.numpy() for k, v in state_dict.items()})
        return LightningModel(model=trained, history=[history],
                              run_id=run_id,
                              feature_cols=self._get("feature_cols"))


class LightningModel(TorchModel):
    """The fitted Spark Transformer — a LightningModule is an
    ``nn.Module``, so inference is the torch transformer's forward."""
