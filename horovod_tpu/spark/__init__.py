"""horovod_tpu.spark — run training inside Spark executors.

Reference: ``horovod/spark/__init__.py`` (``horovod.spark.run``),
``runner.py``, ``driver/``, ``task/`` (SURVEY.md §2.6, mount empty,
unverified): the driver starts task services inside Spark executors via
a barrier stage, wires them into one training world, and runs ``fn`` on
every worker.

TPU-native redesign: Spark places the *controller processes*; the
collectives still ride XLA over ICI/DCN (``jax.distributed`` world
formed from the Spark task ranks), so the Spark layer is pure
control-plane — exactly the role the reference's driver/task RPC plays.
pyspark is not bundled in this image; the module imports cleanly, the
entry points raise a clear error without it (the reference similarly
degrades when built without Spark support).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, Optional, Sequence

from .common.store import FilesystemStore, LocalStore, Store  # noqa: F401


def _require_pyspark():
    try:
        import pyspark  # noqa: F401

        return pyspark
    except ImportError as e:
        raise ImportError(
            "horovod_tpu.spark requires pyspark (`pip install pyspark`); "
            "this environment does not bundle it"
        ) from e


def run(fn: Callable, args: Sequence = (), kwargs: Optional[Dict] = None,
        num_proc: Optional[int] = None, *, env: Optional[Dict] = None,
        start_timeout: float = 600.0, verbose: int = 1,
        use_gloo: bool = False, use_mpi: bool = False) -> list:
    """Reference: ``horovod.spark.run(fn, args=..., num_proc=N)`` — run
    ``fn`` on ``num_proc`` Spark tasks as one training world and return
    the list of results in rank order.

    ``use_gloo``/``use_mpi`` are accepted for signature parity and
    ignored: the world is always formed by ``jax.distributed`` (the
    TPU-native rendezvous; SURVEY.md §2.8).
    """
    pyspark = _require_pyspark()
    kwargs = kwargs or {}
    spark = pyspark.sql.SparkSession.builder.getOrCreate()
    sc = spark.sparkContext
    if num_proc is None:
        num_proc = int(sc.defaultParallelism)

    extra_env = dict(env or {})

    def mapper(index_iter):
        # Runs inside the Spark executor: become controller process
        # `index` of an `num_proc`-process jax.distributed world.
        from pyspark import BarrierTaskContext

        ctx = BarrierTaskContext.get()
        for index in index_iter:
            for k, v in extra_env.items():
                os.environ[k] = str(v)
            # jax.distributed binds the coordinator inside rank 0's task —
            # which runs on an executor node, not the driver — so rank 0
            # announces host:port from *its* node and the barrier
            # allGather publishes it (ADVICE r1; upstream horovod.spark
            # exchanges addresses the same way).
            mine = f"{_local_host()}:{_free_port()}" if index == 0 else ""
            addrs = ctx.allGather(mine)
            coordinator = next(a for a in addrs if a)
            os.environ["HVD_TPU_COORDINATOR_ADDR"] = coordinator
            os.environ["HVD_TPU_NUM_PROCESSES"] = str(num_proc)
            os.environ["HVD_TPU_PROCESS_ID"] = str(index)
            import horovod_tpu as hvd

            hvd.init()
            try:
                yield index, fn(*args, **kwargs)
            finally:
                hvd.shutdown()

    # Barrier mode: all tasks scheduled simultaneously or not at all —
    # a training world cannot start partially (reference uses Spark
    # barrier execution for the same reason).
    rdd = sc.parallelize(range(num_proc), num_proc)
    results = rdd.barrier().mapPartitions(mapper).collect()
    return [r for _, r in sorted(results)]


def _local_host() -> str:
    """Resolvable hostname of the machine this call runs on (an executor
    node when called from inside the barrier stage)."""
    from ..runner.common.network import resolvable_hostname

    return resolvable_hostname()


def _free_port() -> int:
    from ..runner.common.network import free_port

    return free_port()
