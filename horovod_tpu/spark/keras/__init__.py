"""Keras Spark estimator.

Reference: ``horovod/spark/keras/`` (SURVEY.md §2.6, mount empty,
unverified): ``KerasEstimator`` — a Spark ML Estimator that writes the
DataFrame to the store as Parquet (Petastorm in the reference), runs a
distributed ``model.fit`` over ``num_proc`` Spark tasks via
``horovod_tpu.spark.run``, and returns a ``KerasModel`` transformer
holding the trained weights.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..common.params import EstimatorParams
from ..common.store import Store


class KerasEstimator(EstimatorParams):
    """Reference API shape: ``KerasEstimator(model=..., optimizer=...,
    loss=..., store=..., num_proc=N).fit(df) -> KerasModel``."""

    def __init__(self, model=None, optimizer=None, custom_objects=None,
                 **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.optimizer = optimizer
        self.custom_objects = custom_objects or {}

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("KerasEstimator requires model=")
        if self._get("loss") is None:
            raise ValueError("KerasEstimator requires loss=")
        store = self._get("store")
        if store is not None and not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "KerasModel":
        """Distributed fit over a Spark DataFrame (requires pyspark)."""
        self._validate()
        from .. import _require_pyspark, run

        _require_pyspark()
        raise NotImplementedError(
            "DataFrame training requires the Parquet data-loader path, "
            "which needs pyspark at build time; this environment does not "
            "bundle pyspark.  Train with horovod_tpu.spark.run(fn) or the "
            "native data pipeline (horovod_tpu.data) instead.")


class KerasModel:
    """Reference: the fitted Spark Transformer — holds trained weights
    and applies the model to DataFrames."""

    def __init__(self, model=None, history: Optional[List[dict]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.history = history or []
        self.run_id = run_id

    def getModel(self):
        return self.model

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError("DataFrame inference requires pyspark")
