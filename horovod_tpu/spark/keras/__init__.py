"""Keras Spark estimator.

Reference: ``horovod/spark/keras/`` (``KerasEstimator`` → store Parquet
→ distributed ``model.fit`` over Spark tasks → ``KerasModel``
transformer; ``remote.py`` holds the per-worker training fn —
SURVEY.md §2.6, mount empty, unverified).

TPU-native redesign: the data tier is pyarrow Parquet in a Store
directory (replacing Petastorm); the world is ``horovod_tpu.spark.run``
when pyspark is present, and a single-controller in-process world
otherwise — so the whole store → shard → fit → transformer loop runs
(and is tested) without a Spark installation, pyspark gating only the
DataFrame/cluster entry points.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Dict, List, Optional

from ..common import datamodule as dm
from ..common.backend import PredictionTransformer, dispatch_fit
from ..common.params import EstimatorParams
from ..common.store import Store


def _serialize_keras(model, custom_objects=None) -> bytes:
    return pickle.dumps({"json": model.to_json(),
                         "weights": model.get_weights(),
                         "custom_objects": custom_objects or {}})


def _deserialize_keras(blob: bytes):
    import tensorflow as tf

    payload = pickle.loads(blob)
    model = tf.keras.models.model_from_json(
        payload["json"], custom_objects=payload.get("custom_objects") or None)
    model.set_weights(payload["weights"])
    return model


def _train_fn(model_blob: bytes, train_path: str, val_path: Optional[str],
              spec: Dict[str, Any]):
    """Per-worker training body (reference: ``keras/remote.py``).  Runs
    inside a ``spark.run`` task or directly in-process; returns
    ``(history_dict, weights)`` from every rank (rank 0's is used)."""
    import horovod_tpu as hvd
    import horovod_tpu.tensorflow.keras as hvd_keras
    import tensorflow as tf

    if not hvd.is_initialized():
        hvd.init()
    rank, world = hvd.cross_rank(), hvd.cross_size()

    data = dm.read_shard(train_path, rank, world)
    x = dm.stack_features(data, spec["feature_cols"])
    y = dm.stack_features(data, spec["label_cols"])
    val = None
    if val_path:
        vdata = dm.read_shard(val_path, rank, world)
        val = (dm.stack_features(vdata, spec["feature_cols"]),
               dm.stack_features(vdata, spec["label_cols"]))

    model = _deserialize_keras(model_blob)
    opt = tf.keras.optimizers.get(spec["optimizer"])
    opt = hvd_keras.DistributedOptimizer(
        opt, backward_passes_per_step=spec["backward_passes_per_step"])
    model.compile(optimizer=opt, loss=spec["loss"],
                  metrics=list(spec["metrics"]))
    # Workers must start identical (reference: broadcast at epoch 0);
    # weights here come from the same serialized blob, which is the same
    # guarantee.
    hist = model.fit(x, y, batch_size=spec["batch_size"],
                     epochs=spec["epochs"],
                     steps_per_epoch=spec["train_steps_per_epoch"],
                     validation_data=val,
                     verbose=spec["verbose"] if rank == 0 else 0,
                     shuffle=True)
    history = {k: [float(v) for v in vs] for k, vs in hist.history.items()}
    return history, model.get_weights()


class KerasEstimator(EstimatorParams):
    """Reference API shape: ``KerasEstimator(model=..., optimizer=...,
    loss=..., store=..., num_proc=N).fit(df) -> KerasModel``."""

    def __init__(self, model=None, optimizer=None, custom_objects=None,
                 **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.optimizer = optimizer or "sgd"
        self.custom_objects = custom_objects or {}

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("KerasEstimator requires model=")
        if self._get("loss") is None:
            raise ValueError("KerasEstimator requires loss=")
        store = self._get("store")
        if store is None:
            raise ValueError("KerasEstimator requires store=")
        if not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "KerasModel":
        """Materialize ``df`` to the store as Parquet, train over the
        world, return the fitted :class:`KerasModel` transformer.
        ``df`` may be a pyspark DataFrame (cluster path), or a pandas
        DataFrame / dict-of-columns / list-of-dicts (local path — no
        pyspark needed)."""
        self._validate()
        for k, v in (params or {}).items():
            self._set(k, v)
        store: Store = self._get("store")
        run_id = self._get("run_id") or f"keras-{uuid.uuid4().hex[:8]}"
        blob = _serialize_keras(self.model, self.custom_objects)
        history, weights = dispatch_fit(
            self, df, blob, _train_fn, run_id,
            extra_spec={
                "loss": self._get("loss"),
                "metrics": self._get("metrics"),
                "optimizer": self.optimizer,
                "train_steps_per_epoch": self._get("train_steps_per_epoch"),
                "verbose": self._get("verbose"),
            })

        trained = _deserialize_keras(blob)
        trained.set_weights(weights)
        store.write(os.path.join(store.get_checkpoint_path(run_id),
                                 "model.pkl"),
                    _serialize_keras(trained, self.custom_objects))
        return KerasModel(model=trained, history=[history], run_id=run_id,
                          feature_cols=self._get("feature_cols"))


class KerasModel(PredictionTransformer):
    """The fitted Spark Transformer (reference: ``KerasModel``) —
    inference through ``model.predict`` on the shared transformer."""

    def _predict(self, x):
        return self.model.predict(x, verbose=0)
