"""Estimator parameter plumbing.

Reference: ``horovod/spark/common/params.py`` (SURVEY.md §2.6, mount
empty, unverified) — the pyspark ``Params`` mixin defining the shared
estimator knobs (num_proc, batch_size, epochs, store, feature/label
cols…).  Implemented here without the pyspark dependency: typed
attributes with getters/setters matching the reference names, so
estimator code is identical with or without Spark present.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class EstimatorParams:
    """Shared estimator knobs with reference getter/setter names
    (``setNumProc``/``getNumProc`` etc. — camelCase per pyspark ML)."""

    _PARAMS: Dict[str, Any] = {
        "num_proc": None,
        "batch_size": 32,
        "epochs": 1,
        "backward_passes_per_step": 1,
        "store": None,
        "loss": None,
        "metrics": [],
        "feature_cols": ["features"],
        "label_cols": ["label"],
        "validation": None,
        "sample_weight_col": None,
        "compress_sparse": False,
        "shuffle_buffer_size": None,
        "verbose": 1,
        "run_id": None,
        "train_steps_per_epoch": None,
        "validation_steps_per_epoch": None,
    }

    def __init__(self, **kwargs: Any) -> None:
        self._values: Dict[str, Any] = dict(self._PARAMS)
        for k, v in kwargs.items():
            if k not in self._values:
                raise TypeError(f"unknown estimator param {k!r}; valid: "
                                f"{sorted(self._values)}")
            self._values[k] = v

    def _get(self, name: str) -> Any:
        return self._values[name]

    def _set(self, name: str, value: Any) -> "EstimatorParams":
        if name not in self._values:
            raise TypeError(f"unknown estimator param {name!r}")
        self._values[name] = value
        return self

    def __getattr__(self, item: str):
        # setFooBar / getFooBar accessors, reference (pyspark ML) style.
        if item.startswith(("set", "get")) and len(item) > 3:
            snake = _camel_to_snake(item[3:])
            if snake in self._PARAMS:
                if item.startswith("set"):
                    return lambda value: self._set(snake, value)
                return lambda: self._get(snake)
        raise AttributeError(item)

    def param_values(self) -> Dict[str, Any]:
        return dict(self._values)


def _camel_to_snake(name: str) -> str:
    out: List[str] = []
    for ch in name:
        if ch.isupper() and out:
            out.append("_")
        out.append(ch.lower())
    return "".join(out)
