"""Shared estimator fit scaffolding.

Reference: ``horovod/spark/common/backend.py`` (the SparkBackend the
estimators dispatch through — SURVEY.md §2.6, mount empty, unverified).
Every estimator's ``fit`` follows the same sequence: resolve the world
size, materialize train/validation data to the store as Parquet, build
the worker spec, and run the per-worker training fn over the cluster
(pyspark DataFrame) or in-process (local datasets).  Keeping it here
means the keras/torch/lightning tiers differ only in their training fn,
serialization, and checkpoint format.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from . import datamodule as dm


def dispatch_fit(estimator, df, blob: bytes, train_fn: Callable,
                 run_id: str,
                 extra_spec: Optional[Dict[str, Any]] = None) -> Tuple:
    """Run the store → shard → distributed-fit sequence; returns rank
    0's ``train_fn`` result."""
    store = estimator._get("store")
    num_proc = estimator._get("num_proc")
    if num_proc is None:
        # Cluster path: the scheduler's parallelism; local path: 1.
        num_proc = (df.sparkSession.sparkContext.defaultParallelism
                    if dm._is_spark_df(df) else 1)

    train_path = store.get_train_data_path(run_id)
    dm.materialize(df, train_path, num_shards=num_proc)
    val_path = None
    if estimator._get("validation") is not None:
        val_path = store.get_val_data_path(run_id)
        dm.materialize(estimator._get("validation"), val_path,
                       num_shards=num_proc)

    spec = {
        "feature_cols": estimator._get("feature_cols"),
        "label_cols": estimator._get("label_cols"),
        "batch_size": estimator._get("batch_size"),
        "epochs": estimator._get("epochs"),
        "backward_passes_per_step": estimator._get("backward_passes_per_step"),
    }
    spec.update(extra_spec or {})

    if dm._is_spark_df(df):
        from .. import run as spark_run

        results = spark_run(train_fn, args=(blob, train_path, val_path,
                                            spec), num_proc=num_proc)
    else:
        results = [train_fn(blob, train_path, val_path, spec)]
    return results[0]


class PredictionTransformer:
    """Shared fitted-model Transformer: forward-pass inference with a
    ``prediction`` column appended (reference: the Spark Transformer
    half of each estimator).  Subclasses override :meth:`_predict`."""

    def __init__(self, model=None, history=None, run_id=None,
                 feature_cols=None):
        self.model = model
        self.history = history or []
        self.run_id = run_id
        self.feature_cols = feature_cols or ["features"]

    def getModel(self):
        return self.model

    def _predict(self, x):
        """numpy features -> numpy predictions (torch forward default)."""
        import torch

        self.model.eval()
        with torch.no_grad():
            return self.model(torch.from_numpy(x)).numpy()

    def transform(self, df):
        """pandas/dict/list datasets work without pyspark; Spark
        DataFrames round-trip through pandas on the driver (cluster-
        scale inference is out of scope — the reference uses a pandas
        UDF there)."""
        import numpy as np

        pdf = df.toPandas() if dm._is_spark_df(df) else dm._to_pandas(df).copy()
        x = dm.stack_features(dm.to_columns(pdf), self.feature_cols)
        preds = self._predict(x)
        pdf["prediction"] = [np.asarray(p).tolist() for p in preds]
        return pdf
