"""Shared estimator fit scaffolding.

Reference: ``horovod/spark/common/backend.py`` (the SparkBackend the
estimators dispatch through — SURVEY.md §2.6, mount empty, unverified).
Every estimator's ``fit`` follows the same sequence: resolve the world
size, materialize train/validation data to the store as Parquet, build
the worker spec, and run the per-worker training fn over the cluster
(pyspark DataFrame) or in-process (local datasets).  Keeping it here
means the keras/torch/lightning tiers differ only in their training fn,
serialization, and checkpoint format.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from . import datamodule as dm


def dispatch_fit(estimator, df, blob: bytes, train_fn: Callable,
                 run_id: str,
                 extra_spec: Optional[Dict[str, Any]] = None) -> Tuple:
    """Run the store → shard → distributed-fit sequence; returns rank
    0's ``train_fn`` result."""
    store = estimator._get("store")
    num_proc = estimator._get("num_proc")
    if num_proc is None:
        # Cluster path: the scheduler's parallelism; local path: 1.
        num_proc = (df.sparkSession.sparkContext.defaultParallelism
                    if dm._is_spark_df(df) else 1)

    train_path = store.get_train_data_path(run_id)
    dm.materialize(df, train_path, num_shards=num_proc)
    val_path = None
    if estimator._get("validation") is not None:
        val_path = store.get_val_data_path(run_id)
        dm.materialize(estimator._get("validation"), val_path,
                       num_shards=num_proc)

    spec = {
        "feature_cols": estimator._get("feature_cols"),
        "label_cols": estimator._get("label_cols"),
        "batch_size": estimator._get("batch_size"),
        "epochs": estimator._get("epochs"),
        "backward_passes_per_step": estimator._get("backward_passes_per_step"),
    }
    spec.update(extra_spec or {})

    if dm._is_spark_df(df):
        from .. import run as spark_run

        results = spark_run(train_fn, args=(blob, train_path, val_path,
                                            spec), num_proc=num_proc)
    else:
        results = [train_fn(blob, train_path, val_path, spec)]
    return results[0]


def torch_fit_loop(model, optimizer, train_step, val_step,
                   train_path: str, val_path: Optional[str],
                   spec: Dict[str, Any]):
    """Shared per-worker torch loop (reference: the ``remote.py`` of each
    torch-family estimator): world init, rank-0 state broadcast,
    DistributedOptimizer wrap, shard read, seeded same-on-every-rank
    shuffle, epoch/batch history.  ``train_step(model, batch, batch_idx)``
    returns the loss tensor; ``val_step(model, (x, y))`` returns a float
    or None (skipped entry)."""
    import numpy as np
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvt

    if not hvd.is_initialized():
        hvd.init()
    rank, world = hvd.cross_rank(), hvd.cross_size()

    hvt.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvt.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        backward_passes_per_step=spec["backward_passes_per_step"])

    data = dm.read_shard(train_path, rank, world)
    x = torch.from_numpy(dm.stack_features(data, spec["feature_cols"]))
    y = torch.from_numpy(dm.stack_features(data, spec["label_cols"]))
    val = None
    if val_path:
        vdata = dm.read_shard(val_path, rank, world)
        val = (torch.from_numpy(dm.stack_features(vdata, spec["feature_cols"])),
               torch.from_numpy(dm.stack_features(vdata, spec["label_cols"])))

    bs = spec["batch_size"]
    history: Dict[str, Any] = {"loss": []}
    g = torch.Generator().manual_seed(1234)  # same shuffle on every rank
    for _ in range(spec["epochs"]):
        model.train()
        perm = torch.randperm(len(x), generator=g)
        losses = []
        # batch_idx restarts each epoch (the lightning contract; harmless
        # for the plain torch loss closure)
        for batch_idx, i in enumerate(range(0, len(x), bs)):
            idx = perm[i:i + bs]
            opt.zero_grad()
            loss = train_step(model, (x[idx], y[idx]), batch_idx)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        history["loss"].append(float(np.mean(losses)))
        if val is not None and val_step is not None:
            model.eval()
            with torch.no_grad():
                vloss = val_step(model, val)
            if vloss is not None:
                history.setdefault("val_loss", []).append(float(vloss))
    return history, model.state_dict()


class PredictionTransformer:
    """Shared fitted-model Transformer: forward-pass inference with a
    ``prediction`` column appended (reference: the Spark Transformer
    half of each estimator).  Subclasses override :meth:`_predict`."""

    def __init__(self, model=None, history=None, run_id=None,
                 feature_cols=None):
        self.model = model
        self.history = history or []
        self.run_id = run_id
        self.feature_cols = feature_cols or ["features"]

    def getModel(self):
        return self.model

    def _predict(self, x):
        """numpy features -> numpy predictions (torch forward default)."""
        import torch

        self.model.eval()
        with torch.no_grad():
            return self.model(torch.from_numpy(x)).numpy()

    def transform(self, df):
        """pandas/dict/list datasets work without pyspark; Spark
        DataFrames round-trip through pandas on the driver (cluster-
        scale inference is out of scope — the reference uses a pandas
        UDF there)."""
        import numpy as np

        pdf = df.toPandas() if dm._is_spark_df(df) else dm._to_pandas(df).copy()
        x = dm.stack_features(dm.to_columns(pdf), self.feature_cols)
        preds = self._predict(x)
        pdf["prediction"] = [np.asarray(p).tolist() for p in preds]
        return pdf
