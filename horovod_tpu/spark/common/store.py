"""Model/data stores for the Spark estimators.

Reference: ``horovod/spark/common/store.py`` (SURVEY.md §2.6, mount
empty, unverified): a ``Store`` abstracts where intermediate training
data, checkpoints, and final models live (local FS, HDFS, S3); the
estimator writes prepared data there and workers read it back.

TPU-native notes: the local filesystem store is fully functional (and
is what GCS-fuse-mounted buckets look like on TPU VMs); HDFS/S3 direct
drivers are out of scope for this image and raise with guidance.
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Optional


class Store:
    """Reference API: ``get_train_data_path``, ``get_val_data_path``,
    ``get_checkpoint_path``, ``get_logs_path``, ``saving_runs``…"""

    def __init__(self, prefix_path: str):
        self.prefix_path = prefix_path

    # -- layout ---------------------------------------------------------------

    def get_train_data_path(self, idx: Optional[Any] = None) -> str:
        return self._sub("intermediate_train_data", idx)

    def get_val_data_path(self, idx: Optional[Any] = None) -> str:
        return self._sub("intermediate_val_data", idx)

    def get_test_data_path(self, idx: Optional[Any] = None) -> str:
        return self._sub("intermediate_test_data", idx)

    def get_runs_path(self) -> str:
        return os.path.join(self.prefix_path, "runs")

    def get_run_path(self, run_id: str) -> str:
        return os.path.join(self.get_runs_path(), run_id)

    def get_checkpoint_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "checkpoint")

    def get_logs_path(self, run_id: str) -> str:
        return os.path.join(self.get_run_path(run_id), "logs")

    def _sub(self, name: str, idx: Optional[Any]) -> str:
        p = os.path.join(self.prefix_path, name)
        return p if idx is None else os.path.join(p, str(idx))

    # -- IO (subclass responsibility) -----------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def write(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def read_serialized(self, path: str) -> Any:
        return pickle.loads(self.read(path))

    def write_serialized(self, path: str, obj: Any) -> None:
        self.write(path, pickle.dumps(obj))

    @staticmethod
    def create(prefix_path: str) -> "Store":
        """Reference: ``Store.create(path)`` dispatches on scheme."""
        if prefix_path.startswith(("hdfs://", "s3://", "s3a://")):
            raise ValueError(
                f"{prefix_path!r}: HDFS/S3 stores are not available in this "
                "build; mount the bucket (gcsfuse) and use a local path, or "
                "subclass Store")
        return FilesystemStore(prefix_path)


class FilesystemStore(Store):
    """Local/NFS/FUSE-mounted filesystem store (reference:
    ``LocalStore``/``FilesystemStore``)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write(self, path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "wb") as f:
            f.write(data)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)


LocalStore = FilesystemStore
