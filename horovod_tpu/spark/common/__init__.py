"""Shared Spark-estimator plumbing (stores, params).

Reference: ``horovod/spark/common/`` (SURVEY.md §2.6, mount empty,
unverified).
"""

from .params import EstimatorParams  # noqa: F401
from .store import FilesystemStore, LocalStore, Store  # noqa: F401
