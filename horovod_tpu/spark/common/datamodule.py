"""Store-backed data materialization for the Spark estimators.

Reference: ``horovod/spark/common/util.py`` — ``prepare_data`` writes
the DataFrame to the store as Parquet and workers read their shard back
through Petastorm (SURVEY.md §2.6, mount empty, unverified).

TPU-native redesign: Petastorm is replaced by pyarrow Parquet directly —
the store path is a directory of row-group files; each worker reads the
files whose index ≡ its rank (mod world size).  Accepts a pyspark
DataFrame when pyspark is present (``df.write.parquet``), and any of
pandas DataFrame / dict-of-columns / list-of-dicts without it, so the
whole training pipeline is exercisable with no Spark installation.
"""

from __future__ import annotations

import glob
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np


def _is_spark_df(df: Any) -> bool:
    mod = type(df).__module__ or ""
    return mod.startswith("pyspark.")


def _to_pandas(df: Any):
    import pandas as pd

    if isinstance(df, pd.DataFrame):
        return df
    if isinstance(df, dict):
        return pd.DataFrame({k: list(v) for k, v in df.items()})
    if isinstance(df, (list, tuple)):
        return pd.DataFrame(list(df))
    raise TypeError(
        f"Unsupported dataset type {type(df).__name__}: expected a pyspark "
        f"or pandas DataFrame, dict of columns, or list of row dicts")


def materialize(df: Any, path: str, num_shards: int = 1) -> int:
    """Write ``df`` to ``path`` as a directory of Parquet part files —
    ``num_shards`` parts, rows spread round-robin so every part is
    non-empty whenever rows >= shards (fewer rows than shards writes
    only the non-empty parts; ``read_shard``'s wraparound then hands
    short worlds duplicate rows rather than empty shards); returns the
    row count."""
    if _is_spark_df(df):
        # Repartition so the file count matches the worker count — a
        # 1-partition DataFrame would otherwise give every rank the
        # same single file via the wraparound.
        df.repartition(max(num_shards, 1)).write.mode(
            "overwrite").parquet(path)
        return df.count()
    import pyarrow as pa
    import pyarrow.parquet as pq

    pdf = _to_pandas(df)
    os.makedirs(path, exist_ok=True)
    for old in glob.glob(os.path.join(path, "part-*.parquet")):
        os.remove(old)
    n = len(pdf)
    parts = max(num_shards, 1)
    for i in range(parts):
        chunk = pdf.iloc[i::parts]          # round-robin: balanced parts
        if len(chunk) == 0:
            continue
        table = pa.Table.from_pandas(chunk, preserve_index=False)
        pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))
    return n


def read_shard(path: str, shard: int, num_shards: int,
               columns: Optional[Sequence[str]] = None
               ) -> Dict[str, np.ndarray]:
    """Read this worker's shard (files with index ≡ shard mod
    num_shards) as a dict of stacked numpy columns.  List/array columns
    stack into ``[rows, ...]`` arrays."""
    import pyarrow.parquet as pq

    files = sorted(glob.glob(os.path.join(path, "part-*.parquet")) or
                   glob.glob(os.path.join(path, "*.parquet")))
    if not files:
        raise FileNotFoundError(f"no parquet part files under {path}")
    mine = [f for i, f in enumerate(files) if i % num_shards == shard]
    if not mine:          # fewer files than shards: wrap around
        mine = [files[shard % len(files)]]
    tables = [pq.read_table(f, columns=list(columns) if columns else None)
              for f in mine]
    out: Dict[str, np.ndarray] = {}
    for name in tables[0].column_names:
        col: List[Any] = []
        for t in tables:
            col.extend(t.column(name).to_pylist())
        out[name] = _stack_column(col)
    return out


def _stack_column(col: Sequence[Any]) -> np.ndarray:
    """Stack a python column into ``[rows, ...]`` (list/array values
    become a 2-D+ array; scalars a 1-D array; empty columns a [0]
    float32 array)."""
    if not len(col):
        return np.zeros((0,), np.float32)
    if isinstance(col[0], (list, tuple, np.ndarray)):
        return np.stack([np.asarray(v) for v in col])
    return np.asarray(col)


def to_columns(pdf) -> Dict[str, np.ndarray]:
    """A pandas DataFrame as stacked numpy columns (the transform-side
    twin of :func:`read_shard`)."""
    return {c: _stack_column(list(pdf[c])) for c in pdf.columns}


def stack_features(data: Dict[str, np.ndarray],
                   feature_cols: Sequence[str]) -> np.ndarray:
    """``[rows, F]`` feature matrix from one or more columns (scalar
    columns contribute one feature each; array columns are flattened)."""
    mats = []
    for c in feature_cols:
        a = data[c]
        mats.append(a.reshape(len(a), -1).astype(np.float32))
    return mats[0] if len(mats) == 1 else np.concatenate(mats, axis=1)
