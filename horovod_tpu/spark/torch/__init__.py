"""Torch Spark estimator.

Reference: ``horovod/spark/torch/`` (``TorchEstimator`` with a torch
``model``/``optimizer``/``loss`` triple; ``remote.py`` holds the
per-worker loop — SURVEY.md §2.6, mount empty, unverified).  Same
store → Parquet shard → distributed fit → transformer pipeline as the
Keras estimator (see ``spark/keras/__init__.py`` for the TPU-native
design notes); the shared scaffolding lives in
``spark/common/backend.py`` and the worker loop wraps the user
optimizer in ``horovod_tpu.torch.DistributedOptimizer``.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Dict, Optional

from ..common.backend import PredictionTransformer, dispatch_fit
from ..common.params import EstimatorParams
from ..common.store import Store


def _train_fn(blob: bytes, train_path: str, val_path: Optional[str],
              spec: Dict[str, Any]):
    """Per-worker body (reference: ``torch/remote.py``): the shared torch
    fit loop with the user's loss closure."""
    from ..common.backend import torch_fit_loop

    model, optimizer, loss_fn = pickle.loads(blob)
    return torch_fit_loop(
        model, optimizer,
        train_step=lambda m, batch, _i: loss_fn(m(batch[0]), batch[1]),
        val_step=lambda m, val: float(loss_fn(m(val[0]), val[1])),
        train_path=train_path, val_path=val_path, spec=spec)


class TorchEstimator(EstimatorParams):
    """Reference API shape: ``TorchEstimator(model=..., optimizer=...,
    loss=..., store=..., num_proc=N).fit(df) -> TorchModel``."""

    def __init__(self, model=None, optimizer=None, input_shapes=None,
                 **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.optimizer = optimizer
        self.input_shapes = input_shapes

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("TorchEstimator requires model=")
        if self.optimizer is None:
            raise ValueError("TorchEstimator requires optimizer=")
        if self._get("loss") is None:
            raise ValueError("TorchEstimator requires loss=")
        store = self._get("store")
        if store is None:
            raise ValueError("TorchEstimator requires store=")
        if not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "TorchModel":
        """Materialize ``df`` to the store, train, return the fitted
        :class:`TorchModel`.  ``df`` may be a pyspark DataFrame (cluster
        path) or pandas/dict/list-of-dicts (local path, no pyspark)."""
        self._validate()
        for k, v in (params or {}).items():
            self._set(k, v)
        store: Store = self._get("store")
        run_id = self._get("run_id") or f"torch-{uuid.uuid4().hex[:8]}"
        # Model, optimizer, and loss travel as one blob so the
        # optimizer's parameter references stay bound to the same model
        # instance on the worker (cloudpickle: locally-defined modules
        # and losses travel by value, Spark's own transport).
        import cloudpickle

        blob = cloudpickle.dumps(
            (self.model, self.optimizer, self._get("loss")))
        history, state_dict = dispatch_fit(self, df, blob, _train_fn, run_id)

        trained, _, _ = pickle.loads(blob)
        trained.load_state_dict(state_dict)
        store.write_serialized(
            os.path.join(store.get_checkpoint_path(run_id), "model.pt"),
            {k: v.numpy() for k, v in state_dict.items()})
        return TorchModel(model=trained, history=[history], run_id=run_id,
                          feature_cols=self._get("feature_cols"))


class TorchModel(PredictionTransformer):
    """The fitted Spark Transformer (reference: ``TorchModel``);
    forward-pass inference via the shared transformer base."""
