"""Torch Spark estimator.

Reference: ``horovod/spark/torch/`` (``TorchEstimator`` with a torch
``model``/``optimizer``/``loss`` triple; ``remote.py`` holds the
per-worker loop — SURVEY.md §2.6, mount empty, unverified).  Same
store → Parquet shard → distributed fit → transformer pipeline as the
Keras estimator (see ``spark/keras/__init__.py`` for the TPU-native
design notes); the shared scaffolding lives in
``spark/common/backend.py`` and the worker loop wraps the user
optimizer in ``horovod_tpu.torch.DistributedOptimizer``.
"""

from __future__ import annotations

import os
import pickle
import uuid
from typing import Any, Dict, List, Optional

from ..common import datamodule as dm
from ..common.backend import PredictionTransformer, dispatch_fit
from ..common.params import EstimatorParams
from ..common.store import Store


def _train_fn(blob: bytes, train_path: str, val_path: Optional[str],
              spec: Dict[str, Any]):
    """Per-worker loop (reference: ``torch/remote.py``): shard → minibatch
    SGD with gradient allreduce → (history, state_dict)."""
    import numpy as np
    import torch

    import horovod_tpu as hvd
    import horovod_tpu.torch as hvt

    if not hvd.is_initialized():
        hvd.init()
    rank, world = hvd.cross_rank(), hvd.cross_size()

    model, optimizer, loss_fn = pickle.loads(blob)
    hvt.broadcast_parameters(model.state_dict(), root_rank=0)
    opt = hvt.DistributedOptimizer(
        optimizer, named_parameters=model.named_parameters(),
        backward_passes_per_step=spec["backward_passes_per_step"])

    data = dm.read_shard(train_path, rank, world)
    x = torch.from_numpy(dm.stack_features(data, spec["feature_cols"]))
    y = torch.from_numpy(dm.stack_features(data, spec["label_cols"]))
    val = None
    if val_path:
        vdata = dm.read_shard(val_path, rank, world)
        val = (torch.from_numpy(dm.stack_features(vdata, spec["feature_cols"])),
               torch.from_numpy(dm.stack_features(vdata, spec["label_cols"])))

    bs = spec["batch_size"]
    history: Dict[str, List[float]] = {"loss": []}
    if val is not None:
        history["val_loss"] = []
    g = torch.Generator().manual_seed(1234)  # same shuffle on every rank
    for _ in range(spec["epochs"]):
        model.train()
        perm = torch.randperm(len(x), generator=g)
        losses = []
        for i in range(0, len(x), bs):
            idx = perm[i:i + bs]
            opt.zero_grad()
            loss = loss_fn(model(x[idx]), y[idx])
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        history["loss"].append(float(np.mean(losses)))
        if val is not None:
            model.eval()
            with torch.no_grad():
                history["val_loss"].append(
                    float(loss_fn(model(val[0]), val[1])))
    return history, model.state_dict()


class TorchEstimator(EstimatorParams):
    """Reference API shape: ``TorchEstimator(model=..., optimizer=...,
    loss=..., store=..., num_proc=N).fit(df) -> TorchModel``."""

    def __init__(self, model=None, optimizer=None, input_shapes=None,
                 **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.optimizer = optimizer
        self.input_shapes = input_shapes

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("TorchEstimator requires model=")
        if self.optimizer is None:
            raise ValueError("TorchEstimator requires optimizer=")
        if self._get("loss") is None:
            raise ValueError("TorchEstimator requires loss=")
        store = self._get("store")
        if store is None:
            raise ValueError("TorchEstimator requires store=")
        if not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "TorchModel":
        """Materialize ``df`` to the store, train, return the fitted
        :class:`TorchModel`.  ``df`` may be a pyspark DataFrame (cluster
        path) or pandas/dict/list-of-dicts (local path, no pyspark)."""
        self._validate()
        for k, v in (params or {}).items():
            self._set(k, v)
        store: Store = self._get("store")
        run_id = self._get("run_id") or f"torch-{uuid.uuid4().hex[:8]}"
        # Model, optimizer, and loss travel as one blob so the
        # optimizer's parameter references stay bound to the same model
        # instance on the worker (cloudpickle: locally-defined modules
        # and losses travel by value, Spark's own transport).
        import cloudpickle

        blob = cloudpickle.dumps(
            (self.model, self.optimizer, self._get("loss")))
        history, state_dict = dispatch_fit(self, df, blob, _train_fn, run_id)

        trained, _, _ = pickle.loads(blob)
        trained.load_state_dict(state_dict)
        store.write_serialized(
            os.path.join(store.get_checkpoint_path(run_id), "model.pt"),
            {k: v.numpy() for k, v in state_dict.items()})
        return TorchModel(model=trained, history=[history], run_id=run_id,
                          feature_cols=self._get("feature_cols"))


class TorchModel(PredictionTransformer):
    """The fitted Spark Transformer (reference: ``TorchModel``);
    forward-pass inference via the shared transformer base."""
