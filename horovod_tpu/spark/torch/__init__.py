"""Torch Spark estimator.

Reference: ``horovod/spark/torch/`` (SURVEY.md §2.6, mount empty,
unverified) — same estimator contract as the Keras one with a torch
``model``/``optimizer``/``loss`` triple.
"""

from __future__ import annotations

from typing import Any, List, Optional

from ..common.params import EstimatorParams
from ..common.store import Store


class TorchEstimator(EstimatorParams):
    """Reference API shape: ``TorchEstimator(model=..., optimizer=...,
    loss=..., store=..., num_proc=N).fit(df) -> TorchModel``."""

    def __init__(self, model=None, optimizer=None, input_shapes=None,
                 **params: Any) -> None:
        super().__init__(**params)
        self.model = model
        self.optimizer = optimizer
        self.input_shapes = input_shapes

    def _validate(self) -> None:
        if self.model is None:
            raise ValueError("TorchEstimator requires model=")
        if self._get("loss") is None:
            raise ValueError("TorchEstimator requires loss=")
        store = self._get("store")
        if store is not None and not isinstance(store, Store):
            raise TypeError("store must be a horovod_tpu.spark Store")

    def fit(self, df, params: Optional[dict] = None) -> "TorchModel":
        self._validate()
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError(
            "DataFrame training requires pyspark; train with "
            "horovod_tpu.spark.run(fn) or horovod_tpu.torch directly.")


class TorchModel:
    def __init__(self, model=None, history: Optional[List[dict]] = None,
                 run_id: Optional[str] = None):
        self.model = model
        self.history = history or []
        self.run_id = run_id

    def getModel(self):
        return self.model

    def transform(self, df):
        from .. import _require_pyspark

        _require_pyspark()
        raise NotImplementedError("DataFrame inference requires pyspark")
