"""Safe subprocess execution for launcher-spawned commands.

Reference: ``horovod/runner/common/util/safe_shell_exec.py`` (SURVEY.md
§2.5, mount empty, unverified): run worker commands in their own process
group, stream stdout/stderr through the parent, and guarantee the whole
group dies (TERM, then KILL after a grace period) when the command is
cancelled or the parent exits — no orphaned workers on job teardown.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5.0


def _forward(stream, sink, prefix: str = "") -> threading.Thread:
    def pump():
        for line in iter(stream.readline, b""):
            text = line.decode(errors="replace")
            sink.write(prefix + text if prefix else text)
            sink.flush()
        stream.close()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    return t


def terminate_process_group(proc: subprocess.Popen,
                            grace_s: float = GRACEFUL_TERMINATION_TIME_S) -> None:
    """TERM the whole group; KILL whatever survives the grace period."""
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    deadline = time.monotonic() + grace_s
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            break
        time.sleep(0.1)
    if proc.poll() is None:
        try:
            os.killpg(pgid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def execute(command: List[str], *, env: Optional[Dict[str, str]] = None,
            stdout=None, stderr=None, prefix: str = "",
            timeout_s: Optional[float] = None,
            events: Optional[List[threading.Event]] = None) -> int:
    """Run ``command`` in a fresh process group, forwarding output.

    ``events``: optional cancellation events; when any is set the group
    is terminated (reference: the driver's shutdown event fanning out to
    every task's running command).  Returns the exit code (negative on
    signal death, matching subprocess semantics).
    """
    proc = subprocess.Popen(
        command, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    pumps = [
        _forward(proc.stdout, stdout or sys.stdout, prefix),
        _forward(proc.stderr, stderr or sys.stderr, prefix),
    ]
    deadline = (time.monotonic() + timeout_s) if timeout_s else None
    try:
        while proc.poll() is None:
            if events and any(e.is_set() for e in events):
                terminate_process_group(proc)
                break
            if deadline and time.monotonic() > deadline:
                terminate_process_group(proc)
                raise TimeoutError(
                    f"command timed out after {timeout_s}s: {command}")
            time.sleep(0.1)
    except KeyboardInterrupt:
        terminate_process_group(proc)
        raise
    finally:
        for p in pumps:
            p.join(timeout=2)
    return proc.wait()
