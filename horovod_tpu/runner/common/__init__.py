"""Runner support services.

Reference: ``horovod/runner/common/`` (SURVEY.md §2.5, mount empty,
unverified) — the driver/task pre-flight mesh: HMAC-signed pickled RPC
over sockets, network-interface detection, and safe subprocess
execution used by the launcher before any worker calls ``init()``.
"""
