"""Socket RPC + network-interface detection for the runner.

Reference: ``horovod/runner/common/util/network.py`` and
``common/service/*`` (SURVEY.md §2.5, mount empty, unverified): a tiny
threaded TCP service speaking HMAC-signed pickled request/response
messages, plus helpers to enumerate local addresses so the driver can
pick interfaces every host can route to (on TPU pods this selects the
DCN-facing NIC; ICI is invisible to the host network stack).

Security note: frames are authenticated *before* unpickling — a frame
whose HMAC does not match the launcher-minted secret is dropped.
"""

from __future__ import annotations

import contextlib
import hmac
import hashlib
import os
import pickle
import socket
import socketserver
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

from ... import faults as faults_mod
from ...obs import trace as trace_mod
from ...utils.retry import RetryPolicy, retry_call
from .secret import DIGEST_LEN

_LEN = struct.Struct(">Q")


class PingRequest:
    pass


class PingResponse:
    """``clock_us`` is the peer's span clock (``obs.trace.now_us``) at
    response build — the raw material for Cristian's-algorithm clock
    offset estimation (``obs.trace.estimate_clock_offset``), which
    ``scripts/trace_merge.py`` uses to put every rank's spans on one
    time axis."""

    def __init__(self, service_name: str, source_address: str,
                 clock_us: Optional[float] = None):
        self.service_name = service_name
        self.source_address = source_address
        self.clock_us = clock_us


class AckResponse:
    pass


class MetricsRequest:
    """Scrape this process's telemetry registry (``horovod_tpu.obs``)
    over the HMAC control plane — answered by EVERY :class:`BasicService`
    (task agents, the serving endpoint, test services), so a metrics
    scrape needs no second port or credential.  ``fmt`` selects the
    rendered payload: ``"json"`` (snapshot only) or ``"prometheus"``
    (snapshot + text exposition)."""

    def __init__(self, fmt: str = "json"):
        self.fmt = fmt


class MetricsResponse:
    def __init__(self, snapshot: dict, prometheus: Optional[str] = None):
        self.snapshot = snapshot
        self.prometheus = prometheus


class TraceRequest:
    """Fetch this process's recent-span ring (``horovod_tpu.obs.trace``)
    over the HMAC control plane — answered by EVERY
    :class:`BasicService`, so ``scripts/trace_merge.py`` can collect a
    cross-rank trace with the credential it already holds.  ``clear``
    drains the ring (a collector that owns what it fetched)."""

    def __init__(self, clear: bool = False):
        self.clear = clear


class TraceResponse:
    """``spans`` is the ring snapshot (oldest first); ``now_us`` is the
    peer's span clock at response build (a second offset anchor beside
    ``PingResponse.clock_us``); ``rank``/``pid`` tag provenance."""

    def __init__(self, spans: list, now_us: float,
                 rank: Optional[int] = None, pid: Optional[int] = None):
        self.spans = spans
        self.now_us = now_us
        self.rank = rank
        self.pid = pid


class KvMigrateRequest:
    """One chunk of a live KV migration (disaggregated serving,
    ``serve/fleet/migration.py``): a prefill replica streams a
    request's paged KV blocks to a decode replica over this HMAC
    control plane — the block table is the transfer manifest, so only
    live, non-trash blocks move.  ``manifest`` rides the first frame
    (``seq == 0``) and carries per-block sha256 digests the receiver
    verifies before binding anything into its own pool; ``k_blocks`` /
    ``v_blocks`` are ``[n_layer, frame_blocks, block, H, D]`` numpy
    arrays, chunked so each frame stays under
    ``HVD_TPU_FLEET_MIGRATE_CHUNK`` bytes.

    Tensor-parallel senders (docs/tp_serving.md) split the transfer
    head-wise into ``n_shards`` independent streams — frame arrays then
    carry only that shard's ``H/tp`` heads, ``seq``/``total`` count
    within the shard, and the manifest's ``shard_digests`` verify each
    stream before the receiver concatenates heads back together."""

    def __init__(self, request_id: str, seq: int, total: int,
                 k_blocks, v_blocks, manifest: Optional[dict] = None,
                 shard: int = 0, n_shards: int = 1):
        self.request_id = request_id
        self.seq = seq
        self.total = total
        self.k_blocks = k_blocks
        self.v_blocks = v_blocks
        self.manifest = manifest
        self.shard = shard
        self.n_shards = n_shards


class KvMigrateResponse:
    """Per-frame ack; the FINAL frame's response reports the whole
    transfer: ``error`` is None once the digests verified and the
    request was adopted into the decode replica's batcher, else
    ``digest_mismatch`` / ``busy`` / ``draining`` / ``replica_dead`` —
    the sender falls back to decoding locally (never wrong tokens)."""

    def __init__(self, request_id: str, error: Optional[str] = None):
        self.request_id = request_id
        self.error = error


class CollectRequest:
    """Fetch the finished generation a migrated request produced on
    this (decode) replica; blocks until the adopted request completes
    and answers with a ``GenerateResponse``."""

    def __init__(self, request_id: str):
        self.request_id = request_id


class DrainRequest:
    """Stop admitting new work on this serving replica (drain-and-
    retire lifecycle, ``serve/fleet/controller.py``): queued and
    in-flight requests finish, new submissions answer ``draining`` so
    the router shifts load elsewhere.  ``cancel=True`` reverses an
    in-progress drain (the abandon path when the retire turns out
    impossible).  Answered with ``AckResponse``."""

    def __init__(self, reason: str = "", cancel: bool = False):
        self.reason = reason
        self.cancel = cancel


class DropConnection(Exception):
    """Raised from a ``BasicService._handle`` override to close the
    connection without writing a response — the wire signature of a
    crashed peer (used by the serving endpoint's ``serve:mode=drop``
    fault site; clients see a mid-frame ConnectionError and retry)."""


def local_addresses() -> Dict[str, List[str]]:
    """{interface: [ipv4...]} for all non-loopback interfaces (plus
    loopback itself, which single-host runs rely on)."""
    import psutil

    out: Dict[str, List[str]] = {}
    for nic, addrs in psutil.net_if_addrs().items():
        v4 = [a.address for a in addrs if a.family == socket.AF_INET]
        if v4:
            out[nic] = v4
    return out


def routable_addresses(include_loopback: bool = True) -> List[str]:
    addrs = [ip for ips in local_addresses().values() for ip in ips]
    if not include_loopback:
        addrs = [a for a in addrs if not a.startswith("127.")]
    return addrs


def resolvable_hostname() -> str:
    host = socket.gethostname()
    try:
        socket.gethostbyname(host)
        return host
    except OSError:
        return "127.0.0.1"


def free_port(host: str = "0.0.0.0") -> int:
    """Probe a currently-free TCP port on this machine (the usual
    bind-port-0 race applies: claim it promptly)."""
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def _sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


def write_message(sock: socket.socket, obj: Any, key: bytes) -> None:
    payload = pickle.dumps(obj)
    frame = _sign(key, payload) + payload
    sock.sendall(_LEN.pack(len(frame)) + frame)


def read_message(sock: socket.socket, key: bytes) -> Any:
    header = _read_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > 64 * 1024 * 1024:
        raise ValueError(f"RPC frame too large: {length}")
    frame = _read_exact(sock, length)
    digest, payload = frame[:DIGEST_LEN], frame[DIGEST_LEN:]
    if not hmac.compare_digest(digest, _sign(key, payload)):
        raise PermissionError("RPC frame failed HMAC authentication")
    return pickle.loads(payload)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return buf


class BasicService:
    """Threaded TCP request/response service (reference:
    ``network.BasicService``).  Subclasses override ``_handle``."""

    def __init__(self, name: str, key: bytes, host: str = "0.0.0.0",
                 nics: Optional[List[str]] = None):
        self.name = name
        self._key = key
        self._nics = list(nics) if nics else None
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    req = read_message(self.request, outer._key)
                except (PermissionError, ConnectionError, ValueError):
                    return  # unauthenticated/broken peer: drop silently
                # Distributed tracing: a request carrying a propagated
                # context gets a server span parented to the caller's
                # client span, installed as this handler thread's
                # current context — work the handler delegates further
                # (nested RPCs, batcher submissions) parents under it.
                ctx = trace_mod.extract(req)
                span = (trace_mod.span("hvd_tpu_rpc_server", parent=ctx,
                                       kind="server",
                                       args={"req": type(req).__name__,
                                             "service": outer.name})
                        if ctx is not None and trace_mod.enabled()
                        else contextlib.nullcontext())
                try:
                    with span:
                        resp = outer._handle(req, self.client_address)
                except DropConnection:
                    return  # handler chose to die on the wire: no reply
                try:
                    write_message(self.request, resp, outer._key)
                except OSError:
                    return  # peer gone before the reply: routine at scale

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, 0), _Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name=f"{name}-service")
        self._thread.start()

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def addresses(self) -> List[Tuple[str, int]]:
        """Every (ip, port) a client could try.  With ``nics`` set at
        construction (reference: ``horovodrun --network-interfaces``),
        advertisement restricts to those interfaces plus loopback
        (single-host runs keep working); an interface name matching
        nothing raises immediately — a typo'd NIC must fail loudly,
        not as a registration timeout minutes later."""
        if self._nics:
            per_nic = local_addresses()
            unknown = [n for n in self._nics if n not in per_nic]
            if unknown:
                raise ValueError(
                    f"--network-interfaces names unknown interface(s) "
                    f"{unknown}; available: {sorted(per_nic)}")
            ips = [ip for nic in self._nics for ip in per_nic[nic]]
            ips += [ip for addrs in per_nic.values() for ip in addrs
                    if ip.startswith("127.") and ip not in ips]
            return [(ip, self.port) for ip in ips]
        return [(ip, self.port) for ip in routable_addresses()]

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, PingRequest):
            return PingResponse(self.name, client_address[0],
                                clock_us=trace_mod.now_us())
        if isinstance(req, MetricsRequest):
            from ...obs import export as _obs_export

            return MetricsResponse(
                snapshot=_obs_export.json_snapshot(),
                prometheus=(_obs_export.render_prometheus()
                            if getattr(req, "fmt", "json") == "prometheus"
                            else None))
        if isinstance(req, TraceRequest):
            return TraceResponse(
                spans=trace_mod.snapshot(clear=getattr(req, "clear", False)),
                now_us=trace_mod.now_us(), rank=trace_mod.process_rank(),
                pid=os.getpid())
        return AckResponse()

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)


def _default_rpc_policy() -> RetryPolicy:
    """The unified control-plane retry policy: ``HVD_TPU_RPC_RETRIES``
    attempts with ``HVD_TPU_RPC_BACKOFF`` jittered exponential backoff.
    The resolved Config wins when this process ran ``hvd.init``;
    launcher/agent processes (which never init) parse the env afresh —
    same parser, same defaults, no drift."""
    from ... import basics
    from ...config import Config

    cfg = basics.config() if basics.is_initialized() else Config.from_env()
    return RetryPolicy(attempts=max(1, cfg.rpc_retries),
                       base_delay_s=cfg.rpc_backoff_seconds,
                       max_delay_s=5.0)


class BasicClient:
    """Client side; tries each candidate address until one answers the
    ping (reference: the driver probing every task address to find a
    routable interface).

    Post-probe requests retry under the shared policy (jittered
    exponential backoff): a dropped connection or slow peer is routine
    at fleet scale, and a registration lost to one TCP RST otherwise
    costs the whole launch.  The probe itself stays single-shot per
    address (dead candidates are expected — that's what probing is),
    and ``ping()`` stays single-shot because liveness accounting
    (missed-ping counters) owns its own schedule.

    ``name=None`` is the diagnostic wildcard (``scripts/trace_merge.py``
    scraping whatever service owns a port): the probe accepts whichever
    peer answers and adopts its advertised ``service_name``.

    ``probe=False`` skips the construction-time probe and uses the
    first address directly: the fleet telemetry collector
    (``obs/collector.py``) builds a client per replica per scrape
    round under ONE shared deadline, and a blocking ping against a
    dead replica would spend the whole ``probe_timeout`` before the
    real request even starts — the scrape's own request is the probe.
    """

    def __init__(self, name: Optional[str],
                 addresses: List[Tuple[str, int]],
                 key: bytes, probe_timeout: float = 5.0,
                 retry_policy: Optional[RetryPolicy] = None,
                 probe: bool = True):
        self.name = name
        self._key = key
        self._timeout = probe_timeout
        self._retry_policy = retry_policy or _default_rpc_policy()
        if probe:
            self._address = self._probe(addresses)
        else:
            if not addresses:
                raise ValueError("probe=False needs at least one address")
            self._address = tuple(addresses[0])

    @property
    def address(self) -> Tuple[str, int]:
        return self._address

    def _probe(self, addresses) -> Tuple[str, int]:
        errs = []
        for addr in addresses:
            try:
                resp = self._call(PingRequest(), addr)
                if isinstance(resp, PingResponse) \
                        and self.name in (None, resp.service_name):
                    if self.name is None:
                        self.name = resp.service_name
                    return tuple(addr)
            except OSError as e:
                errs.append((addr, e))
        raise ConnectionError(
            f"no address of service {self.name or '<any>'!r} "
            f"answered: {errs}")

    def _call(self, req: Any, addr: Optional[Tuple[str, int]] = None,
              timeout: Optional[float] = None) -> Any:
        # Distributed tracing: every control-plane exchange is a client
        # span (child of the calling thread's step/request trace, or a
        # fresh root for unparented calls — elastic driver chatter stays
        # visible), with the context propagated on the request so the
        # peer's server span parents correctly across the process
        # boundary.
        if not trace_mod.enabled():
            return self._call_inner(req, addr, timeout)
        with trace_mod.span("hvd_tpu_rpc_client", kind="client",
                            args={"req": type(req).__name__,
                                  "service": self.name}) as ctx:
            trace_mod.inject(req, ctx)
            return self._call_inner(req, addr, timeout)

    def _call_inner(self, req: Any, addr: Optional[Tuple[str, int]] = None,
                    timeout: Optional[float] = None) -> Any:
        # Fault site "rpc": drop (ConnectionError before the write — the
        # retry policy's job to absorb) or delay (a slow peer).
        if faults_mod._active is not None:
            faults_mod.on_rpc(type(req).__name__)
        addr = addr or self._address
        with socket.create_connection(addr, timeout=self._timeout) as sock:
            if timeout is not None:
                # Connect under the snappy probe timeout; wait for the
                # *response* as long as the request legitimately takes
                # (a serving generate runs for seconds — a 5s read
                # timeout would misread every slow answer as a death).
                sock.settimeout(timeout)
            write_message(sock, req, self._key)
            return read_message(sock, self._key)

    def request(self, req: Any, *, idempotent: bool = True,
                timeout: Optional[float] = None) -> Any:
        """One request/response exchange, retried under the unified
        policy (OSError covers refused/reset/timed-out sockets).

        ``idempotent=False`` disables the retry: re-sending a request
        whose *response* was lost would re-execute its side effect
        (e.g. a run-command landing twice) — for those, one attempt and
        let the caller own the ambiguity.  ``timeout`` overrides the
        per-response socket timeout (connect keeps the probe timeout)."""
        if not idempotent:
            return self._call(req, timeout=timeout)
        return retry_call(
            lambda: self._call(req, timeout=timeout),
            policy=self._retry_policy,
            retry_on=(OSError,),
            describe=f"rpc {type(req).__name__} -> {self.name}",
        )

    def ping(self) -> PingResponse:
        return self._call(PingRequest())
