"""Driver/task pre-flight services.

Reference: ``horovod/runner/common/service/driver_service.py`` +
``task_service.py`` (SURVEY.md §2.5/§3.4, mount empty, unverified).
Before any worker calls ``init()``, the launcher runs a *driver service*
on the controlling host and a *task service* per target host.  Tasks
register with the driver; the driver probes task→task connectivity and
intersects the interfaces every pair can route (the reference's common-
NIC selection); then tasks are told to exec the worker command.

On TPU pods the platform does placement, so this mesh's job narrows to:
verify mutual reachability over DCN, agree on the coordinator address
for ``jax.distributed``, and fan the run command out — but the protocol
is kept so self-managed (non-GKE) fleets work like the reference.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .network import (
    AckResponse, BasicClient, BasicService, PingRequest, PingResponse,
)
from .safe_shell_exec import execute


class RegisterTaskRequest:
    def __init__(self, index: int, addresses: List[Tuple[str, int]],
                 hostname: str,
                 coordinator_port: Optional[int] = None):
        self.index = index
        self.addresses = addresses
        self.hostname = hostname
        # A free port the agent reserved on ITS host: if this task hosts
        # global rank 0, the jax.distributed coordinator binds here.
        self.coordinator_port = coordinator_port


class AllTaskAddressesRequest:
    def __init__(self, index: int):
        self.index = index


class AllTaskAddressesResponse:
    def __init__(self, all_addresses: Dict[int, List[Tuple[str, int]]]):
        self.all_addresses = all_addresses


class ProbePeerRequest:
    def __init__(self, peer_index: int,
                 peer_addresses: List[Tuple[str, int]]):
        self.peer_index = peer_index
        self.peer_addresses = peer_addresses


class ProbePeerResponse:
    def __init__(self, reachable_address: Optional[Tuple[str, int]]):
        self.reachable_address = reachable_address


class RunCommandRequest:
    def __init__(self, command: List[str], env: Dict[str, str]):
        self.command = command
        self.env = env


class CommandExitCodeRequest:
    pass


class CommandExitCodeResponse:
    def __init__(self, done: bool, exit_code: Optional[int]):
        self.done = done
        self.exit_code = exit_code


class RunDistributedCommandRequest:
    """Exec the worker command once per local slot, each wired into the
    shared ``jax.distributed`` world via the launcher env contract
    (reference: gloo_run sends each host its per-slot commands with the
    Gloo rendezvous env)."""

    def __init__(self, command: List[str], env: Dict[str, str],
                 ranks: List[int], world_size: int, coordinator: str):
        self.command = command
        self.env = env
        self.ranks = ranks
        self.world_size = world_size
        self.coordinator = coordinator


class DistributedExitCodesRequest:
    pass


class DistributedExitCodesResponse:
    def __init__(self, codes: Dict[int, Optional[int]]):
        self.codes = codes  # rank -> exit code (None while running)


class AbortCommandRequest:
    pass


class AgentShutdownRequest:
    pass


class DriverService(BasicService):
    """Collects task registrations and answers the full address table
    (reference: ``HorovodRunDriverService``)."""

    def __init__(self, num_tasks: int, key: bytes, name: str = "driver",
                 nics=None):
        super().__init__(name, key, nics=nics)
        self._num_tasks = num_tasks
        self._tasks: Dict[int, RegisterTaskRequest] = {}
        self._cv = threading.Condition()

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, RegisterTaskRequest):
            with self._cv:
                self._tasks[req.index] = req
                self._cv.notify_all()
            return AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            with self._cv:
                return AllTaskAddressesResponse(
                    {i: t.addresses for i, t in self._tasks.items()})
        return super()._handle(req, client_address)

    def wait_for_initial_registration(self, timeout_s: float = 120.0) -> None:
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._tasks) >= self._num_tasks,
                timeout=timeout_s)
        if not ok:
            missing = sorted(set(range(self._num_tasks)) - set(self._tasks))
            raise TimeoutError(
                f"tasks {missing} did not register within {timeout_s}s")

    def task_addresses(self) -> Dict[int, List[Tuple[str, int]]]:
        with self._cv:
            return {i: t.addresses for i, t in self._tasks.items()}

    def task_hostnames(self) -> Dict[int, str]:
        with self._cv:
            return {i: t.hostname for i, t in self._tasks.items()}

    def task_coordinator_ports(self) -> Dict[int, Optional[int]]:
        with self._cv:
            return {i: getattr(t, "coordinator_port", None)
                    for i, t in self._tasks.items()}


class TaskService(BasicService):
    """Per-host agent: answers pings, probes peers on request, and execs
    the worker command (reference: ``HorovodRunTaskService``)."""

    def __init__(self, index: int, key: bytes, name: Optional[str] = None,
                 nics=None):
        super().__init__(name or f"task-{index}", key, nics=nics)
        self.index = index
        self._key_bytes = key
        self._cmd_thread: Optional[threading.Thread] = None
        self._exit_code: Optional[int] = None
        self._abort = threading.Event()
        self._rank_threads: Dict[int, threading.Thread] = {}
        self._rank_codes: Dict[int, Optional[int]] = {}
        self.shutdown_requested = threading.Event()
        self._coord_sock = None

    @property
    def command_started(self) -> bool:
        """True once any (single or distributed) command was launched."""
        return self._cmd_thread is not None or bool(self._rank_threads)

    def reserve_coordinator_port(self) -> int:
        """Bind (and HOLD) a listening socket for the jax.distributed
        coordinator; released in :meth:`_launch_distributed` just
        before the workers spawn.  Holding the bind shrinks the
        port-stealing window from launch-sequence minutes to the
        milliseconds between release and the rank-0 worker's own bind
        (a true handoff would need SO_REUSEPORT cooperation from XLA)."""
        import socket

        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("0.0.0.0", 0))
        s.listen(1)
        self._coord_sock = s
        return s.getsockname()[1]

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, ProbePeerRequest):
            try:
                client = BasicClient(f"task-{req.peer_index}",
                                     req.peer_addresses, self._key_bytes,
                                     probe_timeout=3.0)
                return ProbePeerResponse(client.address)
            except ConnectionError:
                return ProbePeerResponse(None)
        if isinstance(req, RunCommandRequest):
            self._launch(req.command, req.env)
            return AckResponse()
        if isinstance(req, CommandExitCodeRequest):
            done = (self._cmd_thread is not None
                    and not self._cmd_thread.is_alive())
            return CommandExitCodeResponse(done,
                                           self._exit_code if done else None)
        if isinstance(req, RunDistributedCommandRequest):
            self._launch_distributed(req)
            return AckResponse()
        if isinstance(req, DistributedExitCodesRequest):
            codes = {rank: (self._rank_codes[rank]
                            if not t.is_alive() else None)
                     for rank, t in self._rank_threads.items()}
            return DistributedExitCodesResponse(codes)
        if isinstance(req, AbortCommandRequest):
            self._abort.set()
            return AckResponse()
        if isinstance(req, AgentShutdownRequest):
            self.shutdown_requested.set()
            return AckResponse()
        return super()._handle(req, client_address)

    def _launch_distributed(self, req: RunDistributedCommandRequest) -> None:
        if any(t.is_alive() for t in self._rank_threads.values()):
            raise RuntimeError("a distributed command is already running")
        if self._coord_sock is not None:
            # Release the held coordinator port now — rank 0 (possibly
            # among this agent's workers) binds it during hvd.init.
            self._coord_sock.close()
            self._coord_sock = None

        import os

        for rank in req.ranks:
            # Like the local launcher's _spawn_world: workers inherit
            # the agent's (remote-host) environment, with the driver's
            # overrides and the rank contract layered on top.
            env = dict(os.environ)
            env.update(req.env)
            env.update({
                "HVD_TPU_COORDINATOR_ADDR": req.coordinator,
                "HVD_TPU_NUM_PROCESSES": str(req.world_size),
                "HVD_TPU_PROCESS_ID": str(rank),
            })
            self._rank_codes[rank] = None

            def target(rank=rank, env=env):
                try:
                    self._rank_codes[rank] = execute(
                        req.command, env=env, events=[self._abort])
                except Exception as e:
                    # Spawn failure (missing executable etc.) must
                    # surface as a rank exit code, or the launcher's
                    # exit-code poll waits forever on a dead thread.
                    import sys

                    print(f"rank {rank} failed to spawn: {e}",
                          file=sys.stderr)
                    self._rank_codes[rank] = 127

            t = threading.Thread(target=target, daemon=True)
            self._rank_threads[rank] = t
            t.start()

    def _launch(self, command: List[str], env: Dict[str, str]) -> None:
        if self._cmd_thread is not None and self._cmd_thread.is_alive():
            raise RuntimeError("a command is already running")

        def target():
            self._exit_code = execute(command, env=env,
                                      events=[self._abort])

        self._cmd_thread = threading.Thread(target=target, daemon=True)
        self._cmd_thread.start()

    def wait_for_command(self, timeout_s: Optional[float] = None) -> int:
        if self._cmd_thread is None:
            raise RuntimeError("no command was launched")
        self._cmd_thread.join(timeout=timeout_s)
        if self._cmd_thread.is_alive():
            raise TimeoutError("command still running")
        return self._exit_code

    def abort_command(self) -> None:
        self._abort.set()

    def shutdown(self) -> None:
        self._abort.set()
        if self._coord_sock is not None:
            self._coord_sock.close()
            self._coord_sock = None
        super().shutdown()


def probe_full_mesh(driver: DriverService, key: bytes,
                    timeout_s: float = 60.0) -> Dict[Tuple[int, int],
                                                     Tuple[str, int]]:
    """Drive the pairwise connectivity probe (reference: the driver's
    interface-selection pass): for every ordered task pair (i, j), ask i
    to reach j; returns {(i, j): address_that_worked}.  Raises if any
    pair is unreachable."""
    addresses = driver.task_addresses()
    clients = {i: BasicClient(f"task-{i}", addrs, key)
               for i, addrs in addresses.items()}
    routes: Dict[Tuple[int, int], Tuple[str, int]] = {}
    deadline = time.monotonic() + timeout_s
    for i, client in clients.items():
        for j, peer_addrs in addresses.items():
            if i == j:
                continue
            if time.monotonic() > deadline:
                raise TimeoutError("mesh probe timed out")
            # Per-exchange deadline = whatever remains of the mesh
            # budget: a peer wedged mid-probe must not absorb it all.
            resp = client.request(
                ProbePeerRequest(j, peer_addrs),
                timeout=max(1.0, deadline - time.monotonic()))
            if resp.reachable_address is None:
                raise ConnectionError(f"task {i} cannot reach task {j}")
            routes[(i, j)] = resp.reachable_address
    return routes
