"""Driver/task pre-flight services.

Reference: ``horovod/runner/common/service/driver_service.py`` +
``task_service.py`` (SURVEY.md §2.5/§3.4, mount empty, unverified).
Before any worker calls ``init()``, the launcher runs a *driver service*
on the controlling host and a *task service* per target host.  Tasks
register with the driver; the driver probes task→task connectivity and
intersects the interfaces every pair can route (the reference's common-
NIC selection); then tasks are told to exec the worker command.

On TPU pods the platform does placement, so this mesh's job narrows to:
verify mutual reachability over DCN, agree on the coordinator address
for ``jax.distributed``, and fan the run command out — but the protocol
is kept so self-managed (non-GKE) fleets work like the reference.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .network import (
    AckResponse, BasicClient, BasicService, PingRequest, PingResponse,
)
from .safe_shell_exec import execute


class RegisterTaskRequest:
    def __init__(self, index: int, addresses: List[Tuple[str, int]],
                 hostname: str):
        self.index = index
        self.addresses = addresses
        self.hostname = hostname


class AllTaskAddressesRequest:
    def __init__(self, index: int):
        self.index = index


class AllTaskAddressesResponse:
    def __init__(self, all_addresses: Dict[int, List[Tuple[str, int]]]):
        self.all_addresses = all_addresses


class ProbePeerRequest:
    def __init__(self, peer_index: int,
                 peer_addresses: List[Tuple[str, int]]):
        self.peer_index = peer_index
        self.peer_addresses = peer_addresses


class ProbePeerResponse:
    def __init__(self, reachable_address: Optional[Tuple[str, int]]):
        self.reachable_address = reachable_address


class RunCommandRequest:
    def __init__(self, command: List[str], env: Dict[str, str]):
        self.command = command
        self.env = env


class CommandExitCodeRequest:
    pass


class CommandExitCodeResponse:
    def __init__(self, done: bool, exit_code: Optional[int]):
        self.done = done
        self.exit_code = exit_code


class DriverService(BasicService):
    """Collects task registrations and answers the full address table
    (reference: ``HorovodRunDriverService``)."""

    def __init__(self, num_tasks: int, key: bytes, name: str = "driver"):
        super().__init__(name, key)
        self._num_tasks = num_tasks
        self._tasks: Dict[int, RegisterTaskRequest] = {}
        self._cv = threading.Condition()

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, RegisterTaskRequest):
            with self._cv:
                self._tasks[req.index] = req
                self._cv.notify_all()
            return AckResponse()
        if isinstance(req, AllTaskAddressesRequest):
            with self._cv:
                return AllTaskAddressesResponse(
                    {i: t.addresses for i, t in self._tasks.items()})
        return super()._handle(req, client_address)

    def wait_for_initial_registration(self, timeout_s: float = 120.0) -> None:
        with self._cv:
            ok = self._cv.wait_for(
                lambda: len(self._tasks) >= self._num_tasks,
                timeout=timeout_s)
        if not ok:
            missing = sorted(set(range(self._num_tasks)) - set(self._tasks))
            raise TimeoutError(
                f"tasks {missing} did not register within {timeout_s}s")

    def task_addresses(self) -> Dict[int, List[Tuple[str, int]]]:
        with self._cv:
            return {i: t.addresses for i, t in self._tasks.items()}

    def task_hostnames(self) -> Dict[int, str]:
        with self._cv:
            return {i: t.hostname for i, t in self._tasks.items()}


class TaskService(BasicService):
    """Per-host agent: answers pings, probes peers on request, and execs
    the worker command (reference: ``HorovodRunTaskService``)."""

    def __init__(self, index: int, key: bytes, name: Optional[str] = None):
        super().__init__(name or f"task-{index}", key)
        self.index = index
        self._key_bytes = key
        self._cmd_thread: Optional[threading.Thread] = None
        self._exit_code: Optional[int] = None
        self._abort = threading.Event()

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, ProbePeerRequest):
            try:
                client = BasicClient(f"task-{req.peer_index}",
                                     req.peer_addresses, self._key_bytes,
                                     probe_timeout=3.0)
                return ProbePeerResponse(client.address)
            except ConnectionError:
                return ProbePeerResponse(None)
        if isinstance(req, RunCommandRequest):
            self._launch(req.command, req.env)
            return AckResponse()
        if isinstance(req, CommandExitCodeRequest):
            done = (self._cmd_thread is not None
                    and not self._cmd_thread.is_alive())
            return CommandExitCodeResponse(done,
                                           self._exit_code if done else None)
        return super()._handle(req, client_address)

    def _launch(self, command: List[str], env: Dict[str, str]) -> None:
        if self._cmd_thread is not None and self._cmd_thread.is_alive():
            raise RuntimeError("a command is already running")

        def target():
            self._exit_code = execute(command, env=env,
                                      events=[self._abort])

        self._cmd_thread = threading.Thread(target=target, daemon=True)
        self._cmd_thread.start()

    def wait_for_command(self, timeout_s: Optional[float] = None) -> int:
        if self._cmd_thread is None:
            raise RuntimeError("no command was launched")
        self._cmd_thread.join(timeout=timeout_s)
        if self._cmd_thread.is_alive():
            raise TimeoutError("command still running")
        return self._exit_code

    def abort_command(self) -> None:
        self._abort.set()

    def shutdown(self) -> None:
        self._abort.set()
        super().shutdown()


def probe_full_mesh(driver: DriverService, key: bytes,
                    timeout_s: float = 60.0) -> Dict[Tuple[int, int],
                                                     Tuple[str, int]]:
    """Drive the pairwise connectivity probe (reference: the driver's
    interface-selection pass): for every ordered task pair (i, j), ask i
    to reach j; returns {(i, j): address_that_worked}.  Raises if any
    pair is unreachable."""
    addresses = driver.task_addresses()
    clients = {i: BasicClient(f"task-{i}", addrs, key)
               for i, addrs in addresses.items()}
    routes: Dict[Tuple[int, int], Tuple[str, int]] = {}
    deadline = time.monotonic() + timeout_s
    for i, client in clients.items():
        for j, peer_addrs in addresses.items():
            if i == j:
                continue
            if time.monotonic() > deadline:
                raise TimeoutError("mesh probe timed out")
            resp = client.request(ProbePeerRequest(j, peer_addrs))
            if resp.reachable_address is None:
                raise ConnectionError(f"task {i} cannot reach task {j}")
            routes[(i, j)] = resp.reachable_address
    return routes
