"""Shared-secret generation for the runner RPC layer.

Reference: ``horovod/runner/common/util/secret.py`` (SURVEY.md §2.5,
mount empty, unverified): the driver mints a random key, passes it to
every task via the environment, and every RPC frame is HMAC-signed with
it so an unauthenticated peer can't inject pickled payloads.
"""

from __future__ import annotations

import base64
import os

# Env var carrying the key from driver to spawned tasks (reference:
# HOROVOD_SECRET_KEY).
SECRET_ENV = "HVD_TPU_SECRET_KEY"

DIGEST_LEN = 32  # sha256


def make_secret_key() -> bytes:
    return base64.b64encode(os.urandom(32))


def secret_from_env() -> bytes:
    key = os.environ.get(SECRET_ENV)
    if not key:
        raise RuntimeError(
            f"{SECRET_ENV} is not set; the launcher must pass the RPC "
            "secret to every task")
    return key.encode()
