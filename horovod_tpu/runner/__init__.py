"""Launcher (reference: ``horovod/runner/`` — ``horovodrun`` CLI,
SURVEY.md §2.5).  Entry points:

* CLI: ``python -m horovod_tpu.runner -np 4 python train.py``
* API: ``horovod_tpu.runner.run(np=4, command=[...])``
"""

from .launch import main, run, run_elastic, parse_args  # noqa: F401
from .check_build import check_build_str  # noqa: F401
from .run_func import launch as run_function  # noqa: F401
