"""``--check-build`` feature matrix (reference: ``horovodrun
--check-build`` prints which frameworks/controllers/ops were compiled in
— ``horovod/runner/launch.py``, SURVEY.md §2.7)."""

from __future__ import annotations


def _tf_xla_ok() -> bool:
    try:
        from ..tensorflow import xla_ops

        return xla_ops.available()
    except ImportError:
        return False


def check_build_str() -> str:
    from ..version import __version__

    try:
        import jax

        jax_line = f"jax {jax.__version__}"
    except ImportError:  # pragma: no cover
        jax_line = "jax MISSING"
    try:
        import optax

        optax_line = f"optax {optax.__version__}"
    except ImportError:
        optax_line = "optax not installed (collectives-only mode)"
    try:
        import flax

        flax_line = f"flax {flax.__version__}"
    except ImportError:
        flax_line = "flax not installed (no model zoo)"
    try:
        import torch

        torch_line = f"pytorch {torch.__version__} (horovod_tpu.torch)"
    except ImportError:
        torch_line = "pytorch not installed"
    try:
        import tensorflow as tf

        tf_line = (f"tensorflow {tf.__version__} "
                   "(horovod_tpu.tensorflow, horovod_tpu.keras)")
    except ImportError:
        tf_line = "tensorflow not installed"
    try:
        from .. import native

        native_ok = native.available()
    except ImportError:
        native_ok = False
    native_line = (
        "native runtime built (controller, coordinator, fusion planner, "
        "response cache, group table, stall inspector, timeline writer)"
        if native_ok else "native runtime not built (pure-python fallbacks)")

    lines = [
        f"horovod_tpu v{__version__}",
        "",
        "Available frameworks:",
        f"    [X] {jax_line}",
        f"    [{'X' if 'not' not in optax_line else ' '}] {optax_line}",
        f"    [{'X' if 'not' not in flax_line else ' '}] {flax_line}",
        f"    [{'X' if 'not' not in torch_line else ' '}] {torch_line}",
        f"    [{'X' if 'not' not in tf_line else ' '}] {tf_line}",
        "",
        "Available controllers:",
        "    [X] jax.distributed (DCN coordination service)",
        f"    [{'X' if native_ok else ' '}] native TCP coordinator "
        "(eager multi-process negotiation)",
        "    [ ] MPI (not applicable on TPU)",
        "    [ ] Gloo (not applicable on TPU)",
        "",
        "Available tensor operations:",
        "    [X] XLA collectives over ICI/DCN "
        "(AllReduce/AllGather/AllToAll/ReduceScatter/CollectivePermute)",
        f"    [{'X' if 'built' in native_line and 'not' not in native_line else ' '}] {native_line}",
        "    [X] Pallas kernels (flash attention; ring-attention "
        "flash engine)",
        "    [X] wire compression (fp16, bf16, int8 "
        "transport-quantized allreduce)",
        f"    [{'X' if _tf_xla_ok() else ' '}] TF-XLA adapter "
        "(collectives inside tf.function(jit_compile=True))",
        "    [X] chunked-vocab LM cross-entropy (no [B,T,V] logits "
        "materialization)",
        "",
        "Runtime features:",
        "    [X] online autotune (HOROVOD_AUTOTUNE=1: GP-tuned fusion "
        "threshold, applied at re-jit boundaries)",
        "    [X] uneven-data join (negotiated input pipeline: "
        "JoinedBatchIterator + global_masked_mean)",
        "    [X] timeline (HOROVOD_TIMELINE Chrome trace) + stall "
        "inspector (single- and cross-process)",
        "",
        "Parallelism:",
        "    [X] data parallel (+Adasum any world size, elastic, "
        "process sets, hierarchical allreduce)",
        "    [X] tensor parallel (Megatron column/row rules)",
        "    [X] sequence/context parallel (ring attention, Ulysses)",
        "    [X] pipeline parallel (GPipe schedule, optional remat: "
        "parallel.pipeline)",
        "    [X] expert parallel / MoE (GShard-style top-2 gating: "
        "parallel.moe)",
        "    [X] ZeRO-1 sharded optimizer state (make_zero_train_step)",
        "    [X] FSDP / ZeRO-3 (make_fsdp_train_step, GSPMD-sharded "
        "params+grads+state)",
        "",
        "Launchers:",
        "    [X] local multi-process (-np N)",
        "    [X] remote multi-host (-H host:slots / --hostfile: ssh task "
        "agents, RPC mesh, fail-fast supervision)",
        "    [X] elastic (--host-discovery-script, min/max-np)",
        "    [X] LSF/jsrun (allocation auto-detect, PMIX rank pickup)",
        "    [X] TPU pod passthrough (platform-set coordination env)",
        "    [X] programmatic hvd.run(fn, np=N) (cloudpickled function, "
        "per-rank results)",
        "",
        "Integration test waiver: Spark/Ray/MXNet integrations are",
        "exercised against faithful in-repo API shims driving REAL",
        "processes (tests/pyspark_shim.py, tests/ray_shim.py,",
        "tests/mxnet_shim.py) — NOT against installed pyspark/ray/mxnet;",
        "version skew vs the real libraries is unverified in this image.",
    ]
    return "\n".join(lines)
