"""Programmatic function launcher — reference parity with
``horovod.run``.

Reference (``horovod/runner/__init__.py`` ``run()`` — SURVEY.md §2.5
CLI row, mount empty, unverified): ``horovod.run(func, args=...,
np=N, hosts=...)`` executes a Python FUNCTION across a freshly
launched worker world (cloudpickled to the workers, one result per
rank returned in rank order) — the in-script alternative to the
``horovodrun`` CLI, and the same shape ``horovod_tpu.spark.run``
exposes inside Spark.

TPU-native redesign: the world is the same one the CLI builds (local
spawn via :func:`horovod_tpu.runner.run`, or the ssh-exec'd agent mesh
via :func:`horovod_tpu.runner.remote.remote_run` when ``hosts`` has
non-local entries); the payload travels as a cloudpickle file on the
launcher's filesystem for local runs — remote hosts need a shared
filesystem for the payload/result exchange, which is the reference's
assumption for its checkpoint paths too (documented limitation).
"""

from __future__ import annotations

import os
import pickle
import shutil
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple


def _serializer():
    try:
        import cloudpickle

        return cloudpickle
    except ImportError:  # stdlib fallback: module-level functions only
        return pickle


def launch(func, args: Tuple = (), kwargs: Optional[Dict] = None, *,
           np: int = 1, hosts: Optional[str] = None,
           env: Optional[Dict[str, str]] = None,
           workdir: Optional[str] = None,
           start_timeout: float = 120.0,
           verbose: bool = False) -> List[Any]:
    """Run ``func(*args, **kwargs)`` on every rank of a fresh ``np``-
    process world; returns the per-rank results in rank order
    (reference: ``horovod.run``).  Like the reference's examples,
    ``func`` calls ``hvd.init()`` itself (so it can configure the
    platform first).  ``hosts`` takes the ``-H`` syntax; non-local
    hosts launch through the ssh agent mesh and the payload/result
    exchange must live on a SHARED filesystem — pass ``workdir=`` (a
    default tempdir is node-local /tmp, which remote workers cannot
    see).  A launcher-created tempdir is removed on return; an
    explicit ``workdir`` is left in place."""
    from . import run as run_cmd
    from .remote import is_local_host, parse_hosts, remote_run

    own_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="hvd_tpu_run_")
    try:
        payload = os.path.join(workdir, "payload.pkl")
        with open(payload, "wb") as f:
            _serializer().dump((func, tuple(args), dict(kwargs or {})), f)

        command = [sys.executable, "-m", "horovod_tpu.runner.run_func",
                   payload, workdir]
        base_env = dict(env or {})
        # Workers must resolve horovod_tpu (and the user's modules) the
        # way the launcher does.
        base_env.setdefault(
            "PYTHONPATH",
            os.pathsep.join(p for p in ([os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))]
                + sys.path[:1] + [os.environ.get("PYTHONPATH", "")]) if p))

        host_list = parse_hosts(hosts) if hosts else None
        if host_list and any(not is_local_host(h) for h, _ in host_list):
            if own_workdir:
                from ..utils.logging import get_logger

                get_logger(__name__).warning(
                    "hvd.run with remote hosts but no workdir=: the "
                    "default tempdir is node-local; remote workers "
                    "need a shared-filesystem workdir")
            rc = remote_run(host_list, command, np_=np, env=base_env,
                            start_timeout=start_timeout, verbose=verbose)
        else:
            if host_list:
                total = sum(s for _, s in host_list)
                if np > total:
                    raise ValueError(
                        f"np={np} exceeds the {total} declared slot(s)")
            rc = run_cmd(np, command, env=base_env,
                         start_timeout=start_timeout, verbose=verbose)
        if rc != 0:
            raise RuntimeError(f"worker world exited with rc={rc}")

        results: List[Any] = []
        for rank in range(np):
            path = os.path.join(workdir, f"result_{rank}.pkl")
            if not os.path.exists(path):
                raise RuntimeError(
                    f"rank {rank} produced no result file (crashed "
                    "after its collective work? remote hosts need a "
                    "shared-filesystem workdir=)")
            with open(path, "rb") as f:
                results.append(pickle.load(f))
        return results
    finally:
        if own_workdir:
            shutil.rmtree(workdir, ignore_errors=True)


def _worker_main(payload_path: str, workdir: str) -> int:
    """Per-rank bootstrap (what the launcher's command execs).

    ``func`` owns initialization — reference examples call
    ``hvd.init()`` themselves, and initializing here would also bind
    the backend before the function can configure the platform (e.g.
    the CPU-mesh pin).  The rank for the result file therefore comes
    from the launcher's env contract, valid before init."""
    with open(payload_path, "rb") as f:
        func, args, kwargs = _serializer().load(f)

    rank = int(os.environ.get("HVD_TPU_PROCESS_ID", "0"))
    result = func(*args, **kwargs)
    tmp = os.path.join(workdir, f".result_{rank}.tmp")
    with open(tmp, "wb") as f:
        _serializer().dump(result, f)
    os.replace(tmp, os.path.join(workdir, f"result_{rank}.pkl"))

    import horovod_tpu as hvd

    if hvd.is_initialized():
        # Results are durable on every rank before any rank exits (a
        # fast rank exiting early would otherwise strand peers still
        # inside collectives when the world tears down).
        hvd.barrier()
        hvd.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(_worker_main(sys.argv[1], sys.argv[2]))
