"""Per-host task agent for remote multi-host launch.

Reference: ``horovod/runner/task_fn.py`` — the module the launcher
ssh-execs on every target host (SURVEY.md §2.5/§3.4, mount empty,
unverified): it starts a :class:`TaskService`, registers with the
driver, answers connectivity probes, execs the worker command on
request, and reports exit codes.

TPU-native redesign: the agent's extra job is reserving the
``jax.distributed`` coordinator port on its host at registration time —
the driver points every worker's ``HVD_TPU_COORDINATOR_ADDR`` at the
rank-0 host's reserved port, so world formation needs no ssh-visible
rendezvous files.

Security: the launcher-minted HMAC secret arrives on **stdin** (one hex
line), never on argv — command lines are world-readable via /proc.

Usage (what the launcher execs over ssh)::

    python -m horovod_tpu.runner.task_agent \
        --driver ip:port[,ip:port...] --index N
"""

from __future__ import annotations

import argparse
import random
import sys
import time
from typing import List, Optional, Tuple

from ..config import Config
from ..utils.retry import jittered
from .common.network import BasicClient, resolvable_hostname
from .common.service import RegisterTaskRequest, TaskService


def parse_addresses(spec: str) -> List[Tuple[str, int]]:
    out = []
    for part in spec.split(","):
        host, _, port = part.rpartition(":")
        out.append((host, int(port)))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="horovod_tpu.runner.task_agent")
    ap.add_argument("--driver", required=True,
                    help="driver service address(es), ip:port[,ip:port...]")
    ap.add_argument("--index", type=int, required=True)
    ap.add_argument("--nics", default=None,
                    help="comma-separated interfaces to advertise "
                         "(reference horovodrun --network-interfaces)")
    ap.add_argument("--timeout", type=float, default=3600.0,
                    help="exit with an error if no command arrives "
                         "within this many seconds (idle bound only — "
                         "a RUNNING job is supervised by driver-"
                         "liveness pings, never a wall clock)")
    args = ap.parse_args(argv)

    key = bytes.fromhex(sys.stdin.readline().strip())
    nics = ([n.strip() for n in args.nics.split(",") if n.strip()]
            if args.nics else None)
    service = TaskService(args.index, key, nics=nics)
    try:
        driver = BasicClient("driver", parse_addresses(args.driver), key)
        driver.request(RegisterTaskRequest(
            args.index, service.addresses(), resolvable_hostname(),
            coordinator_port=service.reserve_coordinator_port()),
            timeout=60.0)
        # Serve (probes / run-command / exit-code polls happen on the
        # service threads) until the driver says we're done.  Two exit
        # hatches so a dead driver can't leak agents or workers:
        #  * idle timeout — registered but no command ever arrived;
        #  * liveness — once a command ran, a driver that stops
        #    answering pings means the launcher died: abort workers.
        # The policy knobs are env-configurable (HVD_TPU_AGENT_PING_
        # INTERVAL / _MAX_MISSED) — a 500-host fleet wants a laxer
        # cadence than a 2-host bench — and the cadence is jittered so
        # agents don't ping the driver in lockstep; after a missed ping
        # the next probe comes sooner (jittered exponential ramp back
        # up to the interval) to tell a blip from a dead driver fast.
        from .. import basics

        # Programmatic Config wins; the normal agent path (ssh-exec'd,
        # never init()ed) parses the env with the same parser/defaults.
        cfg = (basics.config() if basics.is_initialized()
               else Config.from_env())
        ping_interval = cfg.agent_ping_interval_seconds
        max_missed = cfg.agent_max_missed_pings
        rng = random.Random(args.index)  # per-agent deterministic spread
        idle_deadline = time.monotonic() + args.timeout
        missed_pings = 0
        wait_s = jittered(ping_interval, 0.25, rng)
        while not service.shutdown_requested.wait(timeout=wait_s):
            wait_s = jittered(ping_interval, 0.25, rng)
            if not service.command_started:
                if time.monotonic() > idle_deadline:
                    print(f"task-{args.index}: no command within "
                          f"{args.timeout:.0f}s", file=sys.stderr)
                    return 1
                continue
            try:
                driver.ping()
                missed_pings = 0
            except OSError:
                missed_pings += 1
                if missed_pings >= max_missed:
                    print(f"task-{args.index}: driver unreachable "
                          f"({missed_pings} missed pings; interval "
                          f"{ping_interval:.0f}s); aborting workers",
                          file=sys.stderr)
                    service.abort_command()
                    return 1
                # Retry quickly (but jittered) while suspicion mounts.
                wait_s = jittered(
                    min(ping_interval, 1.0 * 2 ** (missed_pings - 1)),
                    0.5, rng)
        return 0
    finally:
        service.shutdown()


if __name__ == "__main__":
    sys.exit(main())
