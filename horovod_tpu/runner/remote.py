"""Remote multi-host launch over the driver/task RPC mesh.

Reference: ``horovod/runner/gloo_run.py`` + ``driver_service.py`` flow
(SURVEY.md §2.5, §3.4 step 3, mount empty, unverified): the launcher
starts a driver service, ssh-execs a task agent on every target host,
waits for registrations, probes full pairwise connectivity (the
common-interface pass), then fans the worker command out per slot and
supervises exit codes — first failure kills the job.

TPU-native redesign: there is no per-rank Gloo rendezvous store to
bootstrap.  The mesh's product is ONE address — the rank-0 host's
reserved ``jax.distributed`` coordinator port — plus the standard
``HVD_TPU_COORDINATOR_ADDR/NUM_PROCESSES/PROCESS_ID`` env contract; XLA
collectives ride ICI once the world forms, the RPC mesh is pre-flight
only.  Remote exec defaults to ssh (BatchMode, like the reference) but
is injectable (``exec_fn``) so loopback tests drive the REAL protocol
end-to-end without sshd — the repo's shim-over-real-processes pattern.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from .common.network import BasicClient
from .common.secret import make_secret_key
from .common.service import (
    AbortCommandRequest, AgentShutdownRequest, DistributedExitCodesRequest,
    DriverService, RunDistributedCommandRequest, probe_full_mesh,
)


def is_local_host(host: str) -> bool:
    """One definition of "this machine" for every launcher path (CLI
    and ``hvd.run``) — drift here would route the same spec down
    different launch mechanisms."""
    import socket

    return host in ("localhost", "127.0.0.1", socket.gethostname())


def parse_hosts(spec: str) -> List[Tuple[str, int]]:
    """``"a:2,b:4"`` -> ``[("a", 2), ("b", 4)]`` (reference -H syntax;
    a bare host means one slot)."""
    out: List[Tuple[str, int]] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, slots = part.partition(":")
        if not host:
            raise ValueError(f"bad -H entry: {part!r}")
        out.append((host, int(slots) if slots else 1))
    return out


def _agent_argv(index: int, driver_addrs: List[Tuple[str, int]],
                timeout_s: float,
                nics: Optional[List[str]] = None) -> List[str]:
    spec = ",".join(f"{h}:{p}" for h, p in driver_addrs)
    argv = [sys.executable, "-m", "horovod_tpu.runner.task_agent",
            "--driver", spec, "--index", str(index),
            "--timeout", str(timeout_s)]
    if nics:
        argv += ["--nics", ",".join(nics)]
    return argv


def ssh_exec(host: str, argv: List[str], secret_hex: str, *,
             ssh_port: Optional[int] = None,
             ssh_identity_file: Optional[str] = None) -> subprocess.Popen:
    """Default remote exec: ssh in BatchMode (no password prompts —
    reference gloo_run assumes passwordless ssh), secret over stdin.
    ``ssh_port`` / ``ssh_identity_file`` mirror the reference's
    ``--ssh-port`` / ``--ssh-identity-file`` flags."""
    ssh = ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no"]
    if ssh_port:
        ssh += ["-p", str(ssh_port)]
    if ssh_identity_file:
        ssh += ["-i", ssh_identity_file]
    proc = subprocess.Popen(ssh + ["--", host] + argv,
                            stdin=subprocess.PIPE, text=True)
    proc.stdin.write(secret_hex + "\n")
    proc.stdin.flush()
    proc.stdin.close()
    return proc


def local_exec(host: str, argv: List[str],
               secret_hex: str, **_ssh_opts) -> subprocess.Popen:
    """Exec an agent as a local child (test path: loopback hosts
    pretending to be remote — the full RPC protocol still runs).
    Accepts and ignores ssh keyword options so it can stand in for
    :func:`ssh_exec` verbatim."""
    proc = subprocess.Popen(argv, stdin=subprocess.PIPE, text=True,
                            env=dict(os.environ))
    proc.stdin.write(secret_hex + "\n")
    proc.stdin.flush()
    proc.stdin.close()
    return proc


def remote_run(hosts: List[Tuple[str, int]], command: List[str], *,
               np_: Optional[int] = None,
               env: Optional[Dict[str, str]] = None,
               exec_fn: Optional[Callable[
                   [str, List[str], str], subprocess.Popen]] = None,
               nics: Optional[List[str]] = None,
               ssh_port: Optional[int] = None,
               ssh_identity_file: Optional[str] = None,
               start_timeout: float = 120.0,
               poll_interval_s: float = 0.5,
               verbose: bool = False) -> int:
    """Launch ``command`` across ``hosts`` (``[(host, slots), ...]``)
    through the driver/task RPC mesh; returns the first nonzero worker
    exit code (0 when every rank succeeds).

    ``np_`` caps the world at the first N slots in host order
    (reference: ``horovodrun -np`` against a larger ``-H`` set).
    """
    if not command:
        raise ValueError("No command given")
    if not hosts:
        raise ValueError("No hosts given")

    # Rank layout: host i owns a contiguous rank block, host order.
    total_slots = sum(s for _, s in hosts)
    if np_ is not None and np_ > total_slots:
        raise ValueError(
            f"-np {np_} exceeds total slots {total_slots} in -H")
    world_size = np_ or total_slots
    rank_blocks: List[List[int]] = []
    next_rank = 0
    for _, slots in hosts:
        take = max(0, min(slots, world_size - next_rank))
        rank_blocks.append(list(range(next_rank, next_rank + take)))
        next_rank += take

    if exec_fn is None:
        def exec_fn(host, argv, secret_hex):
            return ssh_exec(host, argv, secret_hex, ssh_port=ssh_port,
                            ssh_identity_file=ssh_identity_file)
    key = make_secret_key()
    driver = DriverService(len(hosts), key, nics=nics)
    agents: List[subprocess.Popen] = []
    clients: Dict[int, BasicClient] = {}
    exit_code = 0
    try:
        driver_addrs = driver.addresses()
        for i, (host, _) in enumerate(hosts):
            if verbose:
                print(f"[horovodtpurun] starting agent {i} on {host}",
                      file=sys.stderr)
            # timeout here is the agent's IDLE bound (registration ->
            # first command); a running job is supervised by the
            # agent's driver-liveness pings, not a wall clock.
            agents.append(exec_fn(
                host, _agent_argv(i, driver_addrs,
                                  timeout_s=start_timeout + 300.0,
                                  nics=nics),
                key.hex()))
        driver.wait_for_initial_registration(timeout_s=start_timeout)
        routes = probe_full_mesh(driver, key)
        if verbose:
            print(f"[horovodtpurun] mesh verified: {len(routes)} routes",
                  file=sys.stderr)

        addresses = driver.task_addresses()
        clients = {i: BasicClient(f"task-{i}", addrs, key)
                   for i, addrs in addresses.items()}

        # Coordinator = rank-0 host's reserved port, at the address its
        # PEERS proved they can route to (the driver's own route may
        # differ on multi-NIC hosts); single-host worlds use the
        # driver's route.
        coord_port = driver.task_coordinator_ports()[0]
        if len(hosts) > 1:
            coord_host = routes[(1, 0)][0]
        else:
            coord_host = clients[0].address[0]
        coordinator = f"{coord_host}:{coord_port}"
        if verbose:
            print(f"[horovodtpurun] coordinator {coordinator}, world "
                  f"{world_size}", file=sys.stderr)

        for i, ranks in enumerate(rank_blocks):
            if not ranks:
                continue
            # Non-idempotent: a retried launch whose first ACK was lost
            # would hit "already running" on the agent.
            clients[i].request(RunDistributedCommandRequest(
                command, env or {}, ranks, world_size, coordinator),
                idempotent=False, timeout=30.0)

        # Supervise: first nonzero exit kills the job (reference
        # behavior); all-zero on every agent means success.
        pending = {i for i, ranks in enumerate(rank_blocks) if ranks}
        aborted = False

        def _abort_all() -> None:
            # One fan-out per job, reaching EVERY still-pending agent —
            # a wedged agent must neither stop the fan-out to the ones
            # after it nor (by being the failure trigger itself) leave
            # survivors' ranks blocked in collectives forever.
            nonlocal aborted
            if aborted:
                return
            aborted = True
            for j in sorted(pending):
                try:
                    clients[j].request(AbortCommandRequest(),
                                       timeout=30.0)
                except OSError:
                    pass

        while pending:
            for i in sorted(pending):
                try:
                    codes = clients[i].request(
                        DistributedExitCodesRequest(), timeout=30.0).codes
                except OSError as e:
                    # Wedged/dead agent: its ranks can never report —
                    # fail the job, stop polling it, and abort the
                    # survivors (whose ranks would otherwise block in
                    # collectives with the dead agent's ranks).
                    print(f"[horovodtpurun] agent {i} unreachable ({e}); "
                          f"treating its ranks as failed", file=sys.stderr)
                    if exit_code == 0:
                        exit_code = 1
                    pending.discard(i)
                    _abort_all()
                    continue
                finished = {r: c for r, c in codes.items() if c is not None}
                bad = {r: c for r, c in finished.items() if c != 0}
                if bad:
                    if exit_code == 0:
                        rank, exit_code = sorted(bad.items())[0]
                        print(f"[horovodtpurun] rank {rank} exited "
                              f"{exit_code}; terminating job",
                              file=sys.stderr)
                    _abort_all()
                if len(finished) == len(codes):
                    pending.discard(i)
            if pending:
                time.sleep(poll_interval_s)
    except (TimeoutError, ConnectionError) as e:
        print(f"[horovodtpurun] {e}", file=sys.stderr)
        exit_code = 1
    finally:
        for client in clients.values():
            try:
                client.request(AgentShutdownRequest(), timeout=15.0)
            except OSError:
                pass
        for proc in agents:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
        driver.shutdown()
    return exit_code
