"""Process launcher.

Reference: ``horovod/runner/launch.py`` (``horovodrun`` argument
parsing, host allocation, gloo/mpirun dispatch) + ``gloo_run.py``
(per-slot process exec with rendezvous env) — SURVEY.md §2.5/§3.4,
mount empty, unverified.

TPU-native redesign: there is no ssh/mpirun/HTTP-KV stack to manage —
``jax.distributed`` *is* the rendezvous (coordinator TCP service +
barrier).  The launcher's remaining jobs:

* local multi-process spawn (one process per slot group) with the
  ``HVD_TPU_COORDINATOR_ADDR/NUM_PROCESSES/PROCESS_ID`` env contract
  that ``horovod_tpu.init()`` consumes — the moral equivalent of
  ``HOROVOD_RANK/SIZE`` + Gloo rendezvous env;
* TPU pod-slice runs: every host runs the same command; the platform
  (GKE/queued resources) sets the coordination env, so the launcher
  just execs — documented passthrough mode;
* ``--check-build``; elastic min/max-np validation.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional


def _free_port() -> int:
    from .common.network import free_port

    return free_port("127.0.0.1")


def parse_hostfile(path: str) -> str:
    """Read a hostfile into the ``-H`` spec string.  Accepts the
    reference horovodrun format (``host slots=N`` per line, # comments)
    and the compact ``host:N`` form; a bare hostname means one slot.
    Every line is validated — a malformed entry names its line number
    instead of becoming a bogus hostname that fails at ssh time."""
    import re

    entries = []
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            m = re.fullmatch(r"(\S+)\s+slots\s*=\s*(\d+)", line)
            if m:
                entries.append(f"{m.group(1)}:{int(m.group(2))}")
                continue
            m = re.fullmatch(r"([A-Za-z0-9._-]+)(?::(\d+))?", line)
            if m:
                entries.append(f"{m.group(1)}:{int(m.group(2) or 1)}")
                continue
            raise ValueError(f"line {lineno}: bad entry {raw.rstrip()!r} "
                             "(expected 'host slots=N' or 'host[:N]')")
    if not entries:
        raise ValueError("no host entries found")
    return ",".join(entries)


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="horovodtpurun",
        description="Launch a horovod_tpu training program "
                    "(reference CLI: horovodrun)",
        # No prefix matching: an abbreviated flag (e.g. --auto) must be
        # an error, not a silent match that a --config-file value could
        # then be "overridden" by — the explicit-CLI-wins scan below
        # matches argv tokens against FULL option strings only.
        allow_abbrev=False,
    )
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="number of worker processes (default: 1 "
                             "locally; the whole allocation under LSF)")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host:slots[,host:slots...] — non-local hosts "
                             "are launched via ssh-exec'd task agents over "
                             "the driver/task RPC mesh (reference: "
                             "gloo_run); on managed TPU pods prefer the "
                             "platform's own placement")
    parser.add_argument("--hostfile", default=None,
                        help="file with one host per line, either "
                             "'host slots=N' (reference horovodrun "
                             "format) or 'host:N'; mutually exclusive "
                             "with -H")
    parser.add_argument("--check-build", action="store_true",
                        help="print the feature matrix and exit")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic: minimum world size")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic: maximum world size")
    parser.add_argument("--host-discovery-script", default=None,
                        help="elastic: script printing host:slots per line")
    parser.add_argument("--reset-limit", type=int, default=0,
                        help="elastic: max world restarts (0 = unlimited; "
                             "reference: HOROVOD_ELASTIC_RESET_LIMIT)")
    parser.add_argument("--blacklist-after", type=int, default=0,
                        help="elastic: blacklist a host after this many "
                             "failures (0 = never)")
    parser.add_argument("--output-filename", default=None,
                        help="redirect each worker's output to "
                             "<dir>/rank.<N>.{stdout,stderr} instead of "
                             "the launcher's terminal (reference "
                             "horovodrun flag; local spawn only — "
                             "remote workers stream through their "
                             "agents)")
    parser.add_argument("--ssh-port", type=int, default=None,
                        help="ssh port for remote agent launch "
                             "(reference horovodrun flag)")
    parser.add_argument("--ssh-identity-file", default=None,
                        help="ssh identity file for remote agent launch "
                             "(reference horovodrun flag)")
    parser.add_argument("--network-interfaces", default=None,
                        help="comma-separated NICs the RPC services "
                             "advertise (reference horovodrun "
                             "--network-interfaces); default: all")
    parser.add_argument("--coordinator", default=None,
                        help="coordinator address (default: 127.0.0.1:random)")
    parser.add_argument("--start-timeout", type=float, default=120.0)
    parser.add_argument("--log-level", default=None, type=str.lower,
                        choices=["trace", "debug", "info", "warning",
                                 "error", "fatal"],
                        help="sets HOROVOD_LOG_LEVEL for every worker "
                             "(reference horovodrun flag; "
                             "case-insensitive like the env var)")
    parser.add_argument("--timeline-filename", default=None,
                        help="write a Chrome-trace timeline of collective "
                             "lifecycles (reference horovodrun flag; sets "
                             "HOROVOD_TIMELINE). Process 0 writes exactly "
                             "this path; other processes write "
                             "<path>.rank<N> — enforced at hvd.init(), so "
                             "it holds on every launch path")
    parser.add_argument("--timeline-mark-cycles", action="store_true",
                        help="mark scheduling cycles in the timeline "
                             "(reference horovodrun flag; sets "
                             "HOROVOD_TIMELINE_MARK_CYCLES)")
    parser.add_argument("--autotune", action="store_true",
                        help="enable online Bayesian autotuning in every "
                             "worker (reference horovodrun flag; sets "
                             "HOROVOD_AUTOTUNE=1)")
    parser.add_argument("--autotune-log-file", default=None,
                        help="JSONL log of autotune samples (reference "
                             "horovodrun flag; sets HOROVOD_AUTOTUNE_LOG)")
    parser.add_argument("--fusion-threshold-mb", type=int, default=None,
                        help="fusion bucket size in MB for every worker "
                             "(reference horovodrun flag; sets "
                             "HOROVOD_FUSION_THRESHOLD in bytes)")
    parser.add_argument("--cycle-time-ms", type=float, default=None,
                        help="reference horovodrun flag; forwarded as "
                             "HOROVOD_CYCLE_TIME — a documented no-op "
                             "here (XLA's async dispatch has no cycle "
                             "loop), workers warn when it is set")
    parser.add_argument("--cache-capacity", type=int, default=None,
                        help="compiled-collective dispatch cache capacity "
                             "(reference horovodrun flag; sets "
                             "HOROVOD_CACHE_CAPACITY)")
    parser.add_argument("--hierarchical-allreduce", action="store_true",
                        help="two-level allreduce in every worker "
                             "(reference horovodrun flag; sets "
                             "HOROVOD_HIERARCHICAL_ALLREDUCE=1)")
    parser.add_argument("--hierarchical-allgather", action="store_true",
                        help="reference horovodrun flag; forwarded as "
                             "HOROVOD_HIERARCHICAL_ALLGATHER — a "
                             "documented no-op (XLA lowers AllGather "
                             "over the topology natively)")
    parser.add_argument("--no-stall-check", action="store_true",
                        help="disable the stall inspector (reference "
                             "horovodrun flag; sets "
                             "HOROVOD_STALL_CHECK_DISABLE=1)")
    parser.add_argument("--stall-check-warning-time-seconds", type=float,
                        default=None,
                        help="reference horovodrun flag; sets "
                             "HOROVOD_STALL_CHECK_TIME_SECONDS")
    parser.add_argument("--stall-check-shutdown-time-seconds", type=float,
                        default=None,
                        help="reference horovodrun flag; sets "
                             "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS")
    parser.add_argument("--config-file", default=None,
                        help="YAML file of launcher parameters (reference "
                             "horovodrun --config-file analogue): a flat "
                             "mapping of long option names (with or "
                             "without leading dashes, '-' or '_' "
                             "separated) to values; explicit CLI flags "
                             "win over file values")
    parser.add_argument("--verbose", action="store_true")
    from ..version import __version__

    parser.add_argument("--version", action="version",
                        version=f"horovod-tpu {__version__}",
                        help="print the framework version and exit "
                             "(reference horovodrun flag)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="program and args (e.g. python train.py)")
    args = parser.parse_args(argv)
    if args.config_file:
        _apply_config_file(parser, args, argv)
    return args


_BOOL_WORDS = {"1": True, "true": True, "yes": True, "on": True,
               "0": False, "false": False, "no": False, "off": False,
               "": False}


def _apply_config_file(parser: argparse.ArgumentParser,
                       args: argparse.Namespace,
                       argv: Optional[List[str]]) -> None:
    """Fill parameters from ``--config-file`` (YAML flat mapping of long
    option names).  Explicit CLI flags win — "explicit" is determined by
    scanning the launcher's own argv tokens (the command remainder is
    excluded, so a worker command's flags can't shadow launcher ones).
    File values go through the same type/choices validation the CLI
    applies."""
    try:
        import yaml
    except ImportError:
        raise SystemExit(
            "--config-file requires pyyaml, which is not installed; "
            "install it with `pip install horovod-tpu[config]` (or "
            "`pip install pyyaml`)")

    with open(args.config_file) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise SystemExit(f"--config-file {args.config_file}: expected a "
                         "flat YAML mapping, got "
                         f"{type(data).__name__}")
    tokens = sys.argv[1:] if argv is None else list(argv)
    tokens = tokens[:len(tokens) - len(args.command)]  # REMAINDER is the tail
    given = set()
    for act in parser._actions:
        for opt in act.option_strings:
            if opt in tokens or any(t.startswith(opt + "=") for t in tokens):
                given.add(act.dest)
    actions = {a.dest: a for a in parser._actions
               if a.default is not argparse.SUPPRESS}  # excludes -h/--help
    for key, value in data.items():
        dest = str(key).lstrip("-").replace("-", "_")
        if dest in ("config_file", "command") or dest not in actions:
            raise SystemExit(f"--config-file {args.config_file}: unknown "
                             f"parameter {key!r}")
        act = actions[dest]
        if isinstance(act, argparse._StoreTrueAction):
            if not isinstance(value, bool):
                try:
                    value = _BOOL_WORDS[str(value).strip().lower()]
                except KeyError:
                    raise SystemExit(
                        f"--config-file {args.config_file}: bad value "
                        f"{value!r} for boolean {key!r}")
        elif act.type is not None and value is not None:
            try:
                value = act.type(value)
            except (TypeError, ValueError):
                raise SystemExit(
                    f"--config-file {args.config_file}: bad value "
                    f"{value!r} for {key!r}")
        if act.choices is not None and value not in act.choices:
            raise SystemExit(
                f"--config-file {args.config_file}: {key!r} must be one "
                f"of {sorted(act.choices)}, got {value!r}")
        if dest not in given:  # CLI wins
            setattr(args, dest, value)


def _spawn_world(np_: int, command: List[str], coordinator: str,
                 env: Optional[Dict[str, str]],
                 verbose: bool,
                 output_dir: Optional[str] = None,
                 output_append: bool = False
                 ) -> List[subprocess.Popen]:
    procs: List[subprocess.Popen] = []
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    if output_dir:
        os.makedirs(output_dir, exist_ok=True)
    for rank in range(np_):
        worker_env = dict(base_env)
        worker_env.update({
            "HVD_TPU_COORDINATOR_ADDR": coordinator,
            "HVD_TPU_NUM_PROCESSES": str(np_),
            "HVD_TPU_PROCESS_ID": str(rank),
        })
        if verbose:
            print(f"[horovodtpurun] spawning rank {rank}: {' '.join(command)}",
                  file=sys.stderr)
        if output_dir:
            # Reference horovodrun --output-filename: one file pair per
            # rank; file handles are inherited by the child and closed
            # here (the child keeps them open).
            # "wb": one launcher invocation owns the file pair —
            # append would silently interleave output from earlier
            # runs.  (Elastic RESTARTS within one invocation do append:
            # the pre-restart world's output is part of this launch.)
            mode = "ab" if output_append else "wb"
            out = open(os.path.join(output_dir, f"rank.{rank}.stdout"), mode)
            err = open(os.path.join(output_dir, f"rank.{rank}.stderr"), mode)
            with out, err:
                procs.append(subprocess.Popen(command, env=worker_env,
                                              stdout=out, stderr=err))
        else:
            procs.append(subprocess.Popen(command, env=worker_env))
    return procs


def _terminate_all(procs: List[subprocess.Popen]) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def run(np_: int, command: List[str], *, coordinator: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        start_timeout: float = 120.0, verbose: bool = False,
        output_dir: Optional[str] = None) -> int:
    """Spawn ``np_`` local worker processes wired into one
    ``jax.distributed`` world; returns the first nonzero exit code (0 on
    success).  Workers that outlive a failed peer are terminated —
    reference behavior (gloo_run kills the job on first failure)."""
    if not command:
        raise ValueError("No command given")
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs = _spawn_world(np_, command, coordinator, env, verbose,
                         output_dir=output_dir)

    exit_code = 0
    deadline = time.monotonic() + start_timeout
    # A single-worker world never binds the jax.distributed coordinator
    # (no rendezvous), so there is nothing to probe — treat it as started.
    started = np_ == 1
    last_probe = 0.0
    try:
        pending = set(range(np_))
        while pending:
            for i in list(pending):
                rc = procs[i].poll()
                if rc is not None:
                    pending.discard(i)
                    started = True  # a worker ran to an exit code
                    if rc != 0 and exit_code == 0:
                        exit_code = rc
                        # First failure kills the job (reference behavior).
                        for j in pending:
                            procs[j].terminate()
            if exit_code == 0 and not any(p.poll() is None for p in procs):
                break
            time.sleep(0.1)
            now = time.monotonic()
            if (not started and now > deadline and now - last_probe >= 2.0):
                last_probe = now
                if _none_started(coordinator):
                    raise TimeoutError("workers failed to start in time")
                started = True  # coordinator bound: probe never runs again
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    finally:
        _terminate_all(procs)
    return exit_code


def run_elastic(command: List[str], *, min_np: int = 1,
                max_np: Optional[int] = None,
                discovery_script: Optional[str] = None,
                discovery=None,
                env: Optional[Dict[str, str]] = None,
                start_timeout: float = 120.0,
                poll_interval_s: float = 1.0,
                reset_limit: int = 0,
                blacklist_after: int = 0,
                verbose: bool = False,
                output_dir: Optional[str] = None) -> int:
    """Elastic local supervision (reference: ``horovodrun
    --host-discovery-script`` driving the ElasticDriver, §3.5 of
    SURVEY.md): poll discovery, run a world sized to the available
    slots, and on membership change or worker failure tear the world
    down and restart it at the new size — workers recover state through
    ``hvd.elastic``/checkpoints.

    Worlds are restarted (never resized in place): a ``jax.distributed``
    world is fixed at init, so resize = teardown + re-init, which is the
    reference's elastic flow too (shutdown → rendezvous → broadcast).
    Returns 0 when a world runs the command to completion on every
    worker; nonzero after ``reset_limit`` failed restarts (0 =
    unlimited).

    ``blacklist_after`` enables host blacklisting after that many
    failures; it defaults to off here because a local supervisor cannot
    attribute a failure to one host — blacklisting the whole (usually
    single-host) set would contradict ``reset_limit=0`` unlimited
    retries.
    """
    from ..elastic.driver import ElasticDriver, ScriptDiscovery

    if discovery is None:
        if not discovery_script:
            raise ValueError("need discovery_script or a discovery object")
        discovery = ScriptDiscovery(discovery_script)
    driver = ElasticDriver(
        discovery, poll_interval_s=poll_interval_s,
        blacklist_after=(blacklist_after if blacklist_after > 0
                         else (1 << 30)))
    try:
        driver.wait_for_available_slots(min_np, timeout_s=start_timeout)
    except TimeoutError as e:
        print(f"[horovodtpurun] {e}", file=sys.stderr)
        return 1

    resets = 0
    while True:
        np_ = driver.world_size()
        if max_np is not None:
            np_ = min(np_, max_np)
        if np_ < min_np:
            print(f"[horovodtpurun] only {np_} slots available "
                  f"(< --min-np {min_np}); waiting", file=sys.stderr)
            try:
                driver.wait_for_available_slots(min_np,
                                                timeout_s=start_timeout)
                continue
            except TimeoutError:
                return 1
        coordinator = f"127.0.0.1:{_free_port()}"
        if verbose:
            print(f"[horovodtpurun] elastic world of {np_} starting",
                  file=sys.stderr)
        procs = _spawn_world(np_, command, coordinator, env, verbose,
                             output_dir=output_dir,
                             output_append=resets > 0)
        hosts_this_world = sorted(driver.hosts)
        failed = False
        try:
            while True:
                # Exit codes first: a world that already finished must
                # not be "restarted" by a late membership delta.
                rcs = [p.poll() for p in procs]
                if all(rc == 0 for rc in rcs):
                    # Strike reset: the hosts of a world that ran to
                    # completion earned their blacklist strikes back.
                    for host in hosts_this_world:
                        driver.record_success(host)
                    return 0
                if any(rc is not None and rc != 0 for rc in rcs):
                    # A local supervisor cannot attribute the failure to
                    # one host; strike every host of the failed world
                    # (only matters when blacklist_after is enabled).
                    for host in hosts_this_world:
                        driver.record_failure(host)
                    _terminate_all(procs)
                    failed = True
                    break
                try:
                    changed = driver.poll_once()
                except Exception as e:
                    # Discovery scripts may be transiently flaky
                    # (reference tolerates this in the driver's own
                    # poll loop); a blip must not crash the supervisor
                    # and orphan the live world.
                    print(f"[horovodtpurun] discovery poll failed "
                          f"({e}); retrying", file=sys.stderr)
                    changed = False
                if changed:
                    if verbose:
                        print("[horovodtpurun] membership changed; "
                              "restarting world", file=sys.stderr)
                    _terminate_all(procs)
                    failed = True   # counts as a reset, not an error
                    break
                time.sleep(poll_interval_s)
        except KeyboardInterrupt:
            _terminate_all(procs)
            return 130
        except Exception:
            _terminate_all(procs)   # never leak a live world
            raise
        if failed:
            resets += 1
            if reset_limit and resets > reset_limit:
                print(f"[horovodtpurun] reset limit ({reset_limit}) "
                      f"exceeded", file=sys.stderr)
                return 1


def _none_started(coordinator: str) -> bool:
    """Liveness probe behind ``--start-timeout`` (reference: gloo_run's
    rendezvous-server timeout).  Rank 0 binds the ``jax.distributed``
    coordinator service during ``hvd.init()``; if nothing is listening
    on that address by the deadline, no worker reached init — a genuine
    start failure, not a long-running world."""
    host, _, port = coordinator.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=2.0):
            return False  # coordinator up: the world started
    except OSError:
        return True


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        from .check_build import check_build_str

        print(check_build_str())
        return 0
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("error: no command to run (usage: horovodtpurun -np 4 "
              "python train.py)", file=sys.stderr)
        return 2
    # Threaded through env= (never os.environ: a rejected invocation
    # must not mutate a programmatic caller's process).
    extra_env = {}
    if args.log_level:
        extra_env["HOROVOD_LOG_LEVEL"] = args.log_level
    if args.timeline_filename:
        extra_env["HOROVOD_TIMELINE"] = args.timeline_filename
    if args.timeline_mark_cycles:
        extra_env["HOROVOD_TIMELINE_MARK_CYCLES"] = "1"
    if args.autotune:
        extra_env["HOROVOD_AUTOTUNE"] = "1"
    if args.autotune_log_file:
        extra_env["HOROVOD_AUTOTUNE_LOG"] = args.autotune_log_file
    if args.fusion_threshold_mb is not None:
        extra_env["HOROVOD_FUSION_THRESHOLD"] = str(
            args.fusion_threshold_mb * 1024 * 1024)
    if args.cycle_time_ms is not None:
        extra_env["HOROVOD_CYCLE_TIME"] = str(args.cycle_time_ms)
    if args.cache_capacity is not None:
        extra_env["HOROVOD_CACHE_CAPACITY"] = str(args.cache_capacity)
    if args.hierarchical_allreduce:
        extra_env["HOROVOD_HIERARCHICAL_ALLREDUCE"] = "1"
    if args.hierarchical_allgather:
        extra_env["HOROVOD_HIERARCHICAL_ALLGATHER"] = "1"
    if args.no_stall_check:
        extra_env["HOROVOD_STALL_CHECK_DISABLE"] = "1"
    if args.stall_check_warning_time_seconds is not None:
        extra_env["HOROVOD_STALL_CHECK_TIME_SECONDS"] = str(
            args.stall_check_warning_time_seconds)
    if args.stall_check_shutdown_time_seconds is not None:
        extra_env["HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"] = str(
            args.stall_check_shutdown_time_seconds)
    nics = ([n.strip() for n in args.network_interfaces.split(",")
             if n.strip()] if args.network_interfaces else None)
    if args.hostfile:
        if args.hosts:
            print("error: -H and --hostfile are mutually exclusive",
                  file=sys.stderr)
            return 2
        try:
            args.hosts = parse_hostfile(args.hostfile)
        except (OSError, ValueError) as e:
            print(f"error: --hostfile: {e}", file=sys.stderr)
            return 2
    if args.hosts:
        from .remote import is_local_host

        non_local = [h for h in args.hosts.split(",")
                     if not is_local_host(h.split(":")[0])]
        if non_local:
            # Remote launch over the driver/task RPC mesh (reference:
            # gloo_run's ssh-exec'd task agents).  All hosts — local
            # included — go through agents so the rank layout is uniform.
            from .remote import parse_hosts, remote_run

            try:
                hosts = parse_hosts(args.hosts)
                # Forward framework/runtime knobs so a HOROVOD_* var set
                # at the CLI means the same thing on every host — but
                # NOT the whole environment: the launcher's
                # PATH/HOME/VIRTUAL_ENV would clobber host-critical
                # values on remote machines (workers inherit the agent
                # host's env underneath these overrides).
                fwd_prefixes = ("HOROVOD_", "HVD_TPU_", "JAX_", "XLA_",
                                "TF_", "LIBTPU_", "TPU_", "PYTHONPATH",
                                "PYTHONUNBUFFERED")
                env = {k: v for k, v in os.environ.items()
                       if k.startswith(fwd_prefixes)}
                env.update(extra_env)
                return remote_run(hosts, command, np_=args.num_proc,
                                  env=env, nics=nics,
                                  ssh_port=args.ssh_port,
                                  ssh_identity_file=args.ssh_identity_file,
                                  start_timeout=args.start_timeout,
                                  verbose=args.verbose)
            except ValueError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
    num_proc = args.num_proc if args.num_proc is not None else 1
    if args.hosts:
        # Local-only -H/--hostfile: the slot counts ARE the world size
        # (reference: `horovodrun -H localhost:8` runs 8 workers).  An
        # explicit -np must fit the declared slots.
        from .remote import parse_hosts

        try:
            total_slots = sum(s for _, s in parse_hosts(args.hosts))
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        if args.num_proc is None:
            num_proc = total_slots
        elif num_proc > total_slots:
            print(f"error: -np {num_proc} exceeds the {total_slots} "
                  f"slot(s) declared in -H/--hostfile", file=sys.stderr)
            return 2
    if args.min_np is not None and num_proc < args.min_np:
        print(f"error: -np {num_proc} < --min-np {args.min_np}",
              file=sys.stderr)
        return 2
    from . import lsf as _lsf

    if args.hosts is None and not args.host_discovery_script \
            and _lsf.in_lsf():
        if args.output_filename:
            print("[horovodtpurun] --output-filename is ignored under "
                  "LSF/jsrun (the scheduler owns task placement and "
                  "output; use jsrun's own redirection)",
                  file=sys.stderr)
        # jsrun tasks inherit the launcher env; this is the one path
        # where the variables must be set in-process (the allocation's
        # task placement is the scheduler's, not ours).
        os.environ.update(extra_env)
        # LSF allocation: place tasks via jsrun (reference: horovodrun's
        # lsf detection + js_run path); -np unset means "use the whole
        # allocation", an explicit -np (including 1) is honored exactly.
        return _lsf.run_lsf(command, np_=args.num_proc,
                            verbose=args.verbose)
    if args.host_discovery_script:
        # Reference semantics: -np is the target size, bounded by
        # --min-np/--max-np; discovery grows the world only up to the
        # max, never past what the user asked for.
        return run_elastic(
            command, min_np=args.min_np or num_proc,
            max_np=args.max_np or num_proc,
            discovery_script=args.host_discovery_script,
            start_timeout=args.start_timeout,
            reset_limit=args.reset_limit,
            blacklist_after=args.blacklist_after,
            verbose=args.verbose,
            env=extra_env,
            output_dir=args.output_filename)
    return run(num_proc, command, coordinator=args.coordinator,
               env=extra_env,
               start_timeout=args.start_timeout, verbose=args.verbose,
               output_dir=args.output_filename)


if __name__ == "__main__":
    sys.exit(main())
