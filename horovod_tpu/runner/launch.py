"""Process launcher.

Reference: ``horovod/runner/launch.py`` (``horovodrun`` argument
parsing, host allocation, gloo/mpirun dispatch) + ``gloo_run.py``
(per-slot process exec with rendezvous env) — SURVEY.md §2.5/§3.4,
mount empty, unverified.

TPU-native redesign: there is no ssh/mpirun/HTTP-KV stack to manage —
``jax.distributed`` *is* the rendezvous (coordinator TCP service +
barrier).  The launcher's remaining jobs:

* local multi-process spawn (one process per slot group) with the
  ``HVD_TPU_COORDINATOR_ADDR/NUM_PROCESSES/PROCESS_ID`` env contract
  that ``horovod_tpu.init()`` consumes — the moral equivalent of
  ``HOROVOD_RANK/SIZE`` + Gloo rendezvous env;
* TPU pod-slice runs: every host runs the same command; the platform
  (GKE/queued resources) sets the coordination env, so the launcher
  just execs — documented passthrough mode;
* ``--check-build``; elastic min/max-np validation.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        prog="horovodtpurun",
        description="Launch a horovod_tpu training program "
                    "(reference CLI: horovodrun)",
    )
    parser.add_argument("-np", "--num-proc", type=int, default=1,
                        help="number of worker processes")
    parser.add_argument("-H", "--hosts", default=None,
                        help="host:slots[,host:slots...] — informational on "
                             "TPU pods (the platform places processes); "
                             "local execution supports localhost only")
    parser.add_argument("--check-build", action="store_true",
                        help="print the feature matrix and exit")
    parser.add_argument("--min-np", type=int, default=None,
                        help="elastic: minimum world size")
    parser.add_argument("--max-np", type=int, default=None,
                        help="elastic: maximum world size")
    parser.add_argument("--host-discovery-script", default=None,
                        help="elastic: script printing host:slots per line")
    parser.add_argument("--coordinator", default=None,
                        help="coordinator address (default: 127.0.0.1:random)")
    parser.add_argument("--start-timeout", type=float, default=120.0)
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="program and args (e.g. python train.py)")
    return parser.parse_args(argv)


def run(np_: int, command: List[str], *, coordinator: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        start_timeout: float = 120.0, verbose: bool = False) -> int:
    """Spawn ``np_`` local worker processes wired into one
    ``jax.distributed`` world; returns the first nonzero exit code (0 on
    success).  Workers that outlive a failed peer are terminated —
    reference behavior (gloo_run kills the job on first failure)."""
    if not command:
        raise ValueError("No command given")
    coordinator = coordinator or f"127.0.0.1:{_free_port()}"
    procs: List[subprocess.Popen] = []
    base_env = dict(os.environ)
    if env:
        base_env.update(env)
    for rank in range(np_):
        worker_env = dict(base_env)
        worker_env.update({
            "HVD_TPU_COORDINATOR_ADDR": coordinator,
            "HVD_TPU_NUM_PROCESSES": str(np_),
            "HVD_TPU_PROCESS_ID": str(rank),
        })
        if verbose:
            print(f"[horovodtpurun] spawning rank {rank}: {' '.join(command)}",
                  file=sys.stderr)
        procs.append(subprocess.Popen(command, env=worker_env))

    exit_code = 0
    deadline = time.monotonic() + start_timeout
    try:
        pending = set(range(np_))
        while pending:
            for i in list(pending):
                rc = procs[i].poll()
                if rc is not None:
                    pending.discard(i)
                    if rc != 0 and exit_code == 0:
                        exit_code = rc
                        # First failure kills the job (reference behavior).
                        for j in pending:
                            procs[j].terminate()
            if exit_code == 0 and not any(p.poll() is None for p in procs):
                break
            time.sleep(0.1)
            if (time.monotonic() > deadline
                    and all(p.poll() is None for p in procs)
                    and _none_started(procs)):
                raise TimeoutError("workers failed to start in time")
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGINT)
        exit_code = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
    return exit_code


def _none_started(procs) -> bool:
    return False  # liveness probe hook; processes self-report via exit


def main(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.check_build:
        from .check_build import check_build_str

        print(check_build_str())
        return 0
    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    if not command:
        print("error: no command to run (usage: horovodtpurun -np 4 "
              "python train.py)", file=sys.stderr)
        return 2
    if args.hosts:
        non_local = [h for h in args.hosts.split(",")
                     if h.split(":")[0] not in ("localhost", "127.0.0.1",
                                                socket.gethostname())]
        if non_local:
            print("error: remote host execution is platform-managed on TPU "
                  "(run this command on every host of the slice, or use GKE/"
                  f"queued resources); non-local hosts given: {non_local}",
                  file=sys.stderr)
            return 2
    if args.min_np is not None and args.num_proc < args.min_np:
        print(f"error: -np {args.num_proc} < --min-np {args.min_np}",
              file=sys.stderr)
        return 2
    return run(args.num_proc, command, coordinator=args.coordinator,
               start_timeout=args.start_timeout, verbose=args.verbose)


if __name__ == "__main__":
    sys.exit(main())
