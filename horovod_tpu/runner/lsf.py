"""LSF / jsrun scheduler launch.

Reference: ``horovod/runner/util/lsf.py`` (LSB host parsing) +
``horovod/runner/js_run.py`` (jsrun command construction) — SURVEY.md
§2.5, mount empty, unverified.  On LSF clusters ``horovodrun`` detects
the allocation (``LSB_JOBID``), derives hosts/slots from
``LSB_DJOB_HOSTFILE`` / ``LSB_MCPU_HOSTS``, and launches one task per
slot through ``jsrun`` instead of ssh.

TPU-native redesign: jsrun places the *controller processes* only; the
rendezvous is still ``jax.distributed`` — rank 0's host (the first
compute host of the allocation) serves the coordinator on a fixed port
and every task derives its rank from the scheduler's own env
(``PMIX_RANK`` / ``OMPI_COMM_WORLD_RANK``, consumed by
``basics._maybe_init_distributed``), so no per-task env stamping is
needed.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_PORT = 29500


def in_lsf() -> bool:
    """True inside an LSF allocation (reference: ``lsf.check_lsf``)."""
    return "LSB_JOBID" in os.environ


def lsf_hosts() -> "OrderedDict[str, int]":
    """Ordered ``{host: slots}`` of the allocation's *compute* hosts.

    ``LSB_DJOB_HOSTFILE`` lists one line per slot (the batch/launch host
    first — excluded, like the reference); ``LSB_MCPU_HOSTS`` is the
    ``host1 n1 host2 n2 ...`` fallback form.
    """
    hostfile = os.environ.get("LSB_DJOB_HOSTFILE")
    if hostfile and os.path.exists(hostfile):
        counts: "OrderedDict[str, int]" = OrderedDict()
        with open(hostfile) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        for host in lines[1:] or lines:   # first line = batch host
            counts[host] = counts.get(host, 0) + 1
        if counts:
            return counts
    mcpu = os.environ.get("LSB_MCPU_HOSTS", "")
    parts = mcpu.split()
    if parts and len(parts) % 2 == 0:
        counts = OrderedDict()
        # First pair = the batch/launch host, excluded like the
        # hostfile path (unless it is the only entry).
        pairs = list(zip(parts[::2], parts[1::2]))
        for host, n in pairs[1:] or pairs:
            counts[host] = counts.get(host, 0) + int(n)
        return counts
    raise RuntimeError(
        "not inside a recognizable LSF allocation (no LSB_DJOB_HOSTFILE "
        "or LSB_MCPU_HOSTS)")


def world_size() -> int:
    return sum(lsf_hosts().values())


def jsrun_command(command: List[str], np_: int,
                  coordinator: str) -> List[str]:
    """The jsrun invocation: one task per slot, framework env forwarded
    (reference: ``js_run.py`` assembles the same shape with smpiargs)."""
    jsrun = shutil.which("jsrun") or "jsrun"
    return [
        jsrun,
        "--np", str(np_),
        "--tasks_per_rs", "1", "--cpu_per_rs", "1",
        "-E", f"HVD_TPU_COORDINATOR_ADDR={coordinator}",
        "-E", f"HVD_TPU_NUM_PROCESSES={np_}",
    ] + list(command)


def run_lsf(command: List[str], np_: Optional[int] = None, *,
            port: int = DEFAULT_PORT,
            env: Optional[Dict[str, str]] = None,
            verbose: bool = False) -> int:
    """Launch ``command`` across the LSF allocation via jsrun; returns
    the jsrun exit code.  Rank assignment comes from the scheduler's
    PMIX/OMPI rank env inside each task."""
    hosts = lsf_hosts()
    if np_ is None or np_ <= 0:
        np_ = sum(hosts.values())
    first_host = next(iter(hosts))
    coordinator = f"{first_host}:{port}"
    cmd = jsrun_command(command, np_, coordinator)
    if verbose:
        print(f"[horovodtpurun] LSF allocation {dict(hosts)}; "
              f"exec: {' '.join(cmd)}", file=sys.stderr)
    if shutil.which("jsrun") is None:
        print("error: LSF allocation detected but `jsrun` is not on PATH; "
              "load the job-step manager module or launch with "
              "`horovodtpurun -np N` locally per host", file=sys.stderr)
        return 2
    run_env = dict(os.environ)
    if env:
        run_env.update(env)
    return subprocess.call(cmd, env=run_env)
