"""Process-level host-tensor collectives shared by the framework bindings.

Reference analogue: the C core called by every binding —
``EnqueueTensorAllreduce/Allgather/Broadcast/Alltoall`` in
``horovod/common/operations.cc`` reached from ``horovod/torch/mpi_ops_v2.cc``
and ``horovod/tensorflow/mpi_ops.cc`` (SURVEY.md §2.1/§2.3, mount empty,
unverified).  In the reference each binding converts a framework tensor to
the common ``Tensor`` interface and enqueues; here each binding converts to
numpy and calls these functions, which map the *process*-level op onto the
framework's *slot*-level SPMD collectives (:mod:`horovod_tpu.ops.collectives`).

Slot mapping (shared contract for all host bindings): each worker process
owns ``local_size`` mesh slots; its contribution rides on its first ("head")
slot and the remaining local rows carry the reduction's neutral element
(0 for sum, ±inf for min/max, 1 for product; Adasum tiles — pairwise
idempotent), so an un-grouped slot reduction equals the process reduction.
Gather-style ops (allgather / broadcast / alltoall / reducescatter) instead
use an internal process set containing one head slot per process.  With the
canonical deployment — one process per chip — both schemes degenerate to the
plain global collective.

Handles returned here resolve to **numpy** arrays; the framework layers wrap
them with their own tensor conversion and in-place semantics.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import basics
from .ops import collectives as C
from .process_sets import ProcessSet

Average = C.Average
Sum = C.Sum
Adasum = C.Adasum
Min = C.Min
Max = C.Max
Product = C.Product

REDUCE_OPS = (Average, Sum, Adasum, Min, Max, Product)


def x64_if(*dtypes):
    """64-bit transport context: JAX downcasts f64/i64 to 32 bits unless
    x64 mode is on (the reference's MPI/NCCL path is exact for these, so
    match it).  No-op for 32-bit-or-narrower wires."""
    if any(np.dtype(d).itemsize == 8 for d in dtypes):
        from ._compat import enable_x64

        return enable_x64(True)
    return contextlib.nullcontext()


def to_host(x) -> np.ndarray:
    """Materialize a replicated global jax.Array on this process."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)
    return np.asarray(x.addressable_shards[0].data)


def row_from_sharded(x, row: int) -> np.ndarray:
    """Extract one leading-dim row of a slot-sharded global array; the
    row must live on one of this process's devices."""
    if getattr(x, "is_fully_addressable", True):
        return np.asarray(x)[row]
    for s in x.addressable_shards:
        idx = s.index[0]
        start = idx.start or 0
        stop = idx.stop if idx.stop is not None else x.shape[0]
        if start <= row < stop:
            return np.asarray(s.data)[row - start]
    raise RuntimeError(f"Row {row} is not addressable from this process")


# --- process/world bookkeeping ----------------------------------------------

def world() -> Tuple[int, int, int]:
    """(process_count, process_index, local_size); asserts homogeneity."""
    basics._require_init()
    if not basics.is_homogeneous():
        raise RuntimeError(
            "host bindings require a homogeneous slot layout "
            "(equal local_size on every process)"
        )
    import jax

    return jax.process_count(), jax.process_index(), basics.local_size()


def head_slots() -> List[int]:
    """First slot index of each process, in process order."""
    gm = basics.global_mesh()
    heads: Dict[int, int] = {}
    for i, d in enumerate(gm.devices):
        heads.setdefault(d.process_index, i)
    return [heads[p] for p in sorted(heads)]


_slot_sets_lock = threading.Lock()
_slot_sets: Dict[Tuple[int, ...], ProcessSet] = {}   # guarded-by: _slot_sets_lock


def slot_set(slot_ranks: Sequence[int]) -> ProcessSet:
    """Registered slot-level process set for ``slot_ranks`` (cached —
    the core table rejects duplicate registrations)."""
    key = tuple(sorted(int(r) for r in slot_ranks))
    with _slot_sets_lock:
        ps = _slot_sets.get(key)
        if ps is None or ps.process_set_id is None:
            from .process_sets import add_process_set, _table

            # A user-registered set with the same ranks IS this slot set
            # (e.g. a subset ProcessSet in a one-chip-per-process world);
            # the core table rejects duplicate rank tuples.
            ps = _table().find(key)
            if ps is None:
                ps = add_process_set(ProcessSet(key))
            _slot_sets[key] = ps
        return ps


def member_ranks(process_set) -> Optional[List[int]]:
    """Process-level ranks of a user-supplied process set (None = all).

    Host-tier process sets are over *controller processes* (reference:
    one process per accelerator); ranks outside the process world are a
    caller error, reported eagerly rather than as an index crash in the
    head-slot translation."""
    if process_set is None:
        return None
    if getattr(process_set, "process_set_id", None) == 0:
        return None  # the global set (id 0 holds every slot, not processes)
    P_ = world()[0]
    ranks = list(process_set.ranks)
    if any(not 0 <= r < P_ for r in ranks):
        raise ValueError(
            f"Process set ranks {ranks} outside the process world "
            f"0..{P_ - 1}: host-tier process sets name controller "
            f"processes, not mesh slots")
    if len(ranks) == P_:
        return None
    return ranks


def set_size(process_set) -> int:
    """Member count of a process set (the whole world for None/global)."""
    ranks = member_ranks(process_set)
    return len(ranks) if ranks is not None else world()[0]


def require_member(ranks: Optional[List[int]], name: str) -> None:
    """Raise for callers outside the process set (reference semantics).
    Must only be called after every collective in the op has been
    dispatched, so member controllers are never left hanging."""
    if ranks is not None and world()[1] not in ranks:
        raise ValueError(
            f"{name}: this worker (rank {world()[1]}) is not a member of "
            f"the process set {ranks}")


_NEUTRAL = {Sum: 0, Average: 0, Min: None, Max: None, Product: 1}


def neutral_for(op: str, np_dtype) -> Any:
    if op == Min:
        return (np.finfo(np_dtype).max if np.issubdtype(np_dtype, np.floating)
                else np.iinfo(np_dtype).max)
    if op == Max:
        return (np.finfo(np_dtype).min if np.issubdtype(np_dtype, np.floating)
                else np.iinfo(np_dtype).min)
    return _NEUTRAL[op]


def local_block(value: np.ndarray, op: str, local_size: int) -> np.ndarray:
    """[local_size, *S] block: head row carries the value, the rest the
    op's neutral element (Adasum tiles — pairwise-idempotent)."""
    if op == Adasum:
        return np.broadcast_to(value[None], (local_size,) + value.shape).copy()
    block = np.empty((local_size,) + value.shape, dtype=value.dtype)
    block[0] = value
    if local_size > 1:
        block[1:] = neutral_for(op, value.dtype)
    return block


def lift_local(block: np.ndarray):
    """Hand a process-local [local_size, *S] block to the core: in
    multi-process runs the core lifts it via
    ``make_array_from_process_local_data``; in single-controller runs the
    block *is* the full stack."""
    return block


# --- handles -----------------------------------------------------------------

class HostHandle:
    """Async handle resolving to numpy (reference: the int handle of
    ``*_async`` ops resolved by ``HandleManager``).  Wraps the in-flight
    device value(s) plus the host-side finish step."""

    def __init__(self, raw, finish: Callable[[], Any], name: str = ""):
        self._raw = raw
        self._finish = finish
        self._result: Any = None
        self._done_flag = False
        self.name = name

    def wait(self):
        if not self._done_flag:
            self._result = self._finish()
            self._done_flag = True
        return self._result

    # alias so hvd.synchronize() treats HostHandle and the jit-tier Handle
    # uniformly
    def result(self):
        return self.wait()

    def done(self) -> bool:
        if self._done_flag:
            return True
        leaves = self._raw if isinstance(self._raw, (list, tuple)) else [self._raw]
        return all(getattr(l, "is_ready", lambda: True)() for l in leaves)


# --- allreduce ---------------------------------------------------------------

def _average_finish(r: np.ndarray, op: str, n: int) -> np.ndarray:
    if op == Average:
        if np.issubdtype(r.dtype, np.integer) or r.dtype == np.bool_:
            r = (r // n).astype(r.dtype)
        else:
            r = (r / n).astype(r.dtype)
    # 0-d arrays decay to numpy scalars under arithmetic; the framework
    # bridges (torch.from_numpy etc.) need real ndarrays.
    return np.asarray(r)


def allreduce_async(value: np.ndarray, *, op: str = Average,
                    process_set=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    compression=None,
                    name: str = "allreduce") -> HostHandle:
    """Process-level allreduce of one host array; resolves to numpy."""
    if op not in REDUCE_OPS:
        raise ValueError(f"Unknown reduction op: {op!r}")
    P_, _, L = world()
    ranks = member_ranks(process_set)
    n = len(ranks) if ranks is not None else P_
    block = local_block(value, op, L)
    core_op = Sum if op == Average else op
    slot_ps = None
    if ranks is not None:
        heads = head_slots()
        slot_ps = slot_set([heads[r] for r in ranks])
    if compression is None:
        from .ops.compression import Compression

        compression = Compression.none
    with x64_if(block.dtype):
        raw = C.allreduce_slots(
            lift_local(block), op=core_op, process_set=slot_ps,
            prescale_factor=float(prescale_factor),
            postscale_factor=float(postscale_factor),
            compression=compression, name=name)
    # Membership is checked *after* dispatch: every controller must issue
    # the same collective program or members would deadlock (SPMD); the
    # reference errors for non-members too (via the C++ status path).
    require_member(ranks, name)

    def finish():
        return _average_finish(to_host(raw), op, n)

    return HostHandle(raw, finish, name)


def grouped_allreduce_async(values: Sequence[np.ndarray], *, op: str = Average,
                            process_set=None, prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            compression=None,
                            name: str = "grouped_allreduce") -> HostHandle:
    """Fused process-level allreduce of several host arrays; resolves to
    a list of numpy arrays."""
    if op not in REDUCE_OPS:
        raise ValueError(f"Unknown reduction op: {op!r}")
    P_, _, L = world()
    ranks = member_ranks(process_set)
    n = len(ranks) if ranks is not None else P_
    core_op = Sum if op == Average else op
    slot_ps = None
    if ranks is not None:
        heads = head_slots()
        slot_ps = slot_set([heads[r] for r in ranks])
    if compression is None:
        from .ops.compression import Compression

        compression = Compression.none
    blocks = [lift_local(local_block(v, op, L)) for v in values]
    with x64_if(*[b.dtype for b in blocks]):
        raws = C.grouped_allreduce_slots(
            blocks, op=core_op, process_set=slot_ps,
            prescale_factor=float(prescale_factor),
            postscale_factor=float(postscale_factor),
            compression=compression, name=name)
    require_member(ranks, name)

    def finish():
        return [_average_finish(to_host(raw), op, n) for raw in raws]

    return HostHandle(raws, finish, name)


# --- allgather ---------------------------------------------------------------

def allgather_async(value: np.ndarray, *, process_set=None,
                    name: str = "allgather") -> HostHandle:
    """Concat along dim 0 over workers; supports ragged first dims (the
    reference's MPI_Allgatherv) via a max-pad + slice round."""
    P_, rank_, L = world()
    ranks = member_ranks(process_set)
    members = ranks if ranks is not None else list(range(P_))
    heads = head_slots()
    ps = slot_set([heads[r] for r in members])

    if value.ndim == 0:
        value = value[None]
    k_local = value.shape[0]

    # Round 1 (dispatched async here): the (possibly ragged) first-dim
    # lengths.  Round 2 depends on the global max length, so it is
    # deferred to finish() — queued allgather_asyncs thus overlap their
    # length exchanges, and wait() order defines round-2 dispatch order
    # (keep it consistent across workers, as with any collective).
    len_block = np.zeros((L, 1), np.int32)
    len_block[0, 0] = k_local
    len_raw = C.allgather_slots(lift_local(len_block), process_set=ps,
                          name=f"{name}.lengths")

    def finish():
        # NOTE: the not-a-member raise must wait until BOTH rounds are
        # dispatched — this is a two-collective op, and a non-member
        # controller that bails between rounds leaves the members
        # hanging in round 2 (found by the np=4 non-contiguous-subset
        # tier, tests/multiproc/test_process_sets_mp.py).  SPMD rule:
        # every controller dispatches every program, members or not.
        lengths = to_host(len_raw).reshape(-1)
        k_max = int(lengths.max())
        padded = np.zeros((k_max,) + value.shape[1:], dtype=value.dtype)
        # k_max spans MEMBER lengths only; a non-member's longer local
        # value must truncate (its rows are discarded by the groups
        # anyway) — overflowing here would bail before the round-2
        # dispatch and hang the members.
        padded[:min(k_local, k_max)] = value[:k_max]
        block = np.zeros((L,) + padded.shape, dtype=value.dtype)
        block[0] = padded
        with x64_if(block.dtype):
            raw = C.allgather_slots(lift_local(block), process_set=ps, name=name)
        require_member(ranks, name)
        g = to_host(raw).reshape((len(members), k_max) + value.shape[1:])
        parts = [g[i, : int(lengths[i])] for i in range(len(members))]
        return np.concatenate(parts, axis=0)

    return HostHandle(len_raw, finish, name)


# --- broadcast ---------------------------------------------------------------

def broadcast_async(value: np.ndarray, root_rank: int = 0, *,
                    process_set=None, name: str = "broadcast") -> HostHandle:
    """Every worker resolves to the root worker's array."""
    P_, _, L = world()
    ranks = member_ranks(process_set)
    if ranks is not None and root_rank not in ranks:
        raise ValueError(f"{name}: root rank {root_rank} not in process set")
    block = np.broadcast_to(value[None], (L,) + value.shape).copy()
    root_slot = head_slots()[root_rank]
    with x64_if(block.dtype):
        raw = C.broadcast_slots(lift_local(block), root_rank=root_slot, name=name)
    require_member(ranks, name)

    def finish():
        return to_host(raw)

    return HostHandle(raw, finish, name)


# --- alltoall ----------------------------------------------------------------

def alltoall(value: np.ndarray, splits: Optional[np.ndarray] = None, *,
             process_set=None,
             name: str = "alltoall") -> Tuple[np.ndarray, np.ndarray]:
    """Scatter dim-0 chunks to every worker, gather received chunks;
    returns ``(gathered, received_splits)``.  Ragged splits ride a
    max-pad exchange (XLA needs static shapes)."""
    P_, rank_, L = world()
    ranks = member_ranks(process_set)
    members = ranks if ranks is not None else list(range(P_))
    n = len(members)
    heads = head_slots()
    ps = slot_set([heads[r] for r in members])
    is_member = rank_ in members
    me = members.index(rank_) if is_member else None

    if not is_member:
        split_sizes = np.zeros((n,), np.int64)  # dispatch-only contribution
    elif splits is None:
        if value.shape[0] % n != 0:
            raise ValueError(
                f"{name}: dim 0 ({value.shape[0]}) not divisible by the "
                f"worker count {n}; pass explicit splits")
        split_sizes = np.full((n,), value.shape[0] // n, np.int64)
    else:
        split_sizes = np.asarray(splits, np.int64).reshape(-1)
        if split_sizes.shape[0] != n or int(split_sizes.sum()) != value.shape[0]:
            raise ValueError(f"{name}: splits must have {n} entries summing "
                             f"to dim 0 ({value.shape[0]})")

    # Exchange the full split matrix S[i, j] = worker i's chunk size for
    # destination j via one summed allreduce: replicated on every
    # controller, so the padded chunk size below is globally agreed and
    # all controllers dispatch the identical program (SPMD requirement).
    sp_local = np.zeros((n, n), np.int32)
    if is_member:
        sp_local[me] = split_sizes
    sp_block = local_block(sp_local, Sum, L)
    S = to_host(C.allreduce_slots(lift_local(sp_block), op=Sum,
                            name=f"{name}.splits"))
    k_max = max(int(S.max()), 1)

    chunks = np.zeros((n, k_max) + value.shape[1:], dtype=value.dtype)
    off = 0
    for i, s in enumerate(split_sizes):
        chunks[i, : int(s)] = value[off: off + int(s)]
        off += int(s)
    block = np.zeros((L, n * k_max) + value.shape[1:], dtype=value.dtype)
    block[0] = chunks.reshape((n * k_max,) + value.shape[1:])
    with x64_if(block.dtype):
        raw = C.alltoall_slots(lift_local(block), process_set=ps, name=name)
    require_member(ranks, name)

    received_splits = S[:, me]
    # Output rows are indexed by *global slot*, so read this process's own
    # head slot — not heads[me], which is the me-th member's slot and only
    # coincides for the global set (ADVICE r1, subset-set corruption).
    got = row_from_sharded(raw, heads[rank_]).reshape(
        (n, k_max) + value.shape[1:])
    parts = [got[i, : int(received_splits[i])] for i in range(n)]
    gathered = np.concatenate(parts, axis=0)
    return gathered, received_splits.astype(np.int64)


# --- reducescatter -----------------------------------------------------------

def reducescatter(value: np.ndarray, *, op: str = Sum, process_set=None,
                  name: str = "reducescatter") -> np.ndarray:
    """Reduce then scatter dim-0 shards; dim 0 must divide by the worker
    count."""
    P_, rank_, L = world()
    ranks = member_ranks(process_set)
    members = ranks if ranks is not None else list(range(P_))
    n = len(members)
    heads = head_slots()
    ps = slot_set([heads[r] for r in members])
    if value.shape[0] % n != 0:
        raise ValueError(f"{name}: dim 0 ({value.shape[0]}) not divisible "
                         f"by worker count {n}")
    block = np.zeros((L,) + value.shape, dtype=value.dtype)
    block[0] = value
    with x64_if(block.dtype):
        raw = C.reducescatter_slots(lift_local(block), op=op, process_set=ps,
                              name=name)
    require_member(ranks, name)
    # Average over member slots == over member processes (neutral rows),
    # so the core's op handling is already process-correct here.  Output
    # rows are indexed by global slot: read this process's own head slot.
    return row_from_sharded(raw, heads[rank_])


def grouped_reducescatter_async(values: Sequence[np.ndarray], *,
                                op: str = Sum, process_set=None,
                                name: str = "grouped_reducescatter"
                                ) -> HostHandle:
    """Fused process-level reducescatter of several host arrays — ONE
    slot-tier dispatch for the whole set (grouped_reducescatter_slots:
    one compiled program, one reduction per dtype bucket) instead of the
    per-tensor loop the tf/torch shims used to run; resolves to the
    list of this process's shards."""
    P_, rank_, L = world()
    ranks = member_ranks(process_set)
    members = ranks if ranks is not None else list(range(P_))
    n = len(members)
    heads = head_slots()
    ps = slot_set([heads[r] for r in members])
    for i, value in enumerate(values):
        if value.shape[0] % n != 0:
            raise ValueError(f"{name}[{i}]: dim 0 ({value.shape[0]}) not "
                             f"divisible by worker count {n}")
    blocks = []
    for value in values:
        block = np.zeros((L,) + value.shape, dtype=value.dtype)
        block[0] = value
        blocks.append(lift_local(block))
    with x64_if(*[b.dtype for b in blocks]):
        raws = C.grouped_reducescatter_slots(blocks, op=op, process_set=ps,
                                             name=name)
    require_member(ranks, name)

    def finish():
        # Output rows are indexed by global slot: read this process's
        # own head slot (see the alltoall note on heads[me] vs
        # heads[rank_]).
        return [row_from_sharded(raw, heads[rank_]) for raw in raws]

    return HostHandle(raws, finish, name)


def grouped_reducescatter(values: Sequence[np.ndarray], *, op: str = Sum,
                          process_set=None,
                          name: str = "grouped_reducescatter"
                          ) -> List[np.ndarray]:
    """Synchronous form of :func:`grouped_reducescatter_async`."""
    return grouped_reducescatter_async(values, op=op,
                                       process_set=process_set,
                                       name=name).wait()


# --- barrier / join ----------------------------------------------------------

def barrier(process_set=None, name: str = "barrier") -> None:
    ranks = member_ranks(process_set)
    slot_ps = None
    if ranks is not None:
        heads = head_slots()
        slot_ps = slot_set([heads[r] for r in ranks])
    C.barrier(process_set=slot_ps, name=name)


def join() -> int:
    return C.join()
