"""Continuous-batching inference engine over ``models.transformer.GPT``.

The serving hot path is two compiled programs:

* **prefill** — one program per *length bucket* ``L``: run the prompt
  (padded to ``L``) through the model, sample the first token, and
  write its K/V into this request's cache.  Padding prompts to a small
  set of bucket shapes bounds recompiles.  With prefix sharing the
  bucket is chosen for the *suffix*: a prompt whose leading tokens are
  resident in the KV pool recomputes only what is not cached — the
  cache-hit TTFT win.
* **decode** — ONE program for the whole slot batch: every active
  request advances per call, each slot at its own depth.  This is the
  continuous-batching property: admission never waits for the batch to
  drain.

Two KV layouts live under this one API (``HVD_TPU_SERVE_KV``):

* **paged** (default) — one ``[num_blocks, block, H, D]`` pool per
  layer plus a host-side block table (``serve/kv/``): requests map
  onto refcounted fixed-size token blocks, identical prompt prefixes
  share physical blocks (copy-on-write on first divergent write), and
  unreferenced prefix blocks are LRU-evicted under pressure.  The
  jitted programs index the pool *through* a per-slot block-table
  array, so there is still ONE compiled decode program — the table is
  data, not shape.  Block 0 is a reserved *trash block*: unmapped
  table entries point at it and invalid positions (padding, rejected
  speculative tokens, past-the-cache) clamp into it, which replaces
  every masking lattice around scatter/gather.
* **dense** — the original per-slot ``[slots, S, H, D]`` rows; kept as
  the token-identity oracle the paged path is tested against.

**Speculative decoding** (per-request opt-in via
``SamplingParams(spec=True)``; greedy requests only): a small drafter
model proposes ``HVD_TPU_SERVE_SPEC_K`` tokens per step, the target
model verifies the whole draft in ONE batched forward inside the same
compiled-program regime, and accepted-prefix semantics guarantee the
emitted tokens are identical to plain greedy decode — a wrong draft
costs speed, never correctness (docs/serving.md has the proof sketch).

Neither program contains a cross-replica collective — the per-token hot
path is replica-local by construction; replication happens one level
up, in ``serve/router.py`` over process sets.

Sampling is greedy / temperature / top-k, resolved **per slot** inside
the one decode program (a ``where`` lattice, not a recompile), so mixed
sampling configs batch together.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from ..models.transformer import GPT, init_kv_cache
from ..utils.logging import get_logger
from .kv import BlockPool, TRASH_BLOCK

logger = get_logger(__name__)


def resolved_config():
    """The serving layer's config source: the live Config when this
    process ran ``hvd.init``, else a fresh env parse (same parser, same
    defaults — the network.py convention, so a bare engine in a script
    and a served engine under the launcher read identical knobs)."""
    from .. import basics
    from ..config import Config

    return basics.config() if basics.is_initialized() else Config.from_env()


class PromptTooLongError(ValueError):
    """Prompt exceeds the largest prefill bucket / cache length."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (greedy when ``temperature == 0``).
    ``spec=True`` opts the request into speculative decoding (engines
    built with a drafter; greedy requests only — temperature rows in
    the same batch keep plain single-token semantics)."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0                 # 0 = full vocab
    stop_token: Optional[int] = None
    spec: bool = False


def _sample(logits, rng, temps, topks):
    """Per-row sampling over ``[B, V]`` float32 logits: greedy rows
    (``temp <= 0``) take argmax; the rest draw from temperature-scaled
    logits restricted to each row's top-k (k per row — ranks against a
    per-row threshold instead of a static ``lax.top_k`` width)."""
    greedy = jnp.argmax(logits, axis=-1)
    ranks = jnp.argsort(jnp.argsort(-logits, axis=-1), axis=-1)
    k = jnp.where(topks > 0, topks, logits.shape[-1])[:, None]
    masked = jnp.where(ranks < k, logits, -jnp.inf)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


class InferenceEngine:
    """Slot-based prefill/decode engine; the batcher owns scheduling.

    ``start(slot, prompt, sampling)`` prefixes a request into ``slot``
    and returns its first token; ``step()`` decodes for every active
    slot and returns ``{slot: [tokens]}`` — one token per slot on the
    plain path, up to ``spec_k + 1`` under speculative decoding.
    Per-phase wall time lands on the framework Timeline (phases
    ``SERVE_PREFILL`` / ``SERVE_DECODE``) when one is active.
    """

    def __init__(self, model: GPT, params, *,
                 max_slots: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 max_seq_len: Optional[int] = None,
                 kv_cache: Optional[str] = None,
                 kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 drafter: Optional[Tuple[GPT, dict]] = None,
                 spec_k: Optional[int] = None,
                 tp: Optional[int] = None,
                 weights_version: int = 0,
                 seed: int = 0):
        cfg = resolved_config()
        self._model = model
        self._params = params
        self.max_slots = int(max_slots or cfg.serve_max_batch)
        self.max_seq_len = int(max_seq_len or model.config.max_seq_len)
        if self.max_seq_len > model.config.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"positional table ({model.config.max_seq_len})")
        buckets = tuple(prefill_buckets or cfg.serve_prefill_buckets)
        # Clamp buckets to the cache length; keep at least one.
        self.prefill_buckets = tuple(sorted(
            {min(int(b), self.max_seq_len) for b in buckets if b > 0}))
        if not self.prefill_buckets:
            raise ValueError(f"no usable prefill buckets in {buckets}")
        self.kv_mode = (kv_cache or cfg.serve_kv).lower()
        if self.kv_mode not in ("paged", "dense"):
            raise ValueError(f"unknown kv_cache mode {self.kv_mode!r}; "
                             f"expected 'paged' or 'dense'")
        # Tensor-parallel replica (docs/tp_serving.md): the forward
        # shards over a 1-D ``tensor`` mesh spanning the first ``tp``
        # local devices — column-parallel qkv/up placement plus the
        # model's gather-before-contract constraints keep the decode
        # bitwise identical to tp=1, so TP is a capacity/latency knob,
        # never a correctness one.  The paged KV pool shards on its
        # head dim (each device holds H/tp heads of every block) while
        # the block table and BlockPool bookkeeping stay rank-invariant
        # host state.
        self.tp = int(tp if tp is not None else cfg.serve_tp)
        self._tp_mesh = None
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {self.tp}")
        if self.tp > 1:
            if self.kv_mode != "paged":
                raise ValueError(
                    "tensor-parallel serving requires the paged KV "
                    "cache (HVD_TPU_SERVE_KV=paged) — the head-sharded "
                    "pool is the TP layout")
            if model.config.n_head % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide the model's head count "
                    f"({model.config.n_head}) for the head-sharded pool")
            from ..plan import tp_plan

            plan = tp_plan(self.tp)
            self._tp_mesh = plan.mesh
            self._model = model = GPT(
                config=dataclasses.replace(model.config,
                                           tp_mesh=plan.mesh,
                                           tp_axis="tensor"),
                mesh=model.mesh)
            self._params = params = self._tp_place_params(params)
        # Slot-state arrays: every mutation goes through the guarded
        # helpers below (_bind_slot / _advance_slot / _clear_slot) so
        # the hvdlint lock checker covers them — release() arrives from
        # RPC handler threads (router cancel) while the batcher thread
        # is mid-step.
        self._slot_lock = threading.Lock()
        self._positions = np.zeros(self.max_slots, np.int32)   # guarded-by: _slot_lock
        self._active = np.zeros(self.max_slots, bool)          # guarded-by: _slot_lock
        self._temps = np.zeros(self.max_slots, np.float32)     # guarded-by: _slot_lock
        self._topks = np.zeros(self.max_slots, np.int32)       # guarded-by: _slot_lock
        self._last_tokens = np.zeros(self.max_slots, np.int32)  # guarded-by: _slot_lock
        self._spec = np.zeros(self.max_slots, bool)            # guarded-by: _slot_lock
        self._prefix_hits = np.zeros(self.max_slots, np.int32)  # guarded-by: _slot_lock
        # Weight hot-swap state (serve/swap.py; docs/hot_swap.md): the
        # running version (the checkpoint step the params came from —
        # 0 for boot weights that never touched the store) and the
        # staged next version awaiting the batcher's flip barrier.
        # Version is read from RPC/stats threads while the batcher
        # thread flips it, and staging happens on the subscriber thread
        # — both ride the slot lock.
        self._weights_version = int(weights_version)  # guarded-by: _slot_lock
        self._staged_params = None                    # guarded-by: _slot_lock
        self._staged_version = None                   # guarded-by: _slot_lock
        self._rng = jax.random.PRNGKey(seed)
        # Trace-time counters: the bounded-recompile contract is
        # testable (each jitted program bumps its key once per trace).
        self.trace_counts = collections.Counter()
        # Donate the engine-wide cache/pool so prefill/decode update it
        # in place — without donation XLA copies the full cache every
        # token, which dominates decode at real cache sizes.  CPU has
        # no donation support (it would only warn), so gate on backend.
        self._donate = (1,) if jax.default_backend() != "cpu" else ()
        n_layer = model.config.n_layer
        head_dim = model.config.d_model // model.config.n_head
        if self.kv_mode == "paged":
            self.kv_block = int(kv_block or cfg.serve_kv_block)
            if self.kv_block < 1:
                raise ValueError(f"kv_block must be >= 1, got "
                                 f"{self.kv_block}")
            self.blocks_per_slot = -(-self.max_seq_len // self.kv_block)
            floor = 1 + self.max_slots * self.blocks_per_slot
            budget = int(kv_blocks if kv_blocks is not None
                         else cfg.serve_kv_blocks)
            if budget == 0:
                # Auto: every slot fully servable plus an equal share
                # of prefix-cache headroom.
                budget = 1 + 2 * self.max_slots * self.blocks_per_slot
            if budget < floor:
                raise ValueError(
                    f"KV pool budget {budget} below the floor {floor} "
                    f"(1 trash + slots x blocks_per_slot) — active "
                    f"requests could deadlock on allocation")
            self.kv_blocks = budget
            shape = (budget, self.kv_block, model.config.n_head, head_dim)

            def _pool_zeros():
                z = jnp.zeros(shape, model.config.dtype)
                if self._tp_mesh is not None:
                    # Head-sharded pool: each shard device holds only
                    # its H/tp heads of every block; the block table
                    # stays whole-pool host state.
                    z = jax.device_put(z, NamedSharding(
                        self._tp_mesh,
                        PartitionSpec(None, None, "tensor", None)))
                return z

            self._pools = [{"k": _pool_zeros(), "v": _pool_zeros()}
                           for _ in range(n_layer)]
            # Block table: one trailing trash column the jitted
            # programs clamp invalid positions into (serve/kv/pool.py).
            self._table = np.full(
                (self.max_slots, self.blocks_per_slot + 1),
                TRASH_BLOCK, np.int32)
            self._copy_fn = jax.jit(
                self._copy_impl,
                donate_argnums=(0,) if self._donate else ())
            self._import_fn = jax.jit(
                self._import_impl,
                donate_argnums=(0,) if self._donate else ())
            dt_size = np.dtype(model.config.dtype).itemsize
            self._kv = BlockPool(
                budget, self.kv_block, self._table, self._copy_block,
                heads=model.config.n_head // self.tp,
                tp_degree=self.tp,
                # Per-SHARD bytes of one block: K+V rows for the H/tp
                # heads this shard holds, across every layer.
                bytes_per_block=(2 * n_layer * self.kv_block
                                 * (model.config.n_head // self.tp)
                                 * head_dim * dt_size))
            self._caches = None
            self._decode_fn = jax.jit(self._decode_paged_impl,
                                      donate_argnums=self._donate)
            self._prefill_fns = {L: self._make_paged_prefill(L)
                                 for L in self.prefill_buckets}
        else:
            self.kv_block = 0
            self.kv_blocks = 0
            self._kv = None
            self._caches = init_kv_cache(model.config, self.max_slots,
                                         self.max_seq_len)
            self._decode_fn = jax.jit(self._decode_impl,
                                      donate_argnums=self._donate)
            self._prefill_fns = {L: self._make_prefill(L)
                                 for L in self.prefill_buckets}
        # Speculative decoding: drafter = (small GPT, its params).
        self._drafter = None
        self._drafter_params = None
        self._drafter_caches = None
        self.spec_k = int(spec_k or cfg.serve_spec_k)
        self.spec_verify_steps = 0
        self.spec_accepted_tokens = 0
        if drafter is not None:
            if self.kv_mode != "paged":
                raise ValueError("speculative decoding requires the "
                                 "paged KV cache (HVD_TPU_SERVE_KV=paged)")
            dmodel, dparams = drafter
            if dmodel.config.max_seq_len < self.max_seq_len:
                raise ValueError(
                    f"drafter positional table "
                    f"({dmodel.config.max_seq_len}) shorter than the "
                    f"serving cache ({self.max_seq_len})")
            self._drafter = dmodel
            self._drafter_params = dparams
            self._drafter_caches = init_kv_cache(
                dmodel.config, self.max_slots, self.max_seq_len)
            self._draft_prefill_fns = {L: self._make_draft_prefill(L)
                                       for L in self.prefill_buckets}
            self._spec_draft_fn = jax.jit(
                self._spec_draft_impl, donate_argnums=self._donate)
            self._spec_verify_fn = jax.jit(
                self._spec_verify_impl, donate_argnums=self._donate)

    # --- tensor-parallel placement ------------------------------------------

    def _tp_place_params(self, tree):
        """Place a host/device param tree on the TP mesh per the
        planner's device rule (``plan.tp_param_spec``): qkv/up kernels
        column-sharded, everything else replicated.  Used at
        construction AND by :meth:`stage_params` so a hot-swapped tree
        lands with the layout the compiled programs were traced for —
        a swap never costs a recompile."""
        from ..ckpt.snapshot import path_string
        from ..plan import tp_param_spec

        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        placed = [
            jax.device_put(leaf, NamedSharding(
                self._tp_mesh,
                tp_param_spec(path_string(path), leaf, self.tp)))
            for path, leaf in flat
        ]
        return jax.tree_util.tree_unflatten(treedef, placed)

    # --- paged-view geometry ------------------------------------------------

    @property
    def _view_len(self) -> int:
        """Gathered per-slot view length: chain blocks + the trash
        column — always > max_seq_len, so clamped-invalid positions
        land in trash rows no valid query can see."""
        return (self.blocks_per_slot + 1) * self.kv_block

    # --- compiled programs: dense tier --------------------------------------

    def _make_prefill(self, L: int):
        model, n_layer = self._model, self._model.config.n_layer

        def prefill(params, caches, tokens, length, slot, rng, temp, topk):
            self.trace_counts[f"prefill_{L}"] += 1  # trace-time only
            positions = jnp.arange(L, dtype=jnp.int32)[None]
            row = init_kv_cache(model.config, 1, L)
            logits, row = model.apply({"params": params}, tokens,
                                      kv_caches=row, positions=positions)
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                axis=0, keepdims=False)
            token = _sample(last[None].astype(jnp.float32), rng,
                            temp[None], topk[None])[0]

            def write(big, chunk):
                return jax.lax.dynamic_update_slice(
                    big, chunk.astype(big.dtype), (slot, 0, 0, 0))

            new = [{"k": write(caches[i]["k"], row[i]["k"]),
                    "v": write(caches[i]["v"], row[i]["v"])}
                   for i in range(n_layer)]
            return token, new

        return jax.jit(prefill, donate_argnums=self._donate)

    def _decode_impl(self, params, caches, tokens, positions, temps,
                     topks, rng):
        self.trace_counts["decode"] += 1  # trace-time only
        logits, new = self._model.apply(
            {"params": params}, tokens[:, None], kv_caches=caches,
            positions=positions[:, None])
        nxt = _sample(logits[:, -1].astype(jnp.float32), rng, temps, topks)
        return nxt, new

    # --- compiled programs: paged tier --------------------------------------

    def _paged_caches(self, pools, tables):
        return [{"k_pool": pools[i]["k"], "v_pool": pools[i]["v"],
                 "table": tables}
                for i in range(self._model.config.n_layer)]

    def _scatter_chunk(self, pools, chunk, blk, off):
        """Write chunk K/V rows into the pools at ``(blk, off)`` (flat
        index arrays; invalid traffic already routed to the trash
        block by the callers' position clamping)."""
        new = []
        for i in range(self._model.config.n_layer):
            k_c = chunk[i]["k"].reshape((-1,) + chunk[i]["k"].shape[-2:])
            v_c = chunk[i]["v"].reshape((-1,) + chunk[i]["v"].shape[-2:])
            new.append({
                "k": pools[i]["k"].at[blk, off].set(
                    k_c.astype(pools[i]["k"].dtype)),
                "v": pools[i]["v"].at[blk, off].set(
                    v_c.astype(pools[i]["v"].dtype)),
            })
        return new

    def _copy_impl(self, pools, src, dst):
        self.trace_counts["kv_copy"] += 1  # trace-time only
        return [{"k": p["k"].at[dst].set(p["k"][src]),
                 "v": p["v"].at[dst].set(p["v"][src])} for p in pools]

    def _copy_block(self, src: int, dst: int) -> None:
        """Device block copy (COW / partial-prefix admission) — the
        callback :class:`BlockPool` drives."""
        self._pools = self._copy_fn(self._pools, jnp.int32(src),
                                    jnp.int32(dst))

    def _import_impl(self, pools, blk, k, v):
        """Write one wire-received block's K/V (``[n_layer, block, H,
        D]``) into every layer's pool at block ``blk`` — the binding
        half of live KV migration (ONE compiled program: the block id
        is data, not shape)."""
        self.trace_counts["kv_import"] += 1  # trace-time only
        return [{"k": pools[i]["k"].at[blk].set(
                     k[i].astype(pools[i]["k"].dtype)),
                 "v": pools[i]["v"].at[blk].set(
                     v[i].astype(pools[i]["v"].dtype))}
                for i in range(self._model.config.n_layer)]

    def _make_paged_prefill(self, L: int):
        model = self._model
        B, S, SV = self.kv_block, self.max_seq_len, self._view_len

        def prefill(params, pools, table_row, tokens, start, length,
                    rng, temp, topk):
            # ``start`` = resident-prefix length (the suffix's first
            # absolute position); ``length`` = real suffix tokens in
            # the L-padded chunk.  Both are traced values: one compiled
            # program per bucket regardless of hit depth.
            self.trace_counts[f"prefill_{L}"] += 1  # trace-time only
            idx = jnp.arange(L, dtype=jnp.int32)
            valid = (idx < length) & (start + idx < S)
            positions = jnp.where(valid, start + idx, SV - 1)
            caches = self._paged_caches(pools, table_row[None])
            logits, chunk = model.apply(
                {"params": params}, tokens, kv_caches=caches,
                positions=positions[None])
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                axis=0, keepdims=False)
            token = _sample(last[None].astype(jnp.float32), rng,
                            temp[None], topk[None])[0]
            blk = table_row[positions // B]   # invalid -> trash column
            new = self._scatter_chunk(pools, chunk, blk, positions % B)
            return token, new

        return jax.jit(prefill, donate_argnums=self._donate)

    def _decode_paged_impl(self, params, pools, tables, tokens,
                           positions, temps, topks, rng):
        self.trace_counts["decode"] += 1  # trace-time only
        caches = self._paged_caches(pools, tables)
        logits, chunk = self._model.apply(
            {"params": params}, tokens[:, None], kv_caches=caches,
            positions=positions[:, None])
        nxt = _sample(logits[:, -1].astype(jnp.float32), rng, temps, topks)
        B = self.kv_block
        blk = jnp.take_along_axis(tables, (positions // B)[:, None],
                                  axis=1)[:, 0]
        new = self._scatter_chunk(pools, chunk, blk, positions % B)
        return nxt, new

    # --- compiled programs: speculative tier --------------------------------

    def _make_draft_prefill(self, L: int):
        drafter = self._drafter
        n_layer = drafter.config.n_layer

        def dprefill(dparams, dcaches, tokens, slot):
            self.trace_counts[f"draft_prefill_{L}"] += 1  # trace-time
            positions = jnp.arange(L, dtype=jnp.int32)[None]
            row = init_kv_cache(drafter.config, 1, L)
            _, row = drafter.apply({"params": dparams}, tokens,
                                   kv_caches=row, positions=positions)

            def write(big, chunk):
                return jax.lax.dynamic_update_slice(
                    big, chunk.astype(big.dtype), (slot, 0, 0, 0))

            return [{"k": write(dcaches[i]["k"], row[i]["k"]),
                     "v": write(dcaches[i]["v"], row[i]["v"])}
                    for i in range(n_layer)]

        return jax.jit(dprefill, donate_argnums=self._donate)

    def _spec_draft_impl(self, dparams, dcaches, tokens, positions):
        """Greedy-draft ``spec_k`` tokens for every slot in ONE program
        (a ``lax.scan`` over the drafter's own dense decode).  The scan
        runs ``K + 1`` iterations: the extra step feeds the last draft
        token so its K/V lands too — with a fully accepted draft the
        next step starts at ``p + K + 1``, and a gap at ``p + K`` would
        silently degrade every later draft (the verify path would still
        be exact; only acceptance would rot).  Entries past the
        accepted prefix go stale but are overwritten sequentially
        before any query can see them (same argument as slot reuse)."""
        self.trace_counts["spec_draft"] += 1  # trace-time only
        drafter = self._drafter

        def body(carry, _):
            caches, toks, pos = carry
            logits, caches = drafter.apply(
                {"params": dparams}, toks[:, None], kv_caches=caches,
                positions=pos[:, None])
            nxt = jnp.argmax(logits[:, -1].astype(jnp.float32),
                             axis=-1).astype(jnp.int32)
            return (caches, nxt, pos + 1), nxt

        (dcaches, _, _), drafts = jax.lax.scan(
            body, (dcaches, tokens, positions), None,
            length=self.spec_k + 1)
        return jnp.moveaxis(drafts[:self.spec_k], 0, 1), dcaches

    def _spec_verify_impl(self, params, pools, tables, tokens, draft,
                          positions, temps, topks, spec_ok, rng):
        """Verify the whole draft in one batched target forward.

        Chunk ``[t0, d1..dK]`` runs at positions ``p..p+K``; the
        accepted prefix is the longest run of drafts matching the
        target's own greedy chain, so the emitted tokens are exactly
        what plain greedy decode would produce (docs/serving.md).  Only
        chunk rows ``<= accepted`` persist their K/V — rejected rows
        scatter into the trash block and the correct token rewrites
        that position next step.  Rows with ``spec_ok`` false (no
        opt-in, or temperature sampling) accept nothing and emit one
        plain-sampled token."""
        self.trace_counts["spec_verify"] += 1  # trace-time only
        K = self.spec_k
        B, S, SV = self.kv_block, self.max_seq_len, self._view_len
        chunk_toks = jnp.concatenate([tokens[:, None], draft], axis=1)
        idx = jnp.arange(K + 1, dtype=jnp.int32)[None]
        pos = positions[:, None] + idx
        pos_safe = jnp.where(pos < S, pos, SV - 1)
        caches = self._paged_caches(pools, tables)
        logits, chunk = self._model.apply(
            {"params": params}, chunk_toks, kv_caches=caches,
            positions=pos_safe)
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        matches = (draft == greedy[:, :K]).astype(jnp.int32)
        accepted = jnp.cumprod(matches, axis=1).sum(axis=1)
        accepted = jnp.where(spec_ok, accepted, 0)
        # The last emitted token needs no K/V write, but every ACCEPTED
        # draft does — cap acceptance at the cache's remaining rows.
        accepted = jnp.minimum(accepted,
                               jnp.maximum(S - 1 - positions, 0))
        first = _sample(logits[:, 0], rng, temps, topks)
        out = greedy.at[:, 0].set(first)   # argmax already, unless temp>0
        keep = (idx <= accepted[:, None]) & (pos < S)
        pos_w = jnp.where(keep, pos, SV - 1)
        blk = jnp.take_along_axis(tables, pos_w // B, axis=1)
        new = self._scatter_chunk(pools, chunk, blk.reshape(-1),
                                  (pos_w % B).reshape(-1))
        return out, accepted, new

    # --- host-side slot API -------------------------------------------------

    def _activity(self, name: str, phase: str, args=None):
        """Timeline span for one serving phase (no-op without an active
        framework timeline)."""
        import contextlib

        from .. import basics

        tl = basics.peek("timeline")   # fail-soft: None pre-init
        if tl is None or not tl.enabled:
            return contextlib.nullcontext()
        return tl.activity(name, phase, args)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLongError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket ({self.prefill_buckets[-1]})")

    def check_prompt(self, prompt_len: int) -> int:
        """Full admission-time validation (the batcher calls this so an
        unservable prompt fails before it costs a queue entry): bucket
        fit AND room to generate.  Returns the bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len >= self.max_seq_len:
            raise PromptTooLongError(
                f"prompt of {prompt_len} tokens leaves no room to "
                f"generate (cache length {self.max_seq_len})")
        return self.bucket_for(prompt_len)

    def check_prompt_tokens(self, prompt: Sequence[int]) -> int:
        """:meth:`check_prompt` plus token-ID range validation.  An
        out-of-vocab id embeds as NaN (``jnp.take`` fill semantics),
        and the paged pool is a SHARED structure: one poison request's
        NaN rows would outlive it in the trash/prefix blocks and
        contaminate every later batchmate through the ``0 x NaN``
        attention sum — so the poison must die at admission, not in
        the pool."""
        bucket = self.check_prompt(len(prompt))
        vocab = self._model.config.vocab_size
        lo, hi = min(prompt), max(prompt)   # C-speed single pass
        if lo < 0 or hi >= vocab:
            raise ValueError(
                f"prompt token id {lo if lo < 0 else hi} outside the "
                f"model vocabulary [0, {vocab})")
        return bucket

    def free_slots(self) -> List[int]:
        with self._slot_lock:
            return [int(s) for s in np.nonzero(~self._active)[0]]

    def active_slots(self) -> List[int]:
        with self._slot_lock:
            return [int(s) for s in np.nonzero(self._active)[0]]

    def slot_full(self, slot: int) -> bool:
        """True when the next decode would write past the cache (the
        next decode writes K/V at index ``_positions[slot]``, valid
        while it is ``< max_seq_len``)."""
        with self._slot_lock:
            return int(self._positions[slot]) >= self.max_seq_len

    def _slot_snapshot(self):
        """Locked copy of the decode-relevant slot arrays: the step
        paths read ONE consistent view instead of racing router-thread
        release()/adopt() mutations field by field (hvdsan read-site
        catch — max_slots-sized copies, nanoseconds)."""
        with self._slot_lock:
            return (self._active.copy(), self._positions.copy(),
                    self._temps.copy(), self._topks.copy(),
                    self._last_tokens.copy(), self._spec.copy())

    # --- guarded slot-state mutation ----------------------------------------
    # The ONE place slot state changes (the hvdlint lock checker holds
    # every annotated mutation to a lexical ``with _slot_lock`` block):
    # prefill used to write these fields inline next to the cache-chunk
    # write, which left router-thread release() racing the batcher.

    def _bind_slot(self, slot: int, n_prompt: int, token: int,
                   sampling: SamplingParams, prefix_hit: int) -> None:
        with self._slot_lock:
            self._active[slot] = True
            self._positions[slot] = n_prompt   # first generated index
            self._temps[slot] = sampling.temperature
            self._topks[slot] = sampling.top_k
            self._last_tokens[slot] = token    # first decode consumes it
            self._spec[slot] = bool(sampling.spec)
            self._prefix_hits[slot] = prefix_hit

    def _advance_slot(self, slot: int, tokens: List[int]) -> None:
        with self._slot_lock:
            if not self._active[slot]:
                return   # released concurrently (cancel): drop
            self._last_tokens[slot] = tokens[-1]
            self._positions[slot] += len(tokens)

    def _clear_slot(self, slot: int) -> None:
        with self._slot_lock:
            self._active[slot] = False
            self._positions[slot] = 0
            self._temps[slot] = 0.0
            self._topks[slot] = 0
            self._spec[slot] = False
            self._prefix_hits[slot] = 0

    # --- prefix sharing -----------------------------------------------------

    def prefix_probe(self, prompt: Sequence[int]) -> int:
        """Resident-prefix length for ``prompt`` right now (no side
        effects) — the batcher's admission-time lookup; 0 on the dense
        tier."""
        if self._kv is None:
            return 0
        return self._kv.probe(list(prompt))

    def prefix_hit_tokens(self, slot: int) -> int:
        """Prefix tokens the last ``start()`` on ``slot`` reused."""
        with self._slot_lock:
            return int(self._prefix_hits[slot])

    # --- request lifecycle --------------------------------------------------

    def start(self, slot: int, prompt: Sequence[int],
              sampling: SamplingParams) -> int:
        """Prefill ``prompt`` into ``slot``; returns the first sampled
        token.  One compiled program per (bucket, slot-batch) shape —
        on the paged tier the bucket covers only the non-resident
        suffix."""
        with self._slot_lock:
            if self._active[slot]:
                raise RuntimeError(f"slot {slot} is already active")
        prompt = [int(t) for t in prompt]
        n = len(prompt)
        self.check_prompt_tokens(prompt)
        if self.kv_mode == "paged":
            hit = self._kv.begin_request(slot, prompt)
            ns = n - hit
            L = self.bucket_for(ns)
            self._kv.ensure_writable(slot, hit, ns)
            padded = np.zeros((1, L), np.int32)
            padded[0, :ns] = np.asarray(prompt[hit:], np.int32)
            fn = self._prefill_fns[L]
            with self._activity(f"serve/slot{slot}", "SERVE_PREFILL",
                                {"bucket": L, "prompt_len": n,
                                 "prefix_hit": hit}):
                token, self._pools = fn(
                    self._params, self._pools,
                    jnp.asarray(self._table[slot]), jnp.asarray(padded),
                    jnp.int32(hit), jnp.int32(ns), self._next_rng(),
                    jnp.float32(sampling.temperature),
                    jnp.int32(sampling.top_k))
                token = int(token)
            self._kv.index_prompt(slot, prompt)
        else:
            hit = 0
            L = self.bucket_for(n)
            padded = np.zeros((1, L), np.int32)
            padded[0, :n] = np.asarray(prompt, np.int32)
            fn = self._prefill_fns[L]
            with self._activity(f"serve/slot{slot}", "SERVE_PREFILL",
                                {"bucket": L, "prompt_len": n}):
                token, self._caches = fn(
                    self._params, self._caches, jnp.asarray(padded),
                    jnp.int32(n), jnp.int32(slot), self._next_rng(),
                    jnp.float32(sampling.temperature),
                    jnp.int32(sampling.top_k))
                token = int(token)
        if self._drafter is not None:
            # The drafter recomputes the full prompt (its dense cache
            # shares nothing) — it is the small model by construction.
            Lf = self.bucket_for(n)
            dp = np.zeros((1, Lf), np.int32)
            dp[0, :n] = np.asarray(prompt, np.int32)
            self._drafter_caches = self._draft_prefill_fns[Lf](
                self._drafter_params, self._drafter_caches,
                jnp.asarray(dp), jnp.int32(slot))
        self._bind_slot(slot, n, token, sampling, hit)
        return token

    def step(self) -> Dict[int, List[int]]:
        """One decode step for every active slot → ``{slot: [tokens]}``
        (one token per slot on the plain path; up to ``spec_k + 1``
        under speculative decoding).  Inactive rows ride along masked
        and write into the trash block."""
        act, pos, temps, topks, last_tokens, spec = self._slot_snapshot()
        active = [int(s) for s in np.nonzero(act)[0]]
        if not active:
            return {}
        if self._drafter is not None and any(
                spec[s] and temps[s] <= 0 for s in active):
            return self._step_spec(
                active, (act, pos, temps, topks, last_tokens, spec))
        positions = np.where(act, pos, 0).astype(np.int32)
        if self.kv_mode == "paged":
            for s in active:
                self._kv.ensure_writable(s, int(positions[s]), 1)
            with self._activity("serve/decode", "SERVE_DECODE",
                                {"batch": len(active)}):
                nxt, self._pools = self._decode_fn(
                    self._params, self._pools, jnp.asarray(self._table),
                    jnp.asarray(last_tokens), jnp.asarray(positions),
                    jnp.asarray(temps), jnp.asarray(topks),
                    self._next_rng())
                nxt = np.asarray(nxt)
        else:
            with self._activity("serve/decode", "SERVE_DECODE",
                                {"batch": len(active)}):
                nxt, self._caches = self._decode_fn(
                    self._params, self._caches,
                    jnp.asarray(last_tokens), jnp.asarray(positions),
                    jnp.asarray(temps), jnp.asarray(topks),
                    self._next_rng())
                nxt = np.asarray(nxt)
        out = {}
        for s in active:
            toks = [int(nxt[s])]
            out[s] = toks
            self._advance_slot(s, toks)
        return out

    def _step_spec(self, active: List[int],
                   snap: tuple) -> Dict[int, List[int]]:
        """Draft-then-verify step: the drafter proposes ``spec_k``
        tokens per slot, the target verifies the whole draft in one
        batched forward, and each slot emits its accepted prefix plus
        the target's next token (1..K+1 tokens, token-identical to
        plain greedy decode).  ``snap`` is step()'s slot snapshot —
        re-snapshotting here could disagree with ``active`` (a
        concurrent cancel between the two reads) and write into a
        just-released slot's chain."""
        K = self.spec_k
        act, pos, temps, topks, last_tokens, spec = snap
        positions = np.where(act, pos, 0).astype(np.int32)
        for s in active:
            p = int(positions[s])
            self._kv.ensure_writable(s, p, min(K + 1, self.max_seq_len - p))
        spec_ok = act & spec & (temps <= 0)
        with self._activity("serve/decode", "SERVE_DECODE",
                            {"batch": len(active), "spec_k": K}):
            draft, self._drafter_caches = self._spec_draft_fn(
                self._drafter_params, self._drafter_caches,
                jnp.asarray(last_tokens), jnp.asarray(positions))
            if self._tp_mesh is not None:
                # The drafter runs single-device (it is the small model
                # by construction); re-home its committed draft onto the
                # TP mesh so the verify program sees one device set.
                draft = jax.device_put(
                    np.asarray(draft),
                    NamedSharding(self._tp_mesh, PartitionSpec()))
            out, accepted, self._pools = self._spec_verify_fn(
                self._params, self._pools, jnp.asarray(self._table),
                jnp.asarray(last_tokens), draft,
                jnp.asarray(positions), jnp.asarray(temps),
                jnp.asarray(topks), jnp.asarray(spec_ok),
                self._next_rng())
            out = np.asarray(out)
            accepted = np.asarray(accepted)
        result: Dict[int, List[int]] = {}
        spec_emitted = spec_steps = 0
        for s in active:
            toks = [int(t) for t in out[s, :int(accepted[s]) + 1]]
            result[s] = toks
            self._advance_slot(s, toks)
            if spec_ok[s]:
                # Only opted-in greedy slots measure drafter quality —
                # plain/temperature batchmates always emit exactly one
                # token and would dilute the ratio toward 1.0.
                spec_steps += 1
                spec_emitted += len(toks)
        self.spec_verify_steps += spec_steps
        self.spec_accepted_tokens += spec_emitted
        from ..obs import instrument as _obs

        _obs.on_spec_accept_ratio(
            self.spec_accepted_tokens / max(1, self.spec_verify_steps))
        return result

    def release(self, slot: int) -> None:
        """Return ``slot`` to the free pool.  Dense tier: cache rows
        are reused (stale keys invisible behind the position mask);
        paged tier: the chain's references drop and unreferenced
        prompt blocks stay resident for future prefix hits until
        evicted."""
        if self._kv is not None:
            self._kv.release(slot)
        self._clear_slot(slot)

    # --- deadline-aware preemption (serve/qos/; docs/qos.md) ----------------
    # Preempt/resume run on the batcher thread only (they drive the
    # same donated pools prefill/decode do); the QoS scheduler owns the
    # decision, this is the KV mechanics.

    def preempt_slot(self, slot: int, prompt: Sequence[int],
                     emitted: Sequence[int]):
        """Evict ``slot``'s generation for later resumption: index the
        full computed sequence (prompt + all emitted tokens whose K/V
        exists) into the prefix cache, release the slot, and return the
        engine's RNG snapshot.  The blocks drop to the LRU but stay
        reachable through the prefix index, so :meth:`resume_slot`
        re-admits with a prefix hit and recomputes only the tail —
        eviction costs a slot swap, not the generation's compute.

        The RNG snapshot is taken BEFORE release so a resume restores
        the exact stream the uninterrupted run would be on — the
        temperature half of the token-identity oracle (same
        sole-active-slot contract as KV migration's rng carry)."""
        rng = np.asarray(self._rng)
        emitted = [int(t) for t in emitted]
        if self._kv is not None and emitted:
            # K/V coverage at preempt time is [0, n + k - 1): the last
            # emitted token is pending consumption, its K/V not yet
            # written — index exactly what is resident.
            seq = [int(t) for t in prompt] + emitted[:-1]
            if seq:
                self._kv.index_prompt(slot, seq)
        self.release(slot)
        return rng

    def can_resume(self, n_prompt: int, n_emitted: int) -> bool:
        """Whether a generation of this shape survives a
        preempt/resume cycle here: the paged tier rebuilds arbitrarily
        long tails in bucket-sized chunks, but a drafter's dense cache
        has no chunked rebuild — its prefill writes one whole bucket —
        so on drafter engines only sequences fitting the largest
        bucket are preemptible (the scheduler skips other victims)."""
        n = n_prompt + max(0, n_emitted - 1)
        if self._drafter is not None:
            return n <= self.prefill_buckets[-1]
        return 0 < n < self.max_seq_len

    def resume_slot(self, slot: int, prompt: Sequence[int],
                    emitted: Sequence[int], sampling: SamplingParams,
                    rng=None) -> int:
        """Re-admit a preempted generation into ``slot``: rebuild K/V
        for ``prompt + emitted[:-1]`` (prefix hit covers whatever
        survived in the cache, a prefill forward recomputes the rest —
        its sampled token is discarded, nothing already emitted is ever
        re-sampled), then bind the slot so the next ``step()`` consumes
        ``emitted[-1]`` at the position the preemption interrupted.
        Returns the prefix-hit token count.

        ``rng`` (the snapshot :meth:`preempt_slot` returned) is
        restored AFTER the recompute forward — the recompute's own
        discarded draw must not perturb the stream — and only while no
        other slot is active, mirroring ``import_slot_kv``'s contract:
        temperature resumption is then bit-identical to the
        uninterrupted run; with concurrent traffic it stays
        distributionally correct (greedy is deterministic either
        way)."""
        with self._slot_lock:
            if self._active[slot]:
                raise RuntimeError(f"slot {slot} is already active")
        prompt = [int(t) for t in prompt]
        emitted = [int(t) for t in emitted]
        if not emitted:
            raise ValueError("resume_slot needs at least one emitted "
                             "token (preemption happens post-prefill)")
        self.check_prompt_tokens(prompt)
        seq = prompt + emitted[:-1]
        n = len(seq)
        if self.kv_mode == "paged":
            hit = self._kv.begin_request(slot, seq)
            # Recompute the non-resident tail in bucket-sized chunks:
            # the paged prefill program takes a start offset, so a
            # resumed sequence longer than the largest bucket (a long
            # generation whose cache was evicted under pressure) still
            # rebuilds — an ordinary prompt never needs this, a resume
            # must not die on it.
            top = self.prefill_buckets[-1]
            pos = hit
            while pos < n:
                ns = min(n - pos, top)
                L = self.bucket_for(ns)
                self._kv.ensure_writable(slot, pos, ns)
                padded = np.zeros((1, L), np.int32)
                padded[0, :ns] = np.asarray(seq[pos:pos + ns], np.int32)
                fn = self._prefill_fns[L]
                with self._activity(f"serve/slot{slot}", "SERVE_PREFILL",
                                    {"bucket": L, "prompt_len": n,
                                     "prefix_hit": hit, "resumed": True}):
                    _, self._pools = fn(
                        self._params, self._pools,
                        jnp.asarray(self._table[slot]),
                        jnp.asarray(padded), jnp.int32(pos),
                        jnp.int32(ns), self._next_rng(),
                        jnp.float32(sampling.temperature),
                        jnp.int32(sampling.top_k))
                pos += ns
            self._kv.index_prompt(slot, seq)
        else:
            hit = 0
            L = self.bucket_for(n)
            padded = np.zeros((1, L), np.int32)
            padded[0, :n] = np.asarray(seq, np.int32)
            fn = self._prefill_fns[L]
            with self._activity(f"serve/slot{slot}", "SERVE_PREFILL",
                                {"bucket": L, "prompt_len": n,
                                 "resumed": True}):
                _, self._caches = fn(
                    self._params, self._caches, jnp.asarray(padded),
                    jnp.int32(n), jnp.int32(slot), self._next_rng(),
                    jnp.float32(sampling.temperature),
                    jnp.int32(sampling.top_k))
        if rng is not None and not self.active_slots():
            self._rng = jnp.asarray(np.asarray(rng, np.uint32))
        if self._drafter is not None:
            # Mirror start(): the drafter recomputes the sequence (its
            # dense cache shares nothing) so speculative decode can
            # draft from the resumed position immediately.
            Lf = self.bucket_for(n)
            dp = np.zeros((1, Lf), np.int32)
            dp[0, :n] = np.asarray(seq, np.int32)
            self._drafter_caches = self._draft_prefill_fns[Lf](
                self._drafter_params, self._drafter_caches,
                jnp.asarray(dp), jnp.int32(slot))
        self._bind_slot(slot, n, emitted[-1], sampling, hit)
        return hit

    # --- zero-downtime weight hot-swap (serve/swap.py; docs/hot_swap.md) ----
    # Staging runs on the subscriber thread; the COMMIT runs on the
    # batcher thread only, at the swap barrier, with no active slots —
    # so the param reference the compiled programs read never changes
    # under an in-flight generation, and a request runs start to finish
    # on exactly one version.

    @property
    def params(self):
        """The live param tree (the swap subscriber seeds its leaf
        cache from it; treat as read-only)."""
        return self._params

    @property
    def weights_version(self) -> int:
        with self._slot_lock:
            return self._weights_version

    def stage_params(self, tree, version: int) -> None:
        """Stage ``tree`` (host arrays) as version ``version`` alongside
        the live params: leaves land on the device now, so the later
        flip is one reference assignment, not a transfer.  Replaces any
        previously staged version (last writer wins — the newest intact
        step is the one worth flipping to)."""
        if self._tp_mesh is not None:
            device = self._tp_place_params(tree)
        else:
            device = jax.tree_util.tree_map(jnp.asarray, tree)
        with self._slot_lock:
            self._staged_params = device
            self._staged_version = int(version)

    def staged_version(self) -> Optional[int]:
        with self._slot_lock:
            return self._staged_version

    def discard_staged(self) -> None:
        """Drop a staged version (digest rejection / abandoned pull /
        dead flip): the live params were never touched."""
        with self._slot_lock:
            self._staged_params = None
            self._staged_version = None

    def commit_staged(self) -> int:
        """THE flip: atomically re-point the engine at the staged
        params and flush the prefix cache (resident KV was computed
        under the old weights — serving it against the new ones would
        be silently wrong).  Batcher thread only, at the swap barrier,
        with no active slots.  Returns the new version."""
        with self._slot_lock:
            if self._staged_params is None:
                raise RuntimeError("no staged params to commit")
            if np.count_nonzero(self._active):
                raise RuntimeError(
                    "commit_staged with active slots — the barrier "
                    "must drain in-flight generations first")
            params = self._staged_params
            version = int(self._staged_version)
            self._staged_params = None
            self._staged_version = None
            self._weights_version = version
        self._params = params
        if self._kv is not None:
            self._kv.flush_cache()
        from ..obs import instrument as _obs

        _obs.on_weights_version(version)
        return version

    # --- live KV migration (serve/fleet/; docs/serving.md) ------------------
    # Export/import run on the batcher thread only (they read/reassign
    # the device pools the compiled programs donate), exactly like
    # start()/step() — the fleet layer routes both through the batcher.

    def export_slot_kv(self, slot: int):
        """Export ``slot``'s resident KV as ``(chain_len, k, v)`` numpy
        arrays of shape ``[n_layer, n_blocks, block, H, D]`` — the
        slot's block table is the transfer manifest: only its live,
        non-trash chain blocks move.  Called at the prefill→decode
        boundary, when the chain covers exactly the prompt's positions
        ``[0, n_prompt)``."""
        if self.kv_mode != "paged":
            raise RuntimeError("KV export requires the paged cache "
                               "(HVD_TPU_SERVE_KV=paged)")
        chain = self._kv.chain_blocks(slot)
        if not chain:
            raise RuntimeError(f"slot {slot} has no KV chain to export")
        idx = jnp.asarray(chain, jnp.int32)
        k = np.stack([np.asarray(p["k"][idx]) for p in self._pools])
        v = np.stack([np.asarray(p["v"][idx]) for p in self._pools])
        return len(chain), k, v

    def import_slot_kv(self, slot: int, prompt: Sequence[int],
                       k_blocks, v_blocks, first_token: int,
                       sampling: SamplingParams,
                       rng=None) -> None:
        """Bind wire-received KV blocks into this engine's pool and
        activate ``slot`` exactly as if prefill had run here: the next
        ``step()`` consumes ``first_token`` at position ``n_prompt``
        and generation continues token-identically.  ``rng`` (the
        sender's post-prefill PRNG key) is adopted only while no other
        slot is active — temperature sampling is then bit-identical to
        the single-replica run; with concurrent traffic it stays
        distributionally correct (greedy/speculative requests are
        deterministic either way).  Digest verification happens in the
        migration layer BEFORE this call — corrupt payloads never reach
        the pool."""
        if self.kv_mode != "paged":
            raise RuntimeError("KV import requires the paged cache "
                               "(HVD_TPU_SERVE_KV=paged)")
        with self._slot_lock:
            if self._active[slot]:
                raise RuntimeError(f"slot {slot} is already active")
        prompt = [int(t) for t in prompt]
        n = len(prompt)
        self.check_prompt_tokens(prompt)
        nb = int(k_blocks.shape[1])
        expected = -(-n // self.kv_block)
        if nb != expected:
            raise ValueError(
                f"imported chain of {nb} block(s) does not cover the "
                f"{n}-token prompt ({expected} expected at block size "
                f"{self.kv_block})")
        chain = self._kv.bind_imported(slot, nb)
        for j, blk in enumerate(chain):
            self._pools = self._import_fn(
                self._pools, jnp.int32(blk),
                jnp.asarray(k_blocks[:, j]), jnp.asarray(v_blocks[:, j]))
        # The imported prefix is resident here now: index it so later
        # admissions (and the global prefix directory) hit it — the
        # "prefix-directory hit landing on a decode replica" path.
        self._kv.index_prompt(slot, prompt)
        if rng is not None and not self.active_slots():
            self._rng = jnp.asarray(np.asarray(rng, np.uint32))
        if self._drafter is not None:
            # Mirror start(): the drafter recomputes the prompt (its
            # dense cache shares nothing) so speculative decode can
            # draft from position n_prompt immediately.
            Lf = self.bucket_for(n)
            dp = np.zeros((1, Lf), np.int32)
            dp[0, :n] = np.asarray(prompt, np.int32)
            self._drafter_caches = self._draft_prefill_fns[Lf](
                self._drafter_params, self._drafter_caches,
                jnp.asarray(dp), jnp.int32(slot))
        self._bind_slot(slot, n, int(first_token), sampling, 0)

    def export_rng(self):
        """This engine's current PRNG key as numpy (migrated with the
        KV so an idle importer can reproduce the sender's sampling
        stream bit-exactly)."""
        return np.asarray(self._rng)

    def drain_evicted_prefixes(self) -> List[tuple]:
        """Leading-block keys evicted since the last drain (piggybacked
        on response frames → global prefix directory invalidation);
        empty on the dense tier."""
        if self._kv is None:
            return []
        return self._kv.drain_evicted_keys()

    # --- observability ------------------------------------------------------

    def kv_stats(self) -> Dict:
        """JSON-ready paged-KV + speculative counters (merged into the
        batcher's snapshot and the serving bench artifact)."""
        out: Dict = {}
        if self._kv is not None:
            out.update(self._kv.stats())
        if self._drafter is not None:
            steps = self.spec_verify_steps
            out["spec_verify_steps"] = steps
            out["spec_accepted_tokens"] = self.spec_accepted_tokens
            out["spec_accept_per_verify"] = (
                round(self.spec_accepted_tokens / steps, 4) if steps
                else None)
        return out
