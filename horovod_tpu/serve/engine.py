"""Continuous-batching inference engine over ``models.transformer.GPT``.

The serving hot path is two compiled programs:

* **prefill** — one program per *length bucket* ``L``: run the prompt
  (padded to ``L``) through the model with a fresh ``[1, L]`` KV cache,
  sample the first token, and write the cache into this request's slot
  of the engine-wide preallocated cache.  Padding prompts to a small
  set of bucket shapes bounds recompiles: serving traffic has arbitrary
  prompt lengths, and an unbucketed engine would compile per length.
* **decode** — ONE program for the whole slot batch: every active
  request advances one token per call, each slot at its own depth
  (``positions`` is per-row, so a request in its 3rd token and one in
  its 300th share the dispatch).  This is the continuous-batching
  property: admission never waits for the batch to drain.

Neither program contains a cross-replica collective — the per-token hot
path is replica-local by construction (the fused computation-collective
literature's guidance: keep collectives off the token critical path);
replication happens one level up, in ``serve/router.py`` over process
sets.

Sampling is greedy / temperature / top-k, resolved **per slot** inside
the one decode program (a ``where`` lattice, not a recompile), so mixed
sampling configs batch together.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.transformer import GPT, init_kv_cache
from ..utils.logging import get_logger

logger = get_logger(__name__)


def resolved_config():
    """The serving layer's config source: the live Config when this
    process ran ``hvd.init``, else a fresh env parse (same parser, same
    defaults — the network.py convention, so a bare engine in a script
    and a served engine under the launcher read identical knobs)."""
    from .. import basics
    from ..config import Config

    return basics.config() if basics.is_initialized() else Config.from_env()


class PromptTooLongError(ValueError):
    """Prompt exceeds the largest prefill bucket / cache length."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (greedy when ``temperature == 0``)."""

    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0                 # 0 = full vocab
    stop_token: Optional[int] = None


def _sample(logits, rng, temps, topks):
    """Per-row sampling over ``[B, V]`` float32 logits: greedy rows
    (``temp <= 0``) take argmax; the rest draw from temperature-scaled
    logits restricted to each row's top-k (k per row — ranks against a
    per-row threshold instead of a static ``lax.top_k`` width)."""
    greedy = jnp.argmax(logits, axis=-1)
    ranks = jnp.argsort(jnp.argsort(-logits, axis=-1), axis=-1)
    k = jnp.where(topks > 0, topks, logits.shape[-1])[:, None]
    masked = jnp.where(ranks < k, logits, -jnp.inf)
    scaled = masked / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


class InferenceEngine:
    """Slot-based prefill/decode engine; the batcher owns scheduling.

    ``start(slot, prompt, sampling)`` prefixes a request into ``slot``
    and returns its first token; ``step()`` decodes one token for every
    active slot.  Per-phase wall time lands on the framework Timeline
    (phases ``SERVE_PREFILL`` / ``SERVE_DECODE``) when one is active.
    """

    def __init__(self, model: GPT, params, *,
                 max_slots: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 max_seq_len: Optional[int] = None,
                 seed: int = 0):
        cfg = resolved_config()
        self._model = model
        self._params = params
        self.max_slots = int(max_slots or cfg.serve_max_batch)
        self.max_seq_len = int(max_seq_len or model.config.max_seq_len)
        if self.max_seq_len > model.config.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"positional table ({model.config.max_seq_len})")
        buckets = tuple(prefill_buckets or cfg.serve_prefill_buckets)
        # Clamp buckets to the cache length; keep at least one.
        self.prefill_buckets = tuple(sorted(
            {min(int(b), self.max_seq_len) for b in buckets if b > 0}))
        if not self.prefill_buckets:
            raise ValueError(f"no usable prefill buckets in {buckets}")
        self._caches = init_kv_cache(model.config, self.max_slots,
                                     self.max_seq_len)
        self._positions = np.zeros(self.max_slots, np.int32)
        self._active = np.zeros(self.max_slots, bool)
        self._temps = np.zeros(self.max_slots, np.float32)
        self._topks = np.zeros(self.max_slots, np.int32)
        self._last_tokens = np.zeros(self.max_slots, np.int32)
        self._rng = jax.random.PRNGKey(seed)
        # Trace-time counters: the bounded-recompile contract is
        # testable (each jitted program bumps its key once per trace).
        self.trace_counts = collections.Counter()
        # Donate the engine-wide cache so prefill/decode update it in
        # place — without donation XLA copies the full [slots, S, H, D]
        # x 2 x n_layer cache every token, which dominates decode at
        # real cache sizes.  CPU has no donation support (it would only
        # warn), so gate on the backend.
        self._donate = (1,) if jax.default_backend() != "cpu" else ()
        self._prefill_fns = {L: self._make_prefill(L)
                             for L in self.prefill_buckets}
        self._decode_fn = jax.jit(self._decode_impl,
                                  donate_argnums=self._donate)

    # --- compiled programs --------------------------------------------------

    def _make_prefill(self, L: int):
        model, n_layer = self._model, self._model.config.n_layer

        def prefill(params, caches, tokens, length, slot, rng, temp, topk):
            self.trace_counts[f"prefill_{L}"] += 1  # trace-time only
            positions = jnp.arange(L, dtype=jnp.int32)[None]
            row = init_kv_cache(model.config, 1, L)
            logits, row = model.apply({"params": params}, tokens,
                                      kv_caches=row, positions=positions)
            last = jax.lax.dynamic_index_in_dim(logits[0], length - 1,
                                                axis=0, keepdims=False)
            token = _sample(last[None].astype(jnp.float32), rng,
                            temp[None], topk[None])[0]

            def write(big, chunk):
                return jax.lax.dynamic_update_slice(
                    big, chunk.astype(big.dtype), (slot, 0, 0, 0))

            new = [{"k": write(caches[i]["k"], row[i]["k"]),
                    "v": write(caches[i]["v"], row[i]["v"])}
                   for i in range(n_layer)]
            return token, new

        return jax.jit(prefill, donate_argnums=self._donate)

    def _decode_impl(self, params, caches, tokens, positions, temps,
                     topks, rng):
        self.trace_counts["decode"] += 1  # trace-time only
        logits, new = self._model.apply(
            {"params": params}, tokens[:, None], kv_caches=caches,
            positions=positions[:, None])
        nxt = _sample(logits[:, -1].astype(jnp.float32), rng, temps, topks)
        return nxt, new

    # --- host-side slot API -------------------------------------------------

    def _activity(self, name: str, phase: str, args=None):
        """Timeline span for one serving phase (no-op without an active
        framework timeline)."""
        import contextlib

        from .. import basics

        tl = basics._state.timeline if basics.is_initialized() else None
        if tl is None or not tl.enabled:
            return contextlib.nullcontext()
        return tl.activity(name, phase, args)

    def _next_rng(self):
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def bucket_for(self, prompt_len: int) -> int:
        for b in self.prefill_buckets:
            if prompt_len <= b:
                return b
        raise PromptTooLongError(
            f"prompt of {prompt_len} tokens exceeds the largest prefill "
            f"bucket ({self.prefill_buckets[-1]})")

    def check_prompt(self, prompt_len: int) -> int:
        """Full admission-time validation (the batcher calls this so an
        unservable prompt fails before it costs a queue entry): bucket
        fit AND room to generate.  Returns the bucket."""
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if prompt_len >= self.max_seq_len:
            raise PromptTooLongError(
                f"prompt of {prompt_len} tokens leaves no room to "
                f"generate (cache length {self.max_seq_len})")
        return self.bucket_for(prompt_len)

    def free_slots(self) -> List[int]:
        return [int(s) for s in np.nonzero(~self._active)[0]]

    def active_slots(self) -> List[int]:
        return [int(s) for s in np.nonzero(self._active)[0]]

    def slot_full(self, slot: int) -> bool:
        """True when the next decode would write past the cache (the
        next decode writes K/V at index ``_positions[slot]``, valid
        while it is ``< max_seq_len``)."""
        return int(self._positions[slot]) >= self.max_seq_len

    def start(self, slot: int, prompt: Sequence[int],
              sampling: SamplingParams) -> int:
        """Prefill ``prompt`` into ``slot``; returns the first sampled
        token.  One compiled program per (bucket, slot-batch) shape."""
        if self._active[slot]:
            raise RuntimeError(f"slot {slot} is already active")
        n = len(prompt)
        L = self.check_prompt(n)
        padded = np.zeros((1, L), np.int32)
        padded[0, :n] = np.asarray(prompt, np.int32)
        fn = self._prefill_fns[L]
        with self._activity(f"serve/slot{slot}", "SERVE_PREFILL",
                            {"bucket": L, "prompt_len": n}):
            token, self._caches = fn(
                self._params, self._caches, jnp.asarray(padded),
                jnp.int32(n), jnp.int32(slot), self._next_rng(),
                jnp.float32(sampling.temperature),
                jnp.int32(sampling.top_k))
            token = int(token)
        self._active[slot] = True
        self._positions[slot] = n     # the first generated token's index
        self._temps[slot] = sampling.temperature
        self._topks[slot] = sampling.top_k
        self._last_tokens[slot] = token   # first decode consumes it
        return token

    def step(self) -> Dict[int, int]:
        """One decode step for every active slot → ``{slot: token}``.
        Inactive rows ride along masked (position 0) and are ignored."""
        active = self.active_slots()
        if not active:
            return {}
        positions = np.where(self._active, self._positions, 0).astype(np.int32)
        with self._activity("serve/decode", "SERVE_DECODE",
                            {"batch": len(active)}):
            nxt, self._caches = self._decode_fn(
                self._params, self._caches, jnp.asarray(self._last_tokens),
                jnp.asarray(positions), jnp.asarray(self._temps),
                jnp.asarray(self._topks), self._next_rng())
            nxt = np.asarray(nxt)
        out = {}
        for s in active:
            out[s] = int(nxt[s])
            self._last_tokens[s] = nxt[s]
            self._positions[s] += 1
        return out

    def release(self, slot: int) -> None:
        """Return ``slot`` to the free pool (cache rows are reused —
        stale keys are invisible behind the position mask)."""
        self._active[slot] = False
        self._positions[slot] = 0
        self._temps[slot] = 0.0
        self._topks[slot] = 0
