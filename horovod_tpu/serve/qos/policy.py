"""QoS classes, per-tenant token-bucket budgets, typed rejections.

The policy layer of the SLO-aware multi-tenant scheduler
(docs/qos.md): three service classes —

* ``interactive`` — deadline-protected traffic.  Never shed by the
  brownout ladder; may preempt batch generations to make its deadline.
* ``standard`` — the default class.  Shed only at the deepest brownout
  level, after batch.
* ``batch`` — throughput traffic.  First to be preempted and first to
  be shed; its requests are the ones that absorb overload.

Each ``(tenant, class)`` pair is one *flow* of the weighted-fair
scheduler (``sched.py``); a flow's weight is ``class weight × tenant
share`` (``HVD_TPU_QOS_CLASS_WEIGHTS`` / ``HVD_TPU_QOS_TENANT_SHARES``).

**Token-bucket budgets** bound each tenant's token throughput (prompt
plus generated tokens, ``HVD_TPU_QOS_TENANT_BUDGETS`` tokens/second
with ``rate × HVD_TPU_QOS_BURST_S`` of burst capacity).  A request is
charged ``len(prompt) + max_new_tokens`` at admission — the
*reservation*, since the generation cap is what it may consume — and
the unused remainder is refunded at completion.  An exhausted bucket
raises :class:`BudgetExhaustedError`, a **typed retriable rejection**
carrying ``retry_after_s`` (when the bucket will cover the request)
so a well-behaved client backs off instead of hammering; the
alternative — queueing the over-budget request — would let one tenant
convert its excess into everyone's latency.

Shedding (:class:`RequestShedError`) is the brownout ladder's typed
rejection (``brownout.py``); it lives here so the wire layer imports
one error taxonomy.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ...config import QOS_CLASSES, parse_qos_map

DEFAULT_CLASS = "standard"
# Built-in WFQ weights, overridden per class by the config grammar.
_DEFAULT_WEIGHTS = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}


class QosError(RuntimeError):
    """Base of the QoS rejection taxonomy (typed, retriable)."""

    retry_after_s: float = 0.0


class BudgetExhaustedError(QosError):
    """The tenant's token bucket cannot cover this request.  Retriable
    by the CLIENT after ``retry_after_s`` — never by the router on
    another replica (the budget is policy, not replica health)."""

    def __init__(self, tenant: str, need: float, retry_after_s: float):
        super().__init__(
            f"tenant {tenant!r} over its token budget ({need:.0f} tokens "
            f"needed); retry after {retry_after_s:.2f}s")
        self.tenant = tenant
        self.retry_after_s = retry_after_s


class RequestShedError(QosError):
    """Brownout shed: the fleet is overloaded and this request's class
    is being dropped to protect higher classes (batch first, then
    standard, never interactive).  Retriable after ``retry_after_s`` —
    a typed answer, not a timeout, so the client learns *why* and
    *when*, and the shed costs the fleet nothing."""

    def __init__(self, qos_class: str, level: int, retry_after_s: float):
        super().__init__(
            f"brownout level {level}: shedding {qos_class!r} traffic; "
            f"retry after {retry_after_s:.2f}s")
        self.qos_class = qos_class
        self.level = level
        self.retry_after_s = retry_after_s


def validate_class(qos_class: Optional[str]) -> str:
    cls = (qos_class or DEFAULT_CLASS).lower()
    if cls not in QOS_CLASSES:
        raise ValueError(f"unknown QoS class {cls!r}; expected one of "
                         f"{QOS_CLASSES}")
    return cls


class TokenBucket:
    """One tenant's refilling token budget; caller holds the policy
    lock (single-owner helper, the ``_locked`` contract)."""

    def __init__(self, rate_per_s: float, burst_s: float) -> None:
        self.rate = float(rate_per_s)
        self.capacity = max(1.0, self.rate * float(burst_s))
        self.tokens = self.capacity
        self._last = time.monotonic()

    def _refill_locked(self, now: float) -> None:
        self.tokens = min(self.capacity,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now

    def take_locked(self, n: float, now: float) -> Optional[float]:
        """Charge ``n`` tokens; returns None on success, else the
        seconds until the bucket would cover ``n``."""
        self._refill_locked(now)
        if self.tokens >= n:
            self.tokens -= n
            return None
        deficit = min(n, self.capacity) - self.tokens
        return deficit / self.rate if self.rate > 0 else float("inf")

    def refund_locked(self, n: float) -> None:
        self.tokens = min(self.capacity, self.tokens + max(0.0, n))


class QosPolicy:
    """Resolved QoS policy for one admission tier (a batcher, or the
    router's gate): flow weights + per-tenant budgets.  Thread-safe —
    charges arrive from every RPC handler thread at once."""

    def __init__(self, *,
                 class_weights: Optional[Dict[str, float]] = None,
                 tenant_shares: Optional[Dict[str, float]] = None,
                 tenant_budgets: Optional[Dict[str, float]] = None,
                 default_budget: float = 0.0,
                 burst_s: float = 2.0) -> None:
        weights = dict(_DEFAULT_WEIGHTS)
        weights.update(class_weights or {})
        self.class_weights = weights
        self.tenant_shares = dict(tenant_shares or {})
        self.burst_s = float(burst_s)
        self.default_budget = float(default_budget)
        self._budget_rates = dict(tenant_budgets or {})
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}  # guarded-by: _lock

    @classmethod
    def from_config(cls, cfg) -> "QosPolicy":
        """Build from the ``HVD_TPU_QOS_*`` knobs (grammar already
        validated at init by config.py)."""
        return cls(
            class_weights=parse_qos_map(cfg.qos_class_weights,
                                        "qos class weights", QOS_CLASSES),
            tenant_shares=(parse_qos_map(cfg.qos_tenant_shares,
                                         "qos tenant shares",
                                         positive=True)
                           if cfg.qos_tenant_shares else None),
            tenant_budgets=(parse_qos_map(cfg.qos_tenant_budgets,
                                          "qos tenant budgets")
                            if cfg.qos_tenant_budgets else None),
            default_budget=cfg.qos_default_budget,
            burst_s=cfg.qos_burst_s)

    def weight(self, tenant: str, qos_class: str) -> float:
        """One flow's WFQ weight: class weight × tenant share."""
        return (self.class_weights.get(qos_class,
                                       _DEFAULT_WEIGHTS[DEFAULT_CLASS])
                * self.tenant_shares.get(tenant, 1.0))

    def _bucket_locked(self, tenant: str) -> Optional[TokenBucket]:
        bucket = self._buckets.get(tenant)
        if bucket is None:
            rate = self._budget_rates.get(tenant, self.default_budget)
            if rate <= 0:
                return None   # unlimited tenant: no bucket at all
            bucket = TokenBucket(rate, self.burst_s)
            self._buckets[tenant] = bucket  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        return bucket

    def charge(self, tenant: str, n_tokens: float) -> float:
        """Charge ``n_tokens`` against ``tenant``'s budget; returns the
        amount charged (0 for unlimited tenants) or raises
        :class:`BudgetExhaustedError` with the retry hint."""
        with self._lock:
            bucket = self._bucket_locked(tenant)
            if bucket is None:
                return 0.0
            retry = bucket.take_locked(float(n_tokens), time.monotonic())
        if retry is not None:
            raise BudgetExhaustedError(tenant, n_tokens, retry)
        return float(n_tokens)

    def refund(self, tenant: str, n_tokens: float) -> None:
        """Return unused reservation (completed request emitted fewer
        tokens than its cap)."""
        if n_tokens <= 0:
            return
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is not None:
                bucket.refund_locked(float(n_tokens))

    def limited_tenants(self) -> Dict[str, float]:
        """Configured rate per budget-limited tenant (stats surface)."""
        out = dict(self._budget_rates)
        return {t: r for t, r in out.items() if r > 0}
