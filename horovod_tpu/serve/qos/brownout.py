"""Graceful brownout: class-ordered shedding with hysteresis.

The router-tier overload valve (docs/qos.md).  Driven by the SAME
signals the fleet controller reads — mean queue depth and interactive
p99 TTFT from the replicas' stats snapshots — a
:class:`BrownoutController` walks a shed ladder:

====== ==========================================
level  shedding
====== ==========================================
0      nothing (normal service)
1      ``batch`` requests answered with a typed
       retriable rejection
2      ``batch`` + ``standard`` shed
====== ==========================================

``interactive`` is **never** shed: the ladder tops out one class short
by construction, so overload degrades throughput traffic first and
latency-SLO traffic last — the opposite of what an unprioritized queue
does (interactive drowns in batch arrivals and times out).

**Hysteresis** (the no-oscillation property the tests pin): the ladder
steps UP the moment the overload signal crosses
``HVD_TPU_QOS_BROWNOUT_HIGH`` (shedding late costs SLOs), but steps
DOWN one level at a time, each step only after the signal has stayed
below ``HVD_TPU_QOS_BROWNOUT_LOW`` for ``HVD_TPU_QOS_BROWNOUT_HOLD_S``
straight — the band between LOW and HIGH holds the current level, so a
load level that hovers at the threshold cannot flap shed/un-shed every
control round (which would turn the batch tier into a strobe light).

A shed answers with :class:`~horovod_tpu.serve.qos.policy
.RequestShedError` — typed and retriable (``retry_after_s`` = the hold
window) rather than a timeout: the client learns why and when, and the
shed request costs the fleet zero slots.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ...obs import instrument as _obs
from ...utils.logging import get_logger
from .policy import BudgetExhaustedError, QosPolicy, RequestShedError

logger = get_logger(__name__)

# Shed order: batch first, then standard.  Interactive is absent by
# construction — the ladder cannot reach it.
SHED_ORDER = ("batch", "standard")
MAX_LEVEL = len(SHED_ORDER)


class BrownoutController:
    """The shed ladder for one router (thread-safe: observed by the
    control loop, consulted by every request thread)."""

    def __init__(self, *, queue_capacity: int,
                 high: float = 0.75, low: float = 0.25,
                 hold_s: float = 5.0, slo_ttft_ms: float = 0.0,
                 clock=None) -> None:
        if not 0.0 <= low < high:
            raise ValueError(
                f"brownout thresholds need 0 <= low < high, got "
                f"low={low} high={high}")
        self.queue_capacity = max(1, int(queue_capacity))
        self.high = float(high)
        self.low = float(low)
        self.hold_s = float(hold_s)
        self.slo_ttft_ms = float(slo_ttft_ms)
        # Injectable monotonic clock for the hold/hysteresis timers —
        # the fleet simulator (serve/fleet/sim.py) runs the ladder
        # under virtual time; default is the real clock.
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._level = 0                    # guarded-by: _lock
        self._calm_since: Optional[float] = None  # guarded-by: _lock

    @classmethod
    def from_config(cls, cfg) -> "BrownoutController":
        """Build from the ``HVD_TPU_QOS_BROWNOUT_*`` /
        ``HVD_TPU_QOS_SLO_TTFT_MS`` knobs; the queue capacity the
        thresholds are fractions of is the serving admission bound
        (``HVD_TPU_SERVE_QUEUE_DEPTH``)."""
        return cls(queue_capacity=cfg.serve_queue_depth,
                   high=cfg.qos_brownout_high,
                   low=cfg.qos_brownout_low,
                   hold_s=cfg.qos_brownout_hold_s,
                   slo_ttft_ms=cfg.qos_slo_ttft_ms)

    @property
    def level(self) -> int:
        with self._lock:
            return self._level

    def observe(self, queue_depth_mean: float,
                interactive_ttft_p99_ms: Optional[float] = None,
                now: Optional[float] = None) -> int:
        """Feed one control-round's signals; returns the (possibly
        stepped) level.  ``now`` is injectable for deterministic
        hysteresis tests."""
        now = self._clock() if now is None else now
        frac = queue_depth_mean / self.queue_capacity
        slo_breached = (self.slo_ttft_ms > 0
                        and interactive_ttft_p99_ms is not None
                        and interactive_ttft_p99_ms > self.slo_ttft_ms)
        overload = frac > self.high or slo_breached
        calm = frac < self.low and not slo_breached
        with self._lock:
            old = self._level
            if overload:
                self._level = min(self._level + 1, MAX_LEVEL)
                self._calm_since = None
            elif calm and self._level > 0:
                if self._calm_since is None:
                    self._calm_since = now
                elif now - self._calm_since >= self.hold_s:
                    self._level -= 1
                    # Each un-brown step earns its own full hold: a
                    # straight drop 2 -> 0 would re-admit the whole
                    # backlog at once and re-trigger the overload.
                    self._calm_since = now
            else:
                # The hysteresis band (or still loaded): hold level AND
                # restart the calm clock — un-browning needs hold_s of
                # uninterrupted calm, not hold_s total.
                self._calm_since = None
            level = self._level
        if level != old:
            logger.warning("brownout level %d -> %d (queue %.2f of "
                           "capacity%s)", old, level, frac,
                           ", interactive SLO breached" if slo_breached
                           else "")
        _obs.on_qos_brownout_level(level)
        return level

    def check(self, qos_class: str) -> None:
        """Raise :class:`RequestShedError` when ``qos_class`` is shed
        at the current level."""
        with self._lock:
            level = self._level
        if level <= 0 or qos_class not in SHED_ORDER:
            return
        if SHED_ORDER.index(qos_class) < level:
            _obs.on_qos_shed(qos_class)
            raise RequestShedError(qos_class, level,
                                   retry_after_s=self.hold_s)


class QosGate:
    """Router-level admission: per-tenant rate limits + brownout.

    Attached via ``Router.attach_qos``; ``admit`` runs before any
    replica is touched, so a shed or over-budget request costs the
    fleet nothing.  ``policy`` is optional — a gate may be
    brownout-only (budgets enforced at the batcher tier instead;
    enabling both tiers with the same budget map double-charges, see
    docs/qos.md's recipes)."""

    def __init__(self, *, brownout: Optional[BrownoutController] = None,
                 policy: Optional[QosPolicy] = None) -> None:
        self.brownout = brownout
        self.policy = policy

    @classmethod
    def from_config(cls, cfg, *,
                    policy: Optional[QosPolicy] = None) -> "QosGate":
        """The standard router-tier wiring: a brownout ladder from the
        ``HVD_TPU_QOS_*`` knobs, budgets only when explicitly handed a
        policy (batcher-tier budgets are the default — see
        docs/qos.md)."""
        return cls(brownout=BrownoutController.from_config(cfg),
                   policy=policy)

    def admit(self, tenant: str, qos_class: str,
              n_tokens: float = 0.0) -> float:
        """Shed check then budget charge; returns the tokens charged
        (refund the unused part via :meth:`refund` after completion).
        Raises :class:`RequestShedError` / :class:`BudgetExhaustedError`
        — both typed and retriable by the CLIENT."""
        from ... import faults as faults_mod

        if self.brownout is not None:
            self.brownout.check(qos_class)
        if self.policy is None or n_tokens <= 0:
            return 0.0
        if faults_mod._active is not None and faults_mod.on_qos_admit():
            return 0.0   # injected flood: this tenant's budget is waived
        try:
            return self.policy.charge(tenant, n_tokens)
        except BudgetExhaustedError:
            _obs.on_qos_budget_reject(tenant)
            raise

    def refund(self, tenant: str, n_tokens: float) -> None:
        if self.policy is not None:
            self.policy.refund(tenant, n_tokens)

    def observe(self, queue_depth_mean: float,
                interactive_ttft_p99_ms: Optional[float] = None,
                now: Optional[float] = None) -> int:
        """Forward one control round's signals to the ladder (no-op
        gate without a brownout controller)."""
        if self.brownout is None:
            return 0
        return self.brownout.observe(queue_depth_mean,
                                     interactive_ttft_p99_ms, now=now)

    def snapshot(self) -> Dict:
        out: Dict = {"brownout_level": (self.brownout.level
                                        if self.brownout else 0)}
        if self.policy is not None:
            out["limited_tenants"] = self.policy.limited_tenants()
        return out
