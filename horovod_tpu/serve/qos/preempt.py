"""Deadline-aware slot preemption: evict-and-requeue batch work.

The decision layer (pure functions — the batcher owns execution, the
engine owns the KV mechanics; docs/qos.md has the state machine):

An **interactive** request queued behind a full slot batch would miss
its deadline whenever the earliest natural slot release lands after
it.  When that happens (and ``HVD_TPU_QOS_PREEMPT`` is on), the
scheduler evicts the *youngest batch-class generation* — the one with
the fewest emitted tokens, i.e. the least recompute at stake — and
requeues it:

1. the victim's KV chain is indexed into the prefix cache and its
   slot released (``InferenceEngine.preempt_slot``): the blocks drop
   to the LRU but stay reachable through ``serve/kv/prefix.py``, so
   nothing is recomputed while memory pressure allows;
2. the victim re-enters the weighted-fair queue carrying its emitted
   tokens and the engine's RNG snapshot (``ServeRequest
   .resume_state``) — requeue bypasses the admission bound and the
   budget charge (its tokens are already paid for; dropping preempted
   work would convert a scheduling decision into data loss);
3. on re-admission ``InferenceEngine.resume_slot`` re-binds with a
   prefix hit and recomputes only the non-resident tail, then
   continues decoding — the **token-identity oracle**: the preempted
   +resumed output equals the uninterrupted run's exactly (greedy
   always; temperature whenever the RNG snapshot is restorable, the
   same sole-active-slot contract KV migration uses).

The wait estimate is deliberately simple — decode cadence (TPOT) times
the smallest remaining generation budget across active slots, i.e. the
soonest *guaranteed* natural release.  Stop tokens can only free slots
earlier, which makes the estimate conservative in the safe direction:
it may preempt when waiting would have just barely worked, it never
waits when the numbers say the deadline dies.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

# Decode cadence fallback before the stats window has samples (one
# decode's host+device cost on the CPU tier is a few ms; 50ms is
# pessimistic on purpose — early requests err toward protection).
FALLBACK_TPOT_S = 0.05

# SLO-trigger headroom: with a TTFT SLO configured, preempt when a
# natural slot release is not expected to land the first token inside
# HALF the SLO budget.  The wait estimate's error bars are wide (stop
# tokens, cadence drift), and a TTFT SLO missed by estimation error is
# exactly the failure this subsystem exists to prevent — so the
# trigger spends batch efficiency to buy SLO certainty, by design.
SLO_HEADROOM = 0.5


def estimate_slot_wait_s(active: Dict[int, object],
                         tpot_s: Optional[float]) -> float:
    """Seconds until the soonest *certain* natural slot release: the
    smallest remaining token budget across active slots, at the
    observed decode cadence."""
    if not active:
        return 0.0
    tpot = tpot_s if tpot_s and tpot_s > 0 else FALLBACK_TPOT_S
    remaining = min(
        max(1, r.sampling.max_new_tokens - len(r.tokens))
        for r in active.values())
    return remaining * tpot


def would_miss(deadline: Optional[float], now: float,
               est_wait_s: float) -> bool:
    """True when waiting ``est_wait_s`` for a natural release would
    blow ``deadline``."""
    return deadline is not None and now + est_wait_s > deadline


def should_preempt(req, now: float, est_wait_s: float,
                   slo_ttft_s: float = 0.0) -> bool:
    """The full trigger: waiting ``est_wait_s`` would miss the
    request's deadline, OR (with a TTFT SLO configured,
    ``HVD_TPU_QOS_SLO_TTFT_MS``) would land the first token past
    ``submitted_at + SLO_HEADROOM × slo`` — the aggressive-protection
    mode the acceptance bound (interactive p99 within 1.5× unloaded
    under a 4× batch flood) requires: with a tight SLO the trigger is
    effectively preempt-on-arrival, with a loose one it degenerates to
    pure deadline feasibility and batch runs undisturbed."""
    if would_miss(req.deadline, now, est_wait_s):
        return True
    if slo_ttft_s > 0:
        target = (getattr(req, "submitted_at", now)
                  + SLO_HEADROOM * slo_ttft_s)
        return now + est_wait_s > target
    return False


def pick_victim(active: Dict[int, object]) -> Optional[Tuple[int, object]]:
    """The youngest batch-class generation ``(slot, request)`` — fewest
    emitted tokens, most recently submitted on ties (least work lost,
    and the most recently admitted request is the fairest to send back
    to the queue it just left).  None when no batch work is running —
    interactive/standard generations are never preempted."""
    victims = [(slot, req) for slot, req in active.items()
               if getattr(req, "qos_class", None) == "batch"
               and not req.done.is_set()]
    if not victims:
        return None
    return min(victims,
               key=lambda sr: (len(sr[1].tokens), -sr[1].submitted_at))
