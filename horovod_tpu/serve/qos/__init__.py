"""SLO-aware multi-tenant QoS scheduling (docs/qos.md).

The capacity-policy tier over the serving engine — same engine, an
order of magnitude more workload shapes:

* :mod:`~horovod_tpu.serve.qos.policy` — service classes
  (``interactive`` / ``standard`` / ``batch``), per-tenant token-bucket
  budgets (prompt + generated tokens), and the typed rejection taxonomy
  (:class:`BudgetExhaustedError` / :class:`RequestShedError` — both
  retriable, both carrying ``retry_after_s``)
* :mod:`~horovod_tpu.serve.qos.sched` — :class:`QosQueue`, the
  stride/virtual-time weighted-fair admission queue replacing the
  batcher's FIFO, with a deadline min-heap so expiry no longer scales
  with queue depth
* :mod:`~horovod_tpu.serve.qos.preempt` — deadline-aware preemption
  decisions: an interactive request about to miss its deadline evicts
  the youngest batch generation to the paged-KV prefix cache and
  requeues it (resumption replays only the non-resident tail,
  token-identical to the uninterrupted run)
* :mod:`~horovod_tpu.serve.qos.brownout` —
  :class:`BrownoutController` / :class:`QosGate`: router-level
  per-tenant rate limits and the hysteresis shed ladder (batch first,
  then standard, never interactive)

Chaos: the ``qos`` fault site (``invert`` at the WFQ pop, ``flood`` at
the budget charge) drills priority inversion and budget floods —
``scripts/chaos_soak.py --mode qos``.
"""

from .brownout import (  # noqa: F401
    BrownoutController, MAX_LEVEL, QosGate, SHED_ORDER,
)
from .policy import (  # noqa: F401
    BudgetExhaustedError, QosError, QosPolicy, RequestShedError,
    TokenBucket, validate_class,
)
from .preempt import (  # noqa: F401
    estimate_slot_wait_s, pick_victim, should_preempt, would_miss,
)
from .sched import QosQueue  # noqa: F401
