"""Weighted-fair admission queue: stride/virtual-time scheduling plus
a deadline min-heap.

Replaces the batcher's FIFO admission queue (docs/qos.md).  Every
``(tenant, class)`` pair is one *flow*; backlogged flows are served in
virtual-finish-time order — the stride discipline:

* a flow's **stride** is ``STRIDE_UNIT / weight`` (weight = class
  weight × tenant share, ``policy.QosPolicy.weight``);
* each dispatch advances the flow's virtual finish time by one stride,
  so over any interval a backlogged flow receives slots in proportion
  to its weight — one hot tenant's flood advances its own clock past
  everyone else's and *cannot starve the rest* (the fairness bound of
  stride scheduling: a flow's service lag is at most one request);
* a flow that goes idle and returns re-enters at ``max(its old clock,
  the global virtual time)`` — it cannot bank credit while idle and
  then burst past active flows.

With a single flow (no tenants configured) the discipline degenerates
to exact FIFO, so the QoS queue is always on — unconfigured servers
behave precisely as before.

**Deadline expiry is a min-heap**, not a queue scan: the old
``_expire`` walked the whole queue under the lock every step, an
O(queue) cost per step that scaled with exactly the overload the
deadline machinery exists to survive.  Entries are lazily invalidated
(pop/remove drop the id from the live set), so expiry is
O(expired · log n) amortized.

Thread safety: the batcher calls under its own lock already, but
cancel/expiry also arrive from RPC handler threads — every method
takes the queue's own lock (always acquired *after* the batcher's,
never the reverse: no lock-order cycle).

The ``qos`` fault site's ``invert`` mode fires at :meth:`pop` — the
scheduler dispatches the LOWEST-priority backlogged flow instead, a
priority-inversion bug injected on purpose (the chaos drill for the
preemption/brownout safety net).
"""

from __future__ import annotations

import collections
import heapq
import itertools
import threading
from typing import Dict, List, Optional, Tuple

from ... import faults as faults_mod

# Virtual-time unit: one weight-1.0 dispatch advances a flow's clock by
# this much.  Any constant works; a large one keeps strides integral-ish
# for readable debugging.
STRIDE_UNIT = 1 << 20


class _Flow:
    __slots__ = ("queue", "vfinish", "weight")

    def __init__(self, weight: float, vtime: float) -> None:
        self.queue: "collections.deque" = collections.deque()
        self.vfinish = vtime
        self.weight = max(1e-6, float(weight))


class QosQueue:
    """Weighted-fair admission queue over ``ServeRequest``-shaped
    items (anything with ``request_id``/``tenant``/``qos_class``/
    ``deadline`` attributes)."""

    def __init__(self, policy) -> None:
        self._policy = policy
        self._lock = threading.Lock()
        self._flows: Dict[Tuple[str, str], _Flow] = {}  # guarded-by: _lock
        self._vtime = 0.0                               # guarded-by: _lock
        self._by_id: Dict[str, object] = {}             # guarded-by: _lock
        self._heap: List[tuple] = []                    # guarded-by: _lock
        self._seq = itertools.count()

    def __len__(self) -> int:
        with self._lock:
            return len(self._by_id)

    def depths(self) -> Dict[str, int]:
        """Queued requests per class (the brownout/controller signal)."""
        out: Dict[str, int] = {}
        with self._lock:
            for (_, cls), flow in self._flows.items():
                if flow.queue:
                    out[cls] = out.get(cls, 0) + len(flow.queue)
        return out

    # --- admission ----------------------------------------------------------

    def push(self, req) -> None:
        key = (req.tenant, req.qos_class)
        with self._lock:
            flow = self._flows.get(key)
            if flow is None:
                flow = _Flow(self._policy.weight(*key), self._vtime)
                self._flows[key] = flow
            elif not flow.queue:
                # Reactivation: no banked credit from the idle period.
                flow.vfinish = max(flow.vfinish, self._vtime)
            flow.queue.append(req)
            self._by_id[req.request_id] = req
            if req.deadline is not None:
                heapq.heappush(self._heap,
                               (req.deadline, next(self._seq), req))

    # --- dispatch -----------------------------------------------------------

    def pop(self):
        """Next request in weighted-fair order (None when empty)."""
        invert = (faults_mod._active is not None
                  and faults_mod.on_qos_pick())
        with self._lock:
            backlogged = [(flow.vfinish, key, flow)
                          for key, flow in self._flows.items()
                          if flow.queue]
            if not backlogged:
                return None
            pick = max(backlogged) if invert else min(backlogged)
            vfinish, _, flow = pick
            self._vtime = max(self._vtime, min(b[0] for b in backlogged))
            req = flow.queue.popleft()
            flow.vfinish = vfinish + STRIDE_UNIT / flow.weight
            self._by_id.pop(req.request_id, None)
            return req

    # --- removal ------------------------------------------------------------

    def remove(self, request_id: str):
        """Take one queued request out by id (cancel); returns it or
        None.  The deadline-heap entry dies lazily."""
        with self._lock:
            req = self._by_id.pop(request_id, None)
            if req is None:
                return None
            flow = self._flows.get((req.tenant, req.qos_class))
            if flow is not None:
                try:
                    flow.queue.remove(req)
                except ValueError:
                    pass
            return req

    def pop_expired(self, now: float) -> list:
        """Every queued request whose deadline passed — O(expired ·
        log n): the heap's top is the earliest deadline, so one peek
        per step suffices when nothing expired."""
        out = []
        with self._lock:
            while self._heap and self._heap[0][0] <= now:
                _, _, req = heapq.heappop(self._heap)
                if self._by_id.pop(req.request_id, None) is None:
                    continue   # already dispatched/cancelled: stale entry
                flow = self._flows.get((req.tenant, req.qos_class))
                if flow is not None:
                    try:
                        flow.queue.remove(req)
                    except ValueError:
                        pass
                out.append(req)
        return out

    def drain(self) -> list:
        """Remove and return everything queued (replica death)."""
        with self._lock:
            out = list(self._by_id.values())
            self._by_id.clear()
            self._heap.clear()
            for flow in self._flows.values():
                flow.queue.clear()
            return out

    # --- scheduling probes --------------------------------------------------

    def urgent(self, qos_class: str = "interactive"
               ) -> Optional[tuple]:
        """``(deadline, request)`` of the most urgent queued request of
        ``qos_class`` — earliest deadline first, then (for the SLO-TTFT
        trigger, which needs deadline-less requests too) earliest
        submitted.  None when the class has nothing queued.  Scans only
        that class's flows — under overload the protected class's queue
        is short by construction (everything else sheds/preempts
        first)."""
        best = None
        with self._lock:
            for (_, cls), flow in self._flows.items():
                if cls != qos_class:
                    continue
                for req in flow.queue:
                    key = ((0, req.deadline) if req.deadline is not None
                           else (1, getattr(req, "submitted_at", 0.0)))
                    if best is None or key < best[0]:
                        best = (key, req)
        if best is None:
            return None
        return best[1].deadline, best[1]
