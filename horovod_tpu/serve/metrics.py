"""Serving observability: TTFT/TPOT/occupancy accounting.

The two latencies that define an LLM serving SLO are time-to-first-token
(TTFT: admission + prefill) and time-per-output-token (TPOT: decode
cadence under continuous batching).  Both are recorded per request by
the batcher and aggregated here into percentile snapshots with the same
JSON-friendly shape ``benchmarks/serving_bench.py`` emits, so the live
``StatsRequest`` endpoint and the offline bench artifact read
identically.

Bounded memory: samples live in fixed-size rings — a serving process
that handles millions of requests must not grow its stats linearly.
The ring and percentile primitives live in :mod:`horovod_tpu.obs.
metrics` (the unified telemetry layer); this module is a thin consumer
that keeps the serving-specific snapshot shape (``percentile`` stays
importable from here for existing callers).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..obs.metrics import Ring, percentile  # noqa: F401 (re-export)

# Bounded per-tenant rollup: the same discipline as the obs registry's
# 64-series cap — a serving process must not grow stats with the tenant
# population; the overflow bucket absorbs the tail.
_MAX_TENANTS = 64
_OVERFLOW_TENANT = "other"


class _ClassStats:
    """Per-QoS-class latency/goodput rollup (caller holds the stats
    lock — single-owner helper, the ``_locked`` contract)."""

    __slots__ = ("ttft_s", "tpot_s", "completed", "expired", "failed",
                 "tokens_out")

    def __init__(self, window: int) -> None:
        self.ttft_s = Ring(window)
        self.tpot_s = Ring(window)
        self.completed = 0
        self.expired = 0
        self.failed = 0
        self.tokens_out = 0


class ServingStats:
    """Thread-safe rolling serving metrics (one instance per batcher).

    ``record_request`` is called once per *finished* request;
    ``record_step`` once per batcher scheduling step (occupancy is a
    per-step sample, weighting busy and idle periods equally —
    the signal that says "add replicas" vs "shrink the fleet").
    """

    def __init__(self, window: int = 4096,
                 weights_version: int = 0) -> None:
        self._lock = threading.Lock()
        self._ttft_s = Ring(window)       # guarded-by: _lock
        self._tpot_s = Ring(window)       # guarded-by: _lock
        self._occupancy = Ring(window)    # guarded-by: _lock
        self._queue_depth = Ring(window)  # guarded-by: _lock
        self.completed = 0                # guarded-by: _lock
        self.rejected = 0                 # guarded-by: _lock
        self.expired = 0                  # guarded-by: _lock
        self.failed = 0                   # guarded-by: _lock
        self.tokens_out = 0               # guarded-by: _lock
        self.prefix_hits = 0              # guarded-by: _lock
        self.prefix_misses = 0            # guarded-by: _lock
        # Weight hot-swap (serve/swap.py): the checkpoint step the
        # replica's weights came from (seeded from the engine at
        # batcher construction, advanced only by flips — ONE consistent
        # path, never shadow-overwritten) and how many flips it
        # survived.
        self.weights_version = int(weights_version)  # guarded-by: _lock
        self.swaps_completed = 0          # guarded-by: _lock
        # Multi-tenant QoS rollups (serve/qos/; docs/qos.md): per-class
        # latency/goodput, bounded per-tenant token accounting, and the
        # preemption/shed/budget counters the SLO dashboards read.
        self._window = window
        self._classes: Dict[str, _ClassStats] = {}  # guarded-by: _lock
        self._tenants: Dict[str, Dict] = {}         # guarded-by: _lock
        self.preemptions = 0              # guarded-by: _lock
        self.budget_rejects = 0           # guarded-by: _lock
        self._t0 = time.monotonic()

    def _class_locked(self, qos_class: Optional[str]) -> _ClassStats:
        cls = qos_class or "standard"
        st = self._classes.get(cls)
        if st is None:
            st = self._classes[cls] = _ClassStats(self._window)  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        return st

    def _tenant_locked(self, tenant: Optional[str]) -> Dict:
        name = tenant or "default"
        row = self._tenants.get(name)
        if row is None:
            if len(self._tenants) >= _MAX_TENANTS:
                name = _OVERFLOW_TENANT   # bounded: the tail collapses
                row = self._tenants.get(name)
            if row is None:
                row = self._tenants[name] = {"completed": 0,  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
                                             "tokens_out": 0,
                                             "rejected": 0}
        return row

    def record_request(self, ttft_s: float, n_tokens: int,
                       total_s: float, qos_class: Optional[str] = None,
                       tenant: Optional[str] = None) -> None:
        with self._lock:
            self.completed += 1
            self.tokens_out += n_tokens
            self._ttft_s.append(ttft_s)
            tpot = None
            if n_tokens > 1 and total_s > ttft_s:
                # TPOT is the inter-token cadence after the first token.
                tpot = (total_s - ttft_s) / (n_tokens - 1)
                self._tpot_s.append(tpot)
            cls = self._class_locked(qos_class)
            cls.completed += 1
            cls.tokens_out += n_tokens
            cls.ttft_s.append(ttft_s)
            if tpot is not None:
                cls.tpot_s.append(tpot)
            trow = self._tenant_locked(tenant)
            trow["completed"] += 1
            trow["tokens_out"] += n_tokens

    def tpot_estimate_s(self) -> Optional[float]:
        """Mean observed decode cadence (the preemption wait
        estimator's input); None before any multi-token completion."""
        with self._lock:
            vals = self._tpot_s.values()
            return sum(vals) / len(vals) if vals else None

    def record_preempted(self) -> None:
        """One batch generation evicted-and-requeued for an
        interactive deadline (serve/qos/preempt.py)."""
        with self._lock:
            self.preemptions += 1

    def record_budget_rejected(self, tenant: Optional[str] = None) -> None:
        """One admission rejected by a tenant's token budget."""
        with self._lock:
            self.budget_rejects += 1
            self._tenant_locked(tenant)["rejected"] += 1

    def record_step(self, active: int, slots: int, queued: int) -> None:
        with self._lock:
            self._occupancy.append(active / max(1, slots))
            self._queue_depth.append(queued)

    def record_prefix(self, hit: bool) -> None:
        """One prefill binding: did the prompt's prefix hit resident KV
        blocks (serve/kv/)?  Ratio lands in the snapshot — the signal
        that says the fleet's routing keeps prefixes warm."""
        with self._lock:
            if hit:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1

    def set_weights_version(self, version: int) -> None:
        """One completed hot-swap flip: the replica now serves
        ``version`` (the checkpoint step)."""
        with self._lock:
            self.weights_version = int(version)
            self.swaps_completed += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_expired(self, qos_class: Optional[str] = None) -> None:
        with self._lock:
            self.expired += 1
            self._class_locked(qos_class).expired += 1

    def record_failed(self, qos_class: Optional[str] = None) -> None:
        with self._lock:
            self.failed += 1
            self._class_locked(qos_class).failed += 1

    def snapshot(self) -> Dict:
        """One JSON-ready dict — the serving bench summary fields and
        the ``StatsRequest`` wire payload share this shape."""
        with self._lock:
            ttft = self._ttft_s.values()
            tpot = self._tpot_s.values()
            occ = self._occupancy.values()
            queued = self._queue_depth.values()
            elapsed = max(1e-9, time.monotonic() - self._t0)
            bound = self.prefix_hits + self.prefix_misses
            out = {
                "requests_completed": self.completed,
                "requests_rejected": self.rejected,
                "requests_expired": self.expired,
                "requests_failed": self.failed,
                "weights_version": self.weights_version,
                "swaps_completed": self.swaps_completed,
                "tokens_out": self.tokens_out,
                "tok_per_s": round(self.tokens_out / elapsed, 3),
                "prefix_hits": self.prefix_hits,
                "prefix_hit_ratio": (round(self.prefix_hits / bound, 4)
                                     if bound else None),
                "occupancy_mean": (round(sum(occ) / len(occ), 4)
                                   if occ else None),
                "queue_depth_mean": (round(sum(queued) / len(queued), 2)
                                     if queued else None),
            }
            for name, samples in (("ttft_ms", ttft), ("tpot_ms", tpot)):
                for q in (50, 99):
                    v = percentile(samples, q)
                    out[f"{name}_p{q}"] = (round(v * 1e3, 3)
                                           if v is not None else None)
            # Multi-tenant QoS block (serve/qos/): per-class latency
            # percentiles + goodput (successfully delivered tokens/s),
            # the bounded per-tenant rollup, and the policy counters.
            # Sheds are deliberately ABSENT here: shedding happens at
            # the ROUTER tier (brownout gate) before a replica ever
            # sees the request — the counters live on the obs registry
            # (hvd_tpu_qos_sheds_total) and the gate's snapshot, and a
            # structurally-zero per-replica shed field would only
            # mislead operators during an active brownout.
            qos: Dict[str, Dict] = {}
            for cls, st in sorted(self._classes.items()):
                row: Dict = {
                    "completed": st.completed, "expired": st.expired,
                    "failed": st.failed,
                    "tokens_out": st.tokens_out,
                    "goodput_tok_per_s": round(st.tokens_out / elapsed, 3),
                }
                for name, ring in (("ttft_ms", st.ttft_s),
                                   ("tpot_ms", st.tpot_s)):
                    vals = ring.values()
                    for q in (50, 99):
                        v = percentile(vals, q)
                        row[f"{name}_p{q}"] = (round(v * 1e3, 3)
                                               if v is not None else None)
                qos[cls] = row
            out["qos"] = qos
            out["tenants"] = {t: dict(r)
                              for t, r in sorted(self._tenants.items())}
            out["preemptions"] = self.preemptions
            out["budget_rejects"] = self.budget_rejects
            return out
