"""Replica-local serving endpoint on the runner's RPC stack.

One :class:`InferenceServer` fronts one continuous-batching replica: it
reuses ``runner/common/network.py``'s :class:`BasicService` (threaded
TCP, HMAC-authenticated frames — the same launcher-minted secret the
driver/task control plane uses, so a serving fleet needs no second
credential system).  Each connection handler blocks on its request's
completion event while the batcher thread schedules; the threaded
server gives per-request concurrency for free.

Error taxonomy on the wire (``GenerateResponse.error``):

* ``busy`` — admission queue full (backpressure; router retries
  elsewhere after backoff)
* ``deadline_exceeded`` — the request's own deadline expired (terminal:
  retrying a dead deadline elsewhere would waste a second replica)
* ``replica_killed`` / ``replica_dead`` — this replica died mid-flight
  / is refusing work (router strikes it and re-runs on a survivor)
* ``prompt_too_long: ...`` — caller error (terminal)

The ``serve`` fault site's ``drop``/``delay`` modes fire here, before
admission: a dropped request closes the connection with no response
(:class:`DropConnection`) — on the router side indistinguishable from
a replica crashing at the worst moment.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .. import faults as faults_mod
from ..runner.common.network import AckResponse, BasicService, DropConnection
from ..utils.logging import get_logger
from .batcher import (ContinuousBatcher, QueueFullError,
                      ReplicaKilledError)
from .engine import PromptTooLongError, SamplingParams

logger = get_logger(__name__)


class GenerateRequest:
    def __init__(self, request_id: str, prompt: List[int],
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, stop_token: Optional[int] = None,
                 deadline_s: Optional[float] = None, spec: bool = False):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.deadline_s = deadline_s
        # Per-request speculative-decoding opt-in (greedy only; ignored
        # by replicas whose engine has no drafter).
        self.spec = spec


class GenerateResponse:
    def __init__(self, request_id: str, tokens: Optional[List[int]],
                 error: Optional[str] = None,
                 ttft_ms: Optional[float] = None):
        self.request_id = request_id
        self.tokens = tokens
        self.error = error
        self.ttft_ms = ttft_ms


class CancelRequest:
    """Abandon ``request_id`` on this replica (router failover: the
    request was re-run elsewhere; answered with ``AckResponse``)."""

    def __init__(self, request_id: str):
        self.request_id = request_id


class StatsRequest:
    pass


class StatsResponse:
    def __init__(self, stats: dict):
        self.stats = stats


class InferenceServer(BasicService):
    """One serving replica: a batcher behind an authenticated socket.

    ``replica_ranks`` records which mesh slots this replica's model
    spans (its data-parallel process-set group; see
    ``serve/router.py::replica_slot_groups``) — advertised in stats so
    fleet tooling can map replicas back onto the mesh.
    """

    def __init__(self, batcher: ContinuousBatcher, key: bytes,
                 name: str = "serve", host: str = "0.0.0.0",
                 nics: Optional[List[str]] = None,
                 replica_ranks: Optional[List[int]] = None,
                 start_batcher: bool = True):
        super().__init__(name, key, host=host, nics=nics)
        self._batcher = batcher
        self.replica_ranks = list(replica_ranks) if replica_ranks else None
        if start_batcher:
            batcher.start()

    @property
    def dead(self) -> bool:
        return self._batcher.dead

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, GenerateRequest):
            return self._generate(req)
        if isinstance(req, CancelRequest):
            self._batcher.cancel(req.request_id)
            return AckResponse()
        if isinstance(req, StatsRequest):
            snap = self._batcher.snapshot()
            if self.replica_ranks is not None:
                snap["replica_ranks"] = self.replica_ranks
            return StatsResponse(snap)
        return super()._handle(req, client_address)

    def _generate(self, req: GenerateRequest) -> GenerateResponse:
        # Fault site "serve" (drop/delay) — before admission, so a
        # dropped request costs the replica nothing.
        if faults_mod._active is not None:
            if faults_mod.on_serve_request(type(req).__name__) == "drop":
                raise DropConnection()
        sampling = SamplingParams(
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature, top_k=req.top_k,
            stop_token=req.stop_token,
            spec=bool(getattr(req, "spec", False)))
        try:
            sr = self._batcher.submit(
                req.prompt, sampling, request_id=req.request_id,
                deadline_s=req.deadline_s)
        except QueueFullError:
            return GenerateResponse(req.request_id, None, error="busy")
        except ReplicaKilledError:
            return GenerateResponse(req.request_id, None,
                                    error="replica_dead")
        except PromptTooLongError as e:
            return GenerateResponse(req.request_id, None,
                                    error=f"prompt_too_long: {e}")
        except ValueError as e:
            # Caller error (empty prompt etc.) — answered terminally; an
            # escaped exception here would close the socket mid-frame
            # and make the router misread a poison request as a replica
            # crash (and bench the healthy fleet retrying it).
            return GenerateResponse(req.request_id, None,
                                    error=f"invalid_request: {e}")
        # The batcher guarantees `done` fires: completion (bounded by
        # the max-tokens cap), deadline expiry, cancellation, or
        # replica death (_die).  Wait in a loop rather than under one
        # arbitrary cap — a deadline-less long generation returning a
        # TRUNCATED token list as a success would be silent data loss.
        # The only unguaranteed case is a batcher thread wedged inside
        # the engine; detect it via `dead` and fail the request loudly.
        while not sr.done.wait(timeout=30.0):
            if self._batcher.dead:
                sr.finish(error="replica_dead")   # idempotent
        if sr.error is not None:
            return GenerateResponse(req.request_id, None, error=sr.error)
        ttft_ms = None
        if sr.first_token_at is not None:
            ttft_ms = round((sr.first_token_at - sr.submitted_at) * 1e3, 3)
        return GenerateResponse(req.request_id, sr.tokens, ttft_ms=ttft_ms)

    def shutdown(self) -> None:
        self._batcher.stop()
        super().shutdown()


def serve_addresses(server: InferenceServer) -> List[Tuple[str, int]]:
    """The replica's advertised (ip, port) candidates — what a deployer
    writes into the router's :class:`~horovod_tpu.serve.router
    .ReplicaSpec`."""
    return server.addresses()
