"""Replica-local serving endpoint on the runner's RPC stack.

One :class:`InferenceServer` fronts one continuous-batching replica: it
reuses ``runner/common/network.py``'s :class:`BasicService` (threaded
TCP, HMAC-authenticated frames — the same launcher-minted secret the
driver/task control plane uses, so a serving fleet needs no second
credential system).  Each connection handler blocks on its request's
completion event while the batcher thread schedules; the threaded
server gives per-request concurrency for free.

Error taxonomy on the wire (``GenerateResponse.error``):

* ``busy`` — admission queue full (backpressure; router retries
  elsewhere after backoff)
* ``deadline_exceeded`` — the request's own deadline expired (terminal:
  retrying a dead deadline elsewhere would waste a second replica)
* ``replica_killed`` / ``replica_dead`` — this replica died mid-flight
  / is refusing work (router strikes it and re-runs on a survivor)
* ``prompt_too_long: ...`` — caller error (terminal)

The ``serve`` fault site's ``drop``/``delay`` modes fire here, before
admission: a dropped request closes the connection with no response
(:class:`DropConnection`) — on the router side indistinguishable from
a replica crashing at the worst moment.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, List, Optional, Tuple

from .. import faults as faults_mod
from ..runner.common.network import (AckResponse, BasicService,
                                     CollectRequest, DrainRequest,
                                     DropConnection, KvMigrateRequest,
                                     KvMigrateResponse)
from ..utils.logging import get_logger
from .batcher import (ContinuousBatcher, QueueFullError,
                      ReplicaDrainingError, ReplicaKilledError)
from .engine import PromptTooLongError, SamplingParams, resolved_config
from .fleet.migration import MigrationBuffer, MigrationError, migrate_slot
from .qos import BudgetExhaustedError
from .swap import (SwapAbandonedError, SwapFailedError, SwapRejectedError,
                   WeightSubscriber)

logger = get_logger(__name__)


class GenerateRequest:
    def __init__(self, request_id: str, prompt: List[int],
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, stop_token: Optional[int] = None,
                 deadline_s: Optional[float] = None, spec: bool = False,
                 migrate_to: Optional[tuple] = None,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None):
        self.request_id = request_id
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.stop_token = stop_token
        self.deadline_s = deadline_s
        # Per-request speculative-decoding opt-in (greedy only; ignored
        # by replicas whose engine has no drafter).
        self.spec = spec
        # Disaggregated fleet: the router asks a prefill replica to
        # hand this request's KV to ``(name, [(ip, port), ...])`` after
        # the first token; None (or a non-prefill replica) runs the
        # full generation locally.
        self.migrate_to = migrate_to
        # Multi-tenant QoS (serve/qos/; docs/qos.md): the weighted-fair
        # flow this request rides; old peers simply never set them
        # (pickled frames, getattr defaults on the receiving side).
        self.tenant = tenant
        self.qos_class = qos_class


class GenerateResponse:
    def __init__(self, request_id: str, tokens: Optional[List[int]],
                 error: Optional[str] = None,
                 ttft_ms: Optional[float] = None,
                 migrated_to: Optional[str] = None,
                 migrate_ms: Optional[float] = None,
                 evicted_prefixes: Optional[list] = None,
                 weights_version: Optional[int] = None):
        self.request_id = request_id
        self.tokens = tokens
        self.error = error
        self.ttft_ms = ttft_ms
        # Weight hot-swap (serve/swap.py): the checkpoint step this
        # response's tokens were generated under.  The router tracks it
        # per replica — a prefix-directory entry recorded under one
        # version must not route a request to the same replica after it
        # flipped (stale KV against new weights would be silently
        # wrong), so a version change invalidates the entries.
        self.weights_version = weights_version
        # KV migration outcome: the decode replica now carrying the
        # generation (the router collects the final tokens there) and
        # the transfer's wall time (the bench's migration-overhead
        # signal).
        self.migrated_to = migrated_to
        self.migrate_ms = migrate_ms
        # Eviction notifications piggybacked for the router's global
        # prefix directory: leading-block keys this replica no longer
        # holds (serve/kv/pool.py::drain_evicted_keys).
        self.evicted_prefixes = evicted_prefixes


class CancelRequest:
    """Abandon ``request_id`` on this replica (router failover: the
    request was re-run elsewhere; answered with ``AckResponse``)."""

    def __init__(self, request_id: str):
        self.request_id = request_id


class StatsRequest:
    pass


class StatsResponse:
    def __init__(self, stats: dict):
        self.stats = stats


class SwapRequest:
    """Hot-swap this replica's weights to checkpoint ``step`` from its
    subscribed store (serve/swap.py; docs/hot_swap.md): diff-pull the
    changed shards, digest-verify, stage, flip at the batcher's swap
    barrier.  The fleet controller's rolling swap sends these bounded
    by ``HVD_TPU_SWAP_MAX_CONCURRENT``.  Answered with
    :class:`SwapResponse`; every failure leaves the old weights
    serving."""

    def __init__(self, step: int):
        self.step = int(step)


class RollbackRequest:
    """Instant rollback: re-point this replica at any journaled step
    still intact in the store, through the SAME staged-flip path a
    forward swap uses (the only difference: the newer-step check is
    waived).  Answered with :class:`SwapResponse`."""

    def __init__(self, step: int):
        self.step = int(step)


class SwapResponse:
    """Outcome of a :class:`SwapRequest`/:class:`RollbackRequest`:
    ``error`` is None once the flip committed; ``weights_version`` is
    the version now serving either way (a failed swap reports the OLD
    version — the replica is always on exactly one).  ``pulled_bytes``
    and ``swap_ms`` size the manifest-diff pull."""

    def __init__(self, step: int, error: Optional[str] = None,
                 weights_version: Optional[int] = None,
                 pulled_bytes: int = 0,
                 swap_ms: Optional[float] = None):
        self.step = step
        self.error = error
        self.weights_version = weights_version
        self.pulled_bytes = pulled_bytes
        self.swap_ms = swap_ms


class InferenceServer(BasicService):
    """One serving replica: a batcher behind an authenticated socket.

    ``replica_ranks`` records which mesh slots this replica's model
    spans (its data-parallel process-set group; see
    ``serve/router.py::replica_slot_groups``) — advertised in stats so
    fleet tooling can map replicas back onto the mesh.
    """

    def __init__(self, batcher: ContinuousBatcher, key: bytes,
                 name: str = "serve", host: str = "0.0.0.0",
                 nics: Optional[List[str]] = None,
                 replica_ranks: Optional[List[int]] = None,
                 start_batcher: bool = True,
                 migrate_chunk_bytes: Optional[int] = None,
                 swap_store: Optional[str] = None,
                 subscribe: bool = True,
                 tp_peers: Optional[List[Tuple[str, List[Tuple[str,
                                                               int]]]]] = None):
        super().__init__(name, key, host=host, nics=nics)
        self._batcher = batcher
        self.replica_ranks = list(replica_ranks) if replica_ranks else None
        # Zero-downtime weight hot-swap (serve/swap.py): with a
        # ``swap_store`` directory this replica subscribes to the
        # checkpoint store — polling for newer intact steps when
        # ``subscribe`` is on, and always answering ``SwapRequest`` /
        # ``RollbackRequest`` (the fleet controller's rolling path).
        self.subscriber: Optional[WeightSubscriber] = None
        if swap_store is not None:
            self.subscriber = WeightSubscriber(batcher, swap_store)
            if subscribe:
                self.subscriber.start()
        # Disaggregated fleet: receiver-side migration assembly (any
        # role may adopt) and the sender-side handoff on prefill
        # replicas (serve/fleet/migration.py over this server's key).
        self._migrations = MigrationBuffer()
        self._adopt_lock = threading.Lock()
        self._adopted: "OrderedDict[str, Any]" = OrderedDict()  # guarded-by: _adopt_lock
        if batcher.role == "prefill":
            chunk = int(migrate_chunk_bytes
                        or resolved_config().fleet_migrate_chunk)

            def _migrator(engine, slot, sreq):
                return migrate_slot(engine, slot, sreq, sreq.migrate_to,
                                    self._key, chunk_bytes=chunk)

            batcher.set_migrator(_migrator)
        # Tensor-parallel replica leader (serve/tp.py; docs/
        # tp_serving.md): ``tp_peers`` names this replica's follower
        # shard ranks — ``[(service_name, [(ip, port), ...]), ...]`` —
        # and installs the lockstep dispatch on the batcher BEFORE it
        # starts, over the same HMAC key (one credential system).
        if tp_peers:
            from .tp import ShardFollower

            batcher.set_lockstep(ShardFollower(list(tp_peers), key))
        if start_batcher:
            batcher.start()

    @property
    def dead(self) -> bool:
        return self._batcher.dead

    @property
    def role(self) -> str:
        return self._batcher.role

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, GenerateRequest):
            return self._generate(req)
        if isinstance(req, CancelRequest):
            self._migrations.discard(req.request_id)
            self._batcher.cancel(req.request_id)
            return AckResponse()
        if isinstance(req, KvMigrateRequest):
            return self._kv_migrate(req)
        if isinstance(req, CollectRequest):
            return self._collect(req)
        if isinstance(req, DrainRequest):
            if getattr(req, "cancel", False):
                self._batcher.undrain()
            else:
                self._batcher.drain()
            return AckResponse()
        if isinstance(req, StatsRequest):
            snap = self._batcher.snapshot()
            if self.replica_ranks is not None:
                snap["replica_ranks"] = self.replica_ranks
            return StatsResponse(snap)
        if isinstance(req, SwapRequest):
            return self._swap(req, rollback=False)
        if isinstance(req, RollbackRequest):
            return self._swap(req, rollback=True)
        return super()._handle(req, client_address)

    def _swap(self, req, rollback: bool) -> SwapResponse:
        """Drive one hot-swap (or rollback) through the subscriber.
        Every failure is a terminal per-request answer carrying the
        version STILL serving — a failed swap is an economics event,
        never a health strike."""
        sub = self.subscriber
        engine = self._batcher.engine
        if sub is None:
            return SwapResponse(req.step, error="no_swap_store",
                                weights_version=engine.weights_version)
        try:
            info = sub.swap_to_info(req.step, rollback=rollback)
        except SwapRejectedError as e:
            return SwapResponse(req.step, error=f"rejected: {e}",
                                weights_version=engine.weights_version)
        except SwapAbandonedError as e:
            return SwapResponse(req.step, error=f"abandoned: {e}",
                                weights_version=engine.weights_version)
        except (SwapFailedError, ReplicaKilledError) as e:
            return SwapResponse(req.step, error=f"failed: {e}",
                                weights_version=engine.weights_version)
        # ``ms`` was measured INSIDE the swap lock — re-timing here
        # would bill a concurrent poller swap's wait to this one.
        return SwapResponse(
            req.step, weights_version=int(info["version"]),
            pulled_bytes=int(info.get("pulled_bytes", 0)),
            swap_ms=info.get("ms", 0.0))

    def _kv_migrate(self, req: KvMigrateRequest) -> KvMigrateResponse:
        """One migration frame: buffer; on the final frame verify the
        digests and adopt the request into the batcher.  Every error is
        a terminal per-transfer answer — the sender falls back to
        decoding locally, so nothing here may strike this replica."""
        try:
            done = self._migrations.add(req)
        except MigrationError as e:
            return KvMigrateResponse(req.request_id, error=str(e))
        if done is None:
            return KvMigrateResponse(req.request_id)   # frame buffered
        manifest, k, v = done
        try:
            sr = self._batcher.adopt(manifest, k, v)
        except QueueFullError:
            return KvMigrateResponse(req.request_id, error="busy")
        except ReplicaDrainingError:
            return KvMigrateResponse(req.request_id, error="draining")
        except ReplicaKilledError:
            return KvMigrateResponse(req.request_id, error="replica_dead")
        except (PromptTooLongError, ValueError) as e:
            return KvMigrateResponse(req.request_id,
                                     error=f"invalid_migration: {e}")
        with self._adopt_lock:
            self._adopted[sr.request_id] = sr
            while len(self._adopted) > 1024:
                self._adopted.popitem(last=False)
        return KvMigrateResponse(req.request_id)

    def _collect(self, creq: CollectRequest) -> GenerateResponse:
        """Block until the adopted (migrated-in) request finishes and
        answer with its full token stream — the router's decode half of
        the admit→prefill→migrate→decode pipeline."""
        with self._adopt_lock:
            sr = self._adopted.get(creq.request_id)
        if sr is None:
            # Adoption lost (restart, cancel, LRU overflow): the router
            # re-routes to a recompute path.
            return GenerateResponse(creq.request_id, None,
                                    error="unknown_request")
        while not sr.done.wait(timeout=30.0):
            if self._batcher.dead:
                sr.finish(error="replica_dead")   # idempotent
        with self._adopt_lock:
            self._adopted.pop(creq.request_id, None)
        if sr.error is not None:
            return GenerateResponse(creq.request_id, None, error=sr.error)
        ttft_ms = None
        if sr.first_token_at is not None:
            ttft_ms = round((sr.first_token_at - sr.submitted_at) * 1e3, 3)
        return GenerateResponse(
            creq.request_id, sr.tokens, ttft_ms=ttft_ms,
            evicted_prefixes=self._drain_evictions(),
            weights_version=(sr.weights_version
                             if sr.weights_version is not None
                             else self._batcher.engine.weights_version))

    def _drain_evictions(self) -> Optional[list]:
        keys = self._batcher.engine.drain_evicted_prefixes()
        return [list(k) for k in keys] or None

    def _generate(self, req: GenerateRequest) -> GenerateResponse:
        # Fault site "serve" (drop/delay) — before admission, so a
        # dropped request costs the replica nothing.
        if faults_mod._active is not None:
            if faults_mod.on_serve_request(type(req).__name__) == "drop":
                raise DropConnection()
        sampling = SamplingParams(
            max_new_tokens=req.max_new_tokens,
            temperature=req.temperature, top_k=req.top_k,
            stop_token=req.stop_token,
            spec=bool(getattr(req, "spec", False)))
        try:
            sr = self._batcher.submit(
                req.prompt, sampling, request_id=req.request_id,
                deadline_s=req.deadline_s,
                migrate_to=getattr(req, "migrate_to", None),
                tenant=getattr(req, "tenant", None),
                qos_class=getattr(req, "qos_class", None))
        except BudgetExhaustedError as e:
            # Typed retriable rejection (docs/qos.md): the CLIENT backs
            # off retry_after_s — the router must neither strike this
            # replica nor re-run the request elsewhere (the budget is
            # policy, not health).
            return GenerateResponse(
                req.request_id, None,
                error=f"budget_exhausted: retry_after_s="
                      f"{e.retry_after_s:.2f}")
        except QueueFullError:
            return GenerateResponse(req.request_id, None, error="busy")
        except ReplicaDrainingError:
            return GenerateResponse(req.request_id, None,
                                    error="draining")
        except ReplicaKilledError:
            return GenerateResponse(req.request_id, None,
                                    error="replica_dead")
        except PromptTooLongError as e:
            return GenerateResponse(req.request_id, None,
                                    error=f"prompt_too_long: {e}")
        except ValueError as e:
            # Caller error (empty prompt etc.) — answered terminally; an
            # escaped exception here would close the socket mid-frame
            # and make the router misread a poison request as a replica
            # crash (and bench the healthy fleet retrying it).
            return GenerateResponse(req.request_id, None,
                                    error=f"invalid_request: {e}")
        # The batcher guarantees `done` fires: completion (bounded by
        # the max-tokens cap), deadline expiry, cancellation, or
        # replica death (_die).  Wait in a loop rather than under one
        # arbitrary cap — a deadline-less long generation returning a
        # TRUNCATED token list as a success would be silent data loss.
        # The only unguaranteed case is a batcher thread wedged inside
        # the engine; detect it via `dead` and fail the request loudly.
        while not sr.done.wait(timeout=30.0):
            if self._batcher.dead:
                sr.finish(error="replica_dead")   # idempotent
        if sr.error is not None:
            return GenerateResponse(req.request_id, None, error=sr.error)
        ttft_ms = None
        if sr.first_token_at is not None:
            ttft_ms = round((sr.first_token_at - sr.submitted_at) * 1e3, 3)
        return GenerateResponse(
            req.request_id, sr.tokens, ttft_ms=ttft_ms,
            migrated_to=(sr.migrate_to[0]
                         if sr.migrated and sr.migrate_to else None),
            migrate_ms=sr.migrate_ms,
            evicted_prefixes=self._drain_evictions(),
            weights_version=(sr.weights_version
                             if sr.weights_version is not None
                             else self._batcher.engine.weights_version))

    def shutdown(self) -> None:
        if self.subscriber is not None:
            self.subscriber.stop()
        self._batcher.stop()
        super().shutdown()


def serve_addresses(server: InferenceServer) -> List[Tuple[str, int]]:
    """The replica's advertised (ip, port) candidates — what a deployer
    writes into the router's :class:`~horovod_tpu.serve.router
    .ReplicaSpec`."""
    return server.addresses()
