"""Zero-downtime weight hot-swap: checkpoint store → serving replica.

The train→serve loop's last hop (docs/hot_swap.md): a trainer commits
steps into a :class:`~horovod_tpu.ckpt.store.ShardStore`; each serving
replica runs a :class:`WeightSubscriber` that

1. **subscribes** — polls the store for a newer *intact* step
   (``ShardStore.newest_intact_step``: manifest-granularity validation,
   so a torn upload never becomes a serving version);
2. **diffs** — compares the new manifest's per-leaf digests against the
   running version (``ckpt.manifest.diff_manifest``) and pulls ONLY the
   changed shards, lazily per ``.npz`` member, verifying every pulled
   leaf against its manifest digest;
3. **stages** — builds the full new param tree (pulled leaves + cached
   unchanged ones) alongside the live params and hands it to the
   engine (``InferenceEngine.stage_params``);
4. **flips** — asks the batcher for its swap barrier
   (``ContinuousBatcher.flip_at_barrier``): admission holds, in-flight
   generations finish on the version they started on, and the engine's
   param reference swaps atomically between decode bursts — then the
   prefix cache is flushed (resident KV was computed under the old
   weights; stale KV against new weights is the silent-wrongness bug
   the mixed-version routing rule exists for).

**Every failure degrades to "keep serving the old weights", never to
dropped or wrong tokens**: a digest mismatch discards the staged pull
and retries under :class:`~horovod_tpu.utils.retry.RetryPolicy`
(``HVD_TPU_SWAP_RETRIES``); a pull stalled past
``HVD_TPU_SWAP_DEADLINE_S`` is abandoned and flight-recorded; a replica
killed at the flip barrier fails over through the router exactly like
any other replica death (the flip is one atomic reference swap, so a
replica is always on exactly one version).

**Rollback** rides the same path: ``swap_to(step, rollback=True)``
re-points the replica at any journaled step still intact in the store —
the ``RollbackRequest`` wire frame (serve/server.py) and the fleet
controller's ``roll_swap(..., rollback=True)`` drive it fleet-wide.

Fault site ``swap`` (``HVD_TPU_FAULT_SPEC``): ``corrupt-shard`` and
``stall`` fire here at the pull; ``kill-mid-flip`` fires at the
batcher's barrier; ``partial-fleet`` at the controller's roll.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from .. import faults as faults_mod
from ..ckpt.errors import CheckpointCorruptionError
from ..ckpt.manifest import Manifest, ManifestError, diff_manifest
from ..ckpt.snapshot import leaf_record_digest, path_string
from ..ckpt.store import ShardStore
from ..obs import flight as flight_mod
from ..obs import instrument as _obs
from ..obs import trace as trace_mod
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy, retry_call
from .batcher import ReplicaKilledError
from .engine import resolved_config

logger = get_logger(__name__)

__all__ = ["WeightSubscriber", "SwapRejectedError", "SwapAbandonedError",
           "SwapFailedError", "leaf_digests"]


class SwapRejectedError(RuntimeError):
    """The pulled step failed verification (damaged manifest, digest
    mismatch, unreadable shard) — the staged pull was discarded and the
    replica keeps serving the old weights."""


class SwapAbandonedError(RuntimeError):
    """The pull/stage/flip ran past ``HVD_TPU_SWAP_DEADLINE_S`` — the
    swap was withdrawn and the replica keeps serving the old weights."""


class SwapFailedError(RuntimeError):
    """The flip itself could not run (replica died at the barrier /
    engine error) — never a half-applied state: the param reference
    either swapped atomically or it did not."""


def leaf_digests(tree: Any) -> Dict[str, tuple]:
    """``{key-path: (digest-hex, host-array)}`` for a param tree — the
    subscriber's running-version leaf cache, in exactly the digest
    format the shard manifests record, so boot weights saved by the
    trainer diff as unchanged."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out: Dict[str, tuple] = {}
    for path, leaf in flat:
        arr = np.asarray(jax.device_get(leaf))
        pstr = path_string(path)
        out[pstr] = (leaf_record_digest(pstr, arr).hex(), arr)
    return out


class WeightSubscriber:
    """Per-replica live-deployment agent over one checkpoint store.

    ``batcher`` is the replica's :class:`ContinuousBatcher` (the flip
    rides its barrier); ``directory`` the ``ShardStore`` root the
    trainer commits into.  The running version seeds from the engine's
    live params (version ``engine.weights_version``) unless ``params``/
    ``version`` say otherwise — seeding from the same tree the trainer
    saved makes the first swap pull only what actually changed.

    Drive it with :meth:`poll_once` (deterministic — tests, drills) or
    :meth:`start`/:meth:`stop` (background polling thread — what the
    serving endpoint does).  After a rollback the forward watch is
    PINNED (newer store steps are the weights just rolled back from);
    the next explicit forward :meth:`swap_to` unpins it.
    """

    def __init__(self, batcher, directory: str, *,
                 params: Any = None,
                 version: Optional[int] = None,
                 poll_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 retries: Optional[int] = None) -> None:
        cfg = resolved_config()
        self._batcher = batcher
        self._engine = batcher.engine
        self._store = ShardStore(directory)
        self.poll_s = float(poll_s if poll_s is not None
                            else cfg.swap_poll_s)
        self.deadline_s = float(deadline_s if deadline_s is not None
                                else cfg.swap_deadline_s)
        self._policy = RetryPolicy(
            attempts=int(retries if retries is not None
                         else cfg.swap_retries),
            base_delay_s=0.1, max_delay_s=2.0)
        self._lock = threading.Lock()
        # One swap at a time per replica: the background poller and a
        # controller SwapRequest otherwise race the engine's single
        # staging slot (the loser's discard would wipe the winner's
        # staged tree mid-flip).
        self._swap_lock = threading.Lock()
        seed_tree = params if params is not None else self._engine.params
        self._have = leaf_digests(seed_tree)      # guarded-by: _lock
        self._version = int(version if version is not None
                            else self._engine.weights_version)  # guarded-by: _lock
        # Set by a rollback: the forward watch is PINNED — newer steps
        # already in the store are exactly the weights just rolled back
        # from, and the poller re-deploying them within one poll period
        # would silently undo the operator's rollback.  Only an
        # explicit forward swap (SwapRequest / swap_to call) clears it.
        self._hold_at: Optional[int] = None       # guarded-by: _lock
        # Last completed swap's pull accounting (tests + bench read
        # it); replaced wholesale by one atomic assignment, never
        # mutated in place, so readers need no lock.
        self.last_swap: Dict[str, Any] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    @property
    def store(self) -> ShardStore:
        return self._store

    # --- subscription --------------------------------------------------------

    def poll_once(self) -> Optional[int]:
        """One watch tick: swap to the newest intact step newer than
        the running version, if any.  Returns the new version, or None
        when the store holds nothing newer.  Failures are absorbed
        (logged + flight-recorded + counted) — the poll loop must
        outlive every bad upload."""
        with self._lock:
            current = self._version
            held = self._hold_at is not None
        if held:
            # Rolled back: the newer steps in the store are the weights
            # the operator just backed away from — auto-deploy stays
            # paused until an explicit forward swap unpins the watch.
            return None
        step = self._store.newest_intact_step(min_step=current)
        if step is None:
            return None
        try:
            return self.swap_to(step, _from_poll=True)
        except (SwapRejectedError, SwapAbandonedError,
                SwapFailedError) as e:
            logger.warning("hot-swap to step %d not applied (%s); "
                           "still serving version %d", step, e, current)
            return None

    def start(self) -> None:
        """Background subscription: poll every ``poll_s`` seconds until
        :meth:`stop`."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(timeout=self.poll_s):
                try:
                    self.poll_once()
                except ReplicaKilledError:
                    return          # replica dead: nothing left to swap
                except Exception:   # defensive: the watch must survive
                    logger.exception("weight-subscriber poll failed")

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="weight-subscriber")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # --- the swap ------------------------------------------------------------

    def swap_to(self, step: int, *, rollback: bool = False,
                _from_poll: bool = False) -> int:
        """Pull, stage and flip to ``step``.  Forward swaps require a
        newer step; ``rollback=True`` re-points at any intact step (the
        journaled-step rollback path) and PINS the forward watch so the
        poller cannot re-deploy the rolled-back-from steps.  Returns
        the new version.  Serialized per subscriber — a poller tick and
        a controller ``SwapRequest`` cannot race the engine's single
        staging slot.

        Raises :class:`SwapRejectedError` after ``HVD_TPU_SWAP_RETRIES``
        failed verification attempts, :class:`SwapAbandonedError` past
        the deadline, :class:`SwapFailedError`/``ReplicaKilledError``
        when the flip could not run — in every case the old weights are
        still serving and nothing staged survives."""
        with self._swap_lock:
            return self._swap_to_locked(int(step), rollback=rollback,
                                        from_poll=_from_poll)

    def swap_to_info(self, step: int, *,
                     rollback: bool = False) -> Dict[str, Any]:
        """:meth:`swap_to` plus THIS swap's own pull accounting, read
        atomically under the swap lock — a concurrent poller swap
        cannot replace ``last_swap`` between the flip and the read (the
        ``SwapResponse`` wire path uses this)."""
        with self._swap_lock:
            version = self._swap_to_locked(int(step), rollback=rollback,
                                           from_poll=False)
            return dict(self.last_swap, version=version)

    def _swap_to_locked(self, step: int, *, rollback: bool,
                        from_poll: bool) -> int:
        with self._lock:
            current = self._version
            if from_poll and self._hold_at is not None:
                # The pin landed while this poller tick waited on the
                # swap lock (an operator rollback just finished) — the
                # tick must NOT redeploy the rolled-back-from step.
                return current
        if step == current:
            # No-op (the replica is already there — a re-rolled step,
            # or the poller won the race): report it as one, not as the
            # PREVIOUS swap's pull.
            self.last_swap = {"step": step, "pulled_leaves": 0,
                              "total_leaves": 0, "pulled_bytes": 0,
                              "total_bytes": 0, "ms": 0.0,
                              "rollback": rollback, "noop": True}
            with self._lock:
                if rollback:
                    self._hold_at = step       # "hold here" still pins
                elif not from_poll:
                    self._hold_at = None
            return current
        if step < current and not rollback:
            raise SwapRejectedError(
                f"step {step} is older than the running version "
                f"{current}; use rollback for a deliberate re-point")
        t0 = time.monotonic()
        pulled_total = [0]
        try:
            with trace_mod.span("hvd_tpu_swap",
                                args={"step": step, "from": current,
                                      "rollback": rollback}):
                result = retry_call(
                    lambda: self._attempt(step, t0, pulled_total),
                    policy=self._policy,
                    retry_on=(SwapRejectedError,),
                    describe=f"weight swap to step {step}")
        except SwapRejectedError as e:
            self._engine.discard_staged()
            _obs.on_swap("rejected", nbytes=pulled_total[0])
            flight_mod.record("swap_rejected", step=step,
                              error=str(e)[:200])
            raise
        except SwapAbandonedError as e:
            self._engine.discard_staged()
            _obs.on_swap("abandoned", nbytes=pulled_total[0])
            flight_mod.record("swap_abandoned", step=step,
                              error=str(e)[:200])
            raise
        except (SwapFailedError, ReplicaKilledError) as e:
            self._engine.discard_staged()
            _obs.on_swap("failed", nbytes=pulled_total[0])
            flight_mod.record("swap_failed", step=step,
                              error=str(e)[:200])
            raise
        ms = (time.monotonic() - t0) * 1e3
        with self._lock:
            if rollback:
                # Pin the forward watch: the steps above this one are
                # the weights just rolled back from — the poller must
                # not silently un-do the operator (only the next
                # explicit forward swap unpins).
                self._hold_at = step
            elif not from_poll:
                self._hold_at = None
        _obs.on_swap("ok", ms=ms, nbytes=pulled_total[0])
        flight_mod.record("weights_swapped", step=step,
                          from_version=current, rollback=rollback,
                          pulled_bytes=result["pulled_bytes"],
                          ms=round(ms, 3))
        self.last_swap = dict(result, ms=round(ms, 3), rollback=rollback)
        logger.info("weights hot-swapped: version %d -> %d (%d/%d "
                    "leaves pulled, %d bytes, %.1f ms%s)", current,
                    step, result["pulled_leaves"],
                    result["total_leaves"], result["pulled_bytes"], ms,
                    " [rollback]" if rollback else "")
        return step

    def _remaining(self, t0: float) -> float:
        """Budget left before the swap is abandoned (docs/hot_swap.md
        failure matrix: a stalled pull must not pin staged buffers and
        a pending barrier forever).  ``deadline_s=0`` means no
        deadline; the barrier wait still carries a 7-day liveness
        backstop (every wait in this codebase is bounded)."""
        if self.deadline_s <= 0:
            return 7 * 86400.0
        left = self.deadline_s - (time.monotonic() - t0)
        if left <= 0:
            raise SwapAbandonedError(
                f"swap past the {self.deadline_s}s deadline "
                f"(HVD_TPU_SWAP_DEADLINE_S)")
        return left

    def _attempt(self, step: int, t0: float, pulled_total) -> Dict:
        """One pull+stage+flip attempt.  ``SwapRejectedError`` is the
        retryable verdict; everything else is terminal for this swap."""
        self._remaining(t0)
        try:
            manifest = self._store.validate_step(step)
        except ManifestError as e:
            raise SwapRejectedError(f"step {step} not intact: {e}") from e
        with self._lock:
            have = {path: digest for path, (digest, _)
                    in self._have.items()}
        by_file, changed, nbytes = diff_manifest(manifest, have)
        mode = (faults_mod.on_swap_pull()
                if faults_mod._active is not None else None)
        leaves: Dict[str, np.ndarray] = {}
        if by_file:
            try:
                # verify=False: verification happens HERE so the
                # corrupt-shard fault (and any real rot between
                # validate and read) is caught by the same check.
                leaves = self._store.read_leaves(step, by_file, manifest,
                                                 verify=False)
            except (CheckpointCorruptionError, ManifestError,
                    OSError) as e:
                raise SwapRejectedError(
                    f"step {step} unreadable: {e}") from e
            pulled_total[0] += sum(int(a.nbytes) for a in leaves.values())
        if mode == "corrupt-shard" and leaves:
            # Damage AFTER the read, BEFORE verification: the manifest
            # declares the true digests, so the check below MUST reject
            # this pull (the wrong-weights-never drill).
            victim = sorted(leaves)[0]
            bad = np.array(leaves[victim], copy=True)
            flat = bad.reshape(-1).view(np.uint8)
            flat[: min(16, flat.size)] ^= 0xFF
            leaves[victim] = bad
        for leaf_id, arr in leaves.items():
            entry = manifest.entries[leaf_id]
            if leaf_record_digest(entry["path"],
                                  arr).hex() != entry["digest"]:
                raise SwapRejectedError(
                    f"step {step}: leaf {entry['path']} failed digest "
                    f"verification; staged pull discarded")
        tp = int(getattr(self._engine, "tp", 1) or 1)
        shard_bytes = (self._shard_pull(leaves, manifest, tp)
                       if tp > 1 and leaves else None)
        self._remaining(t0)
        tree = self._merge(manifest, leaves)
        self._engine.stage_params(tree, step)
        try:
            version = self._batcher.flip_at_barrier(
                self._engine.commit_staged,
                timeout=self._remaining(t0))
        except TimeoutError as e:
            self._engine.discard_staged()
            raise SwapAbandonedError(str(e)) from e
        except RuntimeError as e:
            if isinstance(e, ReplicaKilledError):
                raise
            self._engine.discard_staged()
            raise SwapFailedError(str(e)) from e
        if version is None:   # defensive: a barrier that lost its result
            raise SwapFailedError("flip reported no version")
        # Commit the leaf cache only once the flip really happened.
        with self._lock:
            new_have: Dict[str, tuple] = {}
            for leaf_id, entry in manifest.entries.items():
                path = entry["path"]
                arr = (leaves[leaf_id] if leaf_id in leaves
                       else self._have[path][1])
                new_have[path] = (entry["digest"], arr)
            self._have = new_have
            self._version = int(version)
        out = {
            "step": step,
            "pulled_leaves": len(changed),
            "total_leaves": len(manifest.entries),
            "pulled_bytes": nbytes,
            "total_bytes": manifest.nbytes,
        }
        if shard_bytes is not None:
            # Per-shard accounting (docs/tp_serving.md): shards pull in
            # parallel, so the replica's store-traffic critical path is
            # the WIDEST shard, not the sum — that max is what
            # ``pulled_bytes`` means on a TP replica.  The tp=1
            # equivalent (the whole manifest diff) stays available as
            # ``pulled_bytes_full`` for the bench's ratio.
            out["tp"] = tp
            out["pulled_bytes_per_shard"] = shard_bytes
            out["pulled_bytes_full"] = nbytes
            out["pulled_bytes"] = max(shard_bytes)
        return out

    def _shard_pull(self, leaves: Dict[str, np.ndarray],
                    manifest: Manifest, tp: int):
        """Carve each pulled leaf into the per-shard slices the
        planner's ownership rule assigns (``plan.tp_owned_slice``) and
        reassemble.  On a multi-host TP replica every shard issues its
        own store read for exactly the slice it owns and the full leaf
        exists again only after the intra-replica all-gather, so the
        slow store moves ~1/tp of the diff per shard; this CPU tier
        reads the local store once, then runs the same carve +
        ``np.concatenate`` reassembly so the ownership path is
        exercised end-to-end and the per-shard byte accounting is real
        slice metadata, not an estimate.  Leaves too small to divide
        are replicated: every shard pulls them whole.  Returns
        per-shard pulled bytes and replaces ``leaves`` entries with the
        reassembled arrays (bit-equal by construction — the digest
        check already passed on the full read)."""
        from ..plan import tp_owned_slice

        per_shard = [0] * tp
        for leaf_id, arr in list(leaves.items()):
            path = manifest.entries[leaf_id]["path"]
            first = tp_owned_slice(path, arr.shape, tp, 0)
            if first is None:
                for r in range(tp):
                    per_shard[r] += int(arr.nbytes)
                continue
            dim = first[0]
            parts = []
            for r in range(tp):
                _, start, stop = tp_owned_slice(path, arr.shape, tp, r)
                idx = [slice(None)] * arr.ndim
                idx[dim] = slice(start, stop)
                part = np.ascontiguousarray(arr[tuple(idx)])
                per_shard[r] += int(part.nbytes)
                parts.append(part)
            leaves[leaf_id] = np.concatenate(parts, axis=dim)
        return per_shard

    def _merge(self, manifest: Manifest,
               leaves: Dict[str, np.ndarray]) -> Any:
        """Full new tree: pulled leaves + the running version's cached
        unchanged arrays, rebuilt into the manifest's skeleton."""
        from ..ckpt.manifest import skeleton_fill

        lookup: Dict[str, np.ndarray] = dict(leaves)
        with self._lock:
            for leaf_id, entry in manifest.entries.items():
                if leaf_id in lookup:
                    continue
                cached = self._have.get(entry["path"])
                if cached is None:
                    # diff said unchanged but we hold no copy — cannot
                    # happen through diff_manifest (absent paths always
                    # count as changed); defend anyway.
                    raise SwapRejectedError(
                        f"leaf {entry['path']} neither pulled nor "
                        f"cached")
                lookup[leaf_id] = cached[1]
        try:
            return skeleton_fill(manifest.skeleton, lookup)
        except (KeyError, TypeError) as e:
            raise SwapRejectedError(
                f"step {manifest.step}: skeleton/entries mismatch: "
                f"{e}") from e
