"""Paged KV-cache serving: block pool, prefix sharing, COW.

The vLLM-style order-of-magnitude lever on serving occupancy (ROADMAP
item 3): instead of one dense ``[slots, S, H, D]`` row per request,
every layer keeps ONE preallocated ``[num_blocks, block, H, D]`` pool
and each request maps its sequence onto a chain of fixed-size token
blocks through a host-side block table.  Identical prompt prefixes
resolve to the same physical blocks (radix-trie prefix index),
divergent writes copy-on-write, and unreferenced prefix blocks are
LRU-evicted under pressure.

Device-side layout and the jitted paged programs live in
:mod:`horovod_tpu.serve.engine`; :class:`BlockPool` (allocation,
refcounts, COW, eviction) and :class:`PrefixIndex` (token-trie lookup)
here are pure host bookkeeping — no jax imports, so the allocator unit
tests run in microseconds.

Knobs: ``HVD_TPU_SERVE_KV`` (``paged``/``dense``),
``HVD_TPU_SERVE_KV_BLOCK`` (tokens per block),
``HVD_TPU_SERVE_KV_BLOCKS`` (pool budget; 0 = auto),
``HVD_TPU_SERVE_SPEC_K`` (speculative draft length) — docs/serving.md.
"""

from .pool import BlockPool, KVPoolExhaustedError, TRASH_BLOCK  # noqa: F401
from .prefix import PrefixIndex  # noqa: F401
