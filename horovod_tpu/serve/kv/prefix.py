"""Radix/trie prefix index over token-ID blocks.

Maps token prefixes to resident KV blocks so identical prompt prefixes
(system prompts shared across millions of requests) resolve to the same
physical blocks.  The trie is keyed by *full-block* token tuples — one
edge per ``block_tokens``-sized chunk — plus per-node *partial* leaves
for prompt tails that do not fill a block.  A partial leaf (or a full
block matched only part-way) can still be shared: the reader uses the
first ``r`` rows of the block and copy-on-writes before its first
divergent write (``pool.BlockPool`` owns that protocol; this module is
pure host-side bookkeeping and never touches device memory).

Ownership registry: every block this index references is registered in
``_owners`` so eviction can unlink it (and its now-unreachable subtree)
in O(subtree).  Blocks whose content duplicates an already-indexed node
are simply not registered — one chain of physical blocks per distinct
prefix.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class _Node:
    __slots__ = ("block", "children", "partials")

    def __init__(self, block: Optional[int]) -> None:
        self.block = block
        # full-block token tuple -> child node
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        # partial token tuple (< block_tokens) -> block id
        self.partials: Dict[Tuple[int, ...], int] = {}


def _common_prefix(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class PrefixIndex:
    """Trie over token-ID blocks; see the module docstring."""

    def __init__(self, block_tokens: int) -> None:
        if block_tokens < 1:
            raise ValueError(f"block_tokens must be >= 1, got {block_tokens}")
        self.block = int(block_tokens)
        self._root = _Node(None)
        # block id -> ("full"|"partial", parent node, edge key, node|None)
        self._owners: Dict[int, Tuple[str, _Node, Tuple[int, ...],
                                      Optional[_Node]]] = {}

    def __len__(self) -> int:
        return len(self._owners)

    def is_indexed(self, block: int) -> bool:
        return block in self._owners

    def leading_key(self, block: int) -> Optional[Tuple[int, ...]]:
        """The root-level edge key (the first ``block_tokens`` token
        IDs) when ``block`` is a depth-0 full block, else None — the
        granularity the fleet's global prefix directory keys on, so an
        eviction of a depth-0 block is exactly the event that
        invalidates a directory entry."""
        info = self._owners.get(block)
        if info is None:
            return None
        kind, parent, key, _ = info
        return key if kind == "full" and parent is self._root else None

    def lookup(self, prompt) -> Tuple[List[int],
                                      Optional[Tuple[int, int]]]:
        """Longest resident match for ``prompt``: a chain of fully
        matched blocks plus, optionally, one ``(block, shared_tokens)``
        partial source whose leading rows extend the match (a partial
        leaf, or a full block whose tokens diverge mid-block)."""
        B = self.block
        node = self._root
        blocks: List[int] = []
        i, n = 0, len(prompt)
        while i + B <= n:
            child = node.children.get(tuple(prompt[i:i + B]))
            if child is None:
                break
            blocks.append(child.block)
            node = child
            i += B
        rest = tuple(prompt[i:])
        best: Optional[Tuple[int, int]] = None
        if rest:
            for key, blk in node.partials.items():
                m = _common_prefix(key, rest)
                if m > 0 and (best is None or m > best[1]):
                    best = (blk, m)
            for key, child in node.children.items():
                m = _common_prefix(key, rest)
                if m > 0 and (best is None or m > best[1]):
                    best = (child.block, m)
        return blocks, best

    def insert(self, prompt, chain: List[int]) -> None:
        """Register ``chain``'s blocks for ``prompt``'s prefix: one
        trie edge per full block, the partial tail (if any) as a
        partial leaf.  Blocks duplicating an existing node (another
        physical copy of the same prefix) stay unregistered — the index
        keeps exactly one chain per distinct prefix."""
        B = self.block
        node = self._root
        i, bi, n = 0, 0, len(prompt)
        while i + B <= n and bi < len(chain):
            key = tuple(prompt[i:i + B])
            child = node.children.get(key)
            if child is None:
                blk = chain[bi]
                if blk in self._owners:   # already indexed elsewhere
                    return
                child = _Node(blk)
                node.children[key] = child
                self._owners[blk] = ("full", node, key, child)
            node = child
            i += B
            bi += 1
        if i < n and bi < len(chain):
            key = tuple(prompt[i:])
            blk = chain[bi]
            if key not in node.partials and blk not in self._owners:
                node.partials[key] = blk
                self._owners[blk] = ("partial", node, key, None)

    def remove_subtree(self, block: int) -> List[int]:
        """Unlink ``block`` from the trie and return every indexed
        block that became unreachable (the block itself plus, for a
        full-block node, its whole subtree — a chain is only reachable
        through its ancestors)."""
        info = self._owners.pop(block, None)
        if info is None:
            return []
        kind, parent, key, node = info
        if kind == "partial":
            parent.partials.pop(key, None)
            return [block]
        parent.children.pop(key, None)
        freed: List[int] = []
        stack = [node]
        while stack:
            cur = stack.pop()
            if cur.block is not None:
                freed.append(cur.block)
                self._owners.pop(cur.block, None)
            for blk in cur.partials.values():
                freed.append(blk)
                self._owners.pop(blk, None)
            stack.extend(cur.children.values())
        return freed
