"""Refcounted block pool: the host-side allocator behind paged KV.

One :class:`BlockPool` manages the block ids of one engine's
preallocated per-layer device pools (``[num_blocks, block, H, D]``;
the device arrays themselves live in the engine — this module never
imports jax).  Responsibilities:

* **Allocation** — block ids come from a free list; block 0 is
  reserved as the *trash block*: unmapped block-table entries point at
  it, and the jitted programs route every invalid write (padding,
  rejected speculative tokens, positions past the cache) there, so the
  compiled code needs no masking lattice around scatter/gather.
* **Refcounting + prefix sharing** — a request's chain in the
  :class:`~horovod_tpu.serve.kv.prefix.PrefixIndex` increfs every
  matched block; full prompt blocks are shared read-only across
  requests.  A *partial* match (the shared block's tail rows will be
  written by the new request's suffix) is **copy-on-write**: the first
  divergent write forces a private copy (``copy_block`` device
  callback), counted in ``cow_copies_total``.
* **LRU eviction** — a released request's blocks stay resident (and
  indexed) while unreferenced, so the next request with the same
  prefix hits; under allocation pressure the least-recently-used
  unreferenced block (and its unreachable subtree) is evicted and its
  prefix entries dropped — a readmitted prefix then *recomputes*,
  never serves stale blocks.  The ``serve:mode=evict`` fault fires at
  the allocation event and force-evicts the whole cache (the seeded
  pressure drill).

Thread safety: the batcher thread drives prefill/decode, but
``release`` arrives from RPC handler threads (cancel paths), so every
mutation runs under one lock.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, List, Optional

from ... import faults as faults_mod
from ...obs import instrument as _obs
from ...utils.logging import get_logger
from .prefix import PrefixIndex

logger = get_logger(__name__)

TRASH_BLOCK = 0


class KVPoolExhaustedError(RuntimeError):
    """Every block is referenced by an active request — the pool was
    sized below ``1 + slots * blocks_per_slot`` (the engine validates
    that floor, so this is unreachable through the public API)."""


class BlockPool:
    """Host-side block allocator + prefix-sharing state for one engine.

    ``table`` is the engine's ``[slots, blocks_per_slot + 1]`` int32
    block-table array (the last column is permanently 0 — the trash
    column the jitted programs clamp invalid positions into); the pool
    keeps it in sync with each slot's chain.  ``copy_block(src, dst)``
    is the engine's jitted device copy (COW and partial-prefix
    admission use it).
    """

    def __init__(self, num_blocks: int, block_tokens: int, table,
                 copy_block, *, heads: Optional[int] = None,
                 tp_degree: int = 1,
                 bytes_per_block: Optional[int] = None) -> None:
        if num_blocks < 2:
            raise ValueError(
                f"block pool needs >= 2 blocks (one is the reserved "
                f"trash block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block = int(block_tokens)
        # Tensor-parallel geometry (docs/tp_serving.md): under TP each
        # shard device holds only ``heads`` (= H/tp) heads of every
        # block, and ``bytes_per_block`` is that per-shard footprint —
        # capacity math, not allocation state.  Block ids, refcounts,
        # the prefix index, and the trash-block discipline are
        # rank-invariant host state: every shard of a replica sees the
        # SAME table, so ``kv_blocks_in_use`` keeps fleet-comparable
        # semantics at any TP degree (a block is in use once per
        # replica, never once per shard).
        self.heads = None if heads is None else int(heads)
        self.tp_degree = int(tp_degree)
        self.bytes_per_block = (None if bytes_per_block is None
                                else int(bytes_per_block))
        self._table = table                       # guarded-by: _lock
        self._copy_block = copy_block
        self._lock = threading.Lock()
        self._free: "collections.deque" = collections.deque(
            range(1, num_blocks))                 # guarded-by: _lock
        self._ref: Dict[int, int] = {}            # guarded-by: _lock
        self._chains: Dict[int, List[int]] = {}   # guarded-by: _lock
        # LRU of unreferenced-but-indexed blocks (eviction candidates).
        self._evictable: "collections.OrderedDict" = \
            collections.OrderedDict()             # guarded-by: _lock
        self._index = PrefixIndex(block_tokens)   # guarded-by: _lock
        # Leading-block keys whose depth-0 block was evicted since the
        # last drain — piggybacked on response frames so the fleet's
        # global prefix directory can drop the entry (bounded: a missed
        # key only costs the directory one stale-route retry).
        self._evicted_keys: "collections.deque" = collections.deque(
            maxlen=256)                           # guarded-by: _lock
        self.evictions_total = 0                  # guarded-by: _lock
        self.cow_copies_total = 0                 # guarded-by: _lock
        self.prefix_hits_total = 0                # guarded-by: _lock
        self.prefix_tokens_shared = 0             # guarded-by: _lock
        from ...analysis import sanitizer as _san

        _san.maybe_register("kv_pool", self)

    # --- read side ----------------------------------------------------------

    def blocks_in_use(self) -> int:
        with self._lock:
            return len(self._ref)

    def probe(self, prompt) -> int:
        """Resident-prefix length for ``prompt`` (no side effects) —
        the batcher's admission-time lookup and the router's affinity
        signal."""
        with self._lock:
            blocks, partial = self._lock_free_match(prompt)
            return self._hit_tokens(len(prompt), blocks, partial)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "kv_blocks_total": self.num_blocks - 1,
                "kv_blocks_in_use": len(self._ref),
                "kv_blocks_cached": len(self._evictable),
                "kv_evictions_total": self.evictions_total,
                "kv_cow_copies_total": self.cow_copies_total,
                "kv_prefix_hits_total": self.prefix_hits_total,
                "kv_prefix_tokens_shared": self.prefix_tokens_shared,
                "heads": self.heads,
                "tp_degree": self.tp_degree,
                "bytes_per_block": self.bytes_per_block,
            }

    def chain_blocks(self, slot: int) -> List[int]:
        """Copy of ``slot``'s live block chain (the KV-migration
        transfer manifest: only these non-trash blocks move)."""
        with self._lock:
            return list(self._chains.get(slot, ()))

    def flush_cache(self) -> int:
        """Drop EVERY cached (unreferenced) block and its prefix-index
        subtree; returns the count freed.  The weight hot-swap flip
        calls this (serve/swap.py): resident KV was computed under the
        OLD weights, and a later prefix hit against it under the new
        weights would emit silently wrong tokens — the one failure mode
        a swap must never trade for its TTFT win.  Evicted leading
        keys land in the normal eviction-notification queue, so the
        fleet's global prefix directory learns too."""
        with self._lock:
            before = self.evictions_total
            self._evict_cached_locked()
            return self.evictions_total - before

    def drain_evicted_keys(self) -> List[tuple]:
        """Leading-block keys evicted since the last drain (consumed:
        the caller owns notifying the prefix directory)."""
        with self._lock:
            out = list(self._evicted_keys)
            self._evicted_keys.clear()
            return out

    # --- request lifecycle --------------------------------------------------

    def bind_imported(self, slot: int, n_blocks: int) -> List[int]:
        """Allocate a fresh ``n_blocks``-long chain for ``slot`` whose
        K/V content arrives over the wire (live KV migration) instead
        of from local prefill.  No prefix match runs — the sender's
        blocks are bound verbatim so the decode continues
        token-identically; ``index_prompt`` afterwards makes the
        imported prefix shareable here like any locally-computed one."""
        if n_blocks < 1:
            raise ValueError(f"n_blocks must be >= 1, got {n_blocks}")
        with self._lock:
            if slot in self._chains:
                raise RuntimeError(f"slot {slot} already has a chain")
            chain: List[int] = []
            try:
                for _ in range(n_blocks):
                    nb = self._alloc_locked()
                    self._ref[nb] = 1
                    chain.append(nb)
            except Exception:
                # Mid-chain exhaustion: blocks already allocated are
                # not yet attached to any chain, so nothing would ever
                # release them — roll them back before propagating or
                # every failed adoption under pressure leaks pool.
                for nb in chain:
                    self._ref.pop(nb, None)
                    self._free.append(nb)
                raise
            self._chains[slot] = chain
            self._write_table_locked(slot)
            self._publish_in_use_locked()
            return chain

    def begin_request(self, slot: int, prompt) -> int:
        """Bind ``slot`` to the longest resident prefix of ``prompt``:
        incref fully matched blocks (shared read-only), COW-copy a
        partial source (its tail rows will be written by the suffix),
        and write the slot's table row.  Returns the number of prefix
        tokens whose K/V need no recompute (always < len(prompt): the
        sampler needs the last prompt token's logits, so at least one
        suffix token always runs)."""
        n = len(prompt)
        with self._lock:
            blocks, partial = self._lock_free_match(prompt)
            chain: List[int] = []
            for b in blocks:
                self._ref[b] = self._ref.get(b, 0) + 1
                self._evictable.pop(b, None)
                chain.append(b)
            plen = 0
            if partial is not None:
                src, plen = partial
                # Copy-on-write at first divergent write — which is the
                # suffix's first token, known to land inside this block,
                # so the private copy happens at admission.
                nb = self._alloc_locked()
                self._copy_block(src, nb)
                self._ref[nb] = 1
                chain.append(nb)
                self.cow_copies_total += 1
                _obs.on_kv_cow_copy()
            self._chains[slot] = chain
            self._write_table_locked(slot)
            hit = len(blocks) * self.block + plen
            if hit > 0:
                self.prefix_hits_total += 1
                self.prefix_tokens_shared += hit
                _obs.on_kv_prefix_hit()
            self._publish_in_use_locked()
            return hit

    def ensure_writable(self, slot: int, start: int, n: int) -> None:
        """Make positions ``[start, start + n)`` of ``slot`` writable:
        allocate chain blocks that do not exist yet and COW any shared
        block in the write range (refcount > 1 means another request
        still reads it).

        A slot with NO chain entry was released concurrently (router
        cancel between the batcher's active-snapshot and this call) —
        allocating for it would create a ghost chain nothing ever
        releases (a permanent block leak), so the call is a no-op: the
        slot's table row is already all-trash and the in-flight decode
        writes harmlessly into block 0."""
        if n <= 0:
            return
        with self._lock:
            chain = self._chains.get(slot)
            if chain is None:
                return
            first = start // self.block
            last = (start + n - 1) // self.block
            if last < len(chain) and all(
                    self._ref.get(chain[j], 0) == 1
                    for j in range(first, last + 1)):
                # Hot-path fast exit: the range is covered by blocks
                # this slot exclusively owns — true for kv_block - 1 of
                # every kv_block decode tokens, so the per-token cost
                # is one lock + one range check, not a table rewrite
                # and gauge publish.
                return
            for j in range(first, last + 1):
                if j < len(chain):
                    b = chain[j]
                    if self._ref.get(b, 0) > 1:
                        nb = self._alloc_locked()
                        self._copy_block(b, nb)
                        self._ref[b] -= 1
                        self._ref[nb] = 1
                        chain[j] = nb
                        self.cow_copies_total += 1
                        _obs.on_kv_cow_copy()
                else:
                    while len(chain) <= j:
                        nb = self._alloc_locked()
                        self._ref[nb] = 1
                        chain.append(nb)
            self._write_table_locked(slot)
            self._publish_in_use_locked()

    def index_prompt(self, slot: int, prompt) -> None:
        """Register ``slot``'s prompt blocks in the prefix index (after
        prefill wrote them): full blocks as trie edges, the partial
        tail as a partial leaf.  Indexed blocks outlive the request —
        release parks them in the LRU instead of freeing."""
        with self._lock:
            chain = self._chains.get(slot)
            if chain:
                self._index.insert(list(prompt), chain)

    def release(self, slot: int) -> None:
        """Drop ``slot``'s references; unreferenced blocks stay
        resident (LRU) while indexed, return to the free list
        otherwise.  The table row is zeroed (everything points at the
        trash block again)."""
        with self._lock:
            chain = self._chains.pop(slot, None) or []
            for b in chain:
                r = self._ref.get(b, 0) - 1
                if r > 0:
                    self._ref[b] = r
                    continue
                self._ref.pop(b, None)
                if self._index.is_indexed(b):
                    self._evictable[b] = True
                    self._evictable.move_to_end(b)
                else:
                    self._free.append(b)
            self._table[slot, :] = TRASH_BLOCK
            self._publish_in_use_locked()

    # --- internals ----------------------------------------------------------

    def _lock_free_match(self, prompt):
        """Index match trimmed so at least one suffix token remains
        (deduplicated between probe and begin_request); caller holds
        the lock."""
        n = len(prompt)
        blocks, partial = self._index.lookup(prompt)
        while blocks and len(blocks) * self.block > n - 1:
            partial = (blocks.pop(), self.block)
        if partial is not None:
            src, plen = partial
            plen = min(plen, n - 1 - len(blocks) * self.block)
            partial = (src, plen) if plen > 0 else None
        return blocks, partial

    def _hit_tokens(self, n: int, blocks, partial) -> int:
        return len(blocks) * self.block + (partial[1] if partial else 0)

    def _write_table_locked(self, slot: int) -> None:
        chain = self._chains.get(slot, [])
        self._table[slot, :len(chain)] = chain  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        self._table[slot, len(chain):] = TRASH_BLOCK  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock

    def _publish_in_use_locked(self) -> None:
        _obs.on_kv_blocks_in_use(len(self._ref))

    def _alloc_locked(self) -> int:
        # The evict fault's event coordinate: one event per allocation.
        if faults_mod._active is not None and faults_mod.on_serve_evict():
            self._evict_cached_locked()
        if not self._free:
            while self._evictable and not self._free:
                b, _ = self._evictable.popitem(last=False)   # oldest
                self._free_subtree_locked(b)
        if not self._free:
            raise KVPoolExhaustedError(
                f"all {self.num_blocks - 1} KV blocks referenced by "
                f"active requests; raise HVD_TPU_SERVE_KV_BLOCKS")
        return self._free.popleft()

    def _evict_cached_locked(self) -> None:
        """Forced pressure (``serve:mode=evict``): drop every cached
        unreferenced block — a readmitted prefix must recompute."""
        while self._evictable:
            b, _ = self._evictable.popitem(last=False)
            self._free_subtree_locked(b)

    def _free_subtree_locked(self, block: int) -> None:
        key = self._index.leading_key(block)
        if key is not None:
            self._evicted_keys.append(key)
        freed = self._index.remove_subtree(block) or [block]
        n = 0
        for d in freed:
            if self._ref.get(d, 0):
                # Unreachable-but-referenced (an active chain still
                # reads it): unlinking from the index is enough — the
                # block frees normally at release.
                continue
            self._ref.pop(d, None)  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
            self._evictable.pop(d, None)
            self._free.append(d)
            n += 1
        self.evictions_total += n  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        if n:
            _obs.on_kv_evictions(n)
