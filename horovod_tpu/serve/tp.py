"""Tensor-parallel replica control plane: rank 0 leads, shard ranks
follow in lockstep (docs/tp_serving.md).

A TP-sharded serving replica is ONE process set behind ONE endpoint:
rank 0 owns admission, the wire, QoS, and swap (its
:class:`~horovod_tpu.serve.server.InferenceServer` / batcher are the
only ones the router ever talks to), and the non-zero ranks run a
lockstep decode loop driven over the same HMAC ``BasicService`` frames
the rest of the control plane uses.  The batcher's dispatch points —
prefill start, decode step, slot release — each emit one
:class:`ShardStepRequest` to every follower *before* rank 0 executes
the same operation locally, so all ranks hold identical host-side
state (block table, prefix index, refcounts) at every step boundary.

Failure semantics are the whole point of the shared frame discipline:
a follower that dies mid-decode (wire error, not-ok answer, or
deadline ``HVD_TPU_SERVE_TP_STEP_TIMEOUT_S``) kills the WHOLE replica
— :class:`ShardFollower` raises, the batcher ``_die``\\ s with reason
``shard_rank_lost``, and the router observes one ``replica_killed``
strike for the replica, exactly as if a TP=1 replica crashed.  A
replica never serves tokens computed by a partial shard group.

Two tiers share this protocol:

* **device tier** — the SPMD engine shards attention heads and MLP
  columns over the MeshPlan ``tensor`` axis inside one program
  (``engine.InferenceEngine(tp=N)``); lockstep frames carry only
  control decisions (which slot starts/steps/releases), never
  activations — XLA's collectives own the math.
* **CPU wire tier** (tests, ``tests/multiproc/``) — each rank drives a
  full engine in lockstep, proving the control-plane properties the
  device tier relies on: rank-invariant host state, per-step token
  digests cross-checked between ranks, and the single-strike failure
  path above, all over real sockets.

Lockstep currently covers the unified-role serving loop (start / step
/ release).  Migrated-KV import and preemption resume stay rank-0
concerns — run TP replicas with ``role="unified"`` and QoS preemption
off; the engine-level SPMD path is unaffected.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from typing import Any, List, Optional, Tuple

from .. import faults as faults_mod
from ..runner.common.network import (BasicClient, BasicService,
                                     DropConnection)
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy
from .engine import SamplingParams, resolved_config

logger = get_logger(__name__)


class ShardLockstepError(RuntimeError):
    """A follower shard rank fell out of lockstep (wire death, not-ok
    answer, digest divergence, or step deadline).  Rank 0's batcher
    converts this into replica death (``shard_rank_lost``) — the
    router's single-strike failover path."""


class ShardStepRequest:
    """One lockstep dispatch from a TP replica's rank 0 to a follower
    shard rank.  ``seq`` is the replica-wide dispatch counter (strictly
    increasing; a follower answering out of order is out of lockstep),
    ``op`` is ``start`` / ``step`` / ``release``, and ``payload``
    carries the op's arguments (``start``: slot, prompt, sampling;
    ``release``: slot; ``step``: empty — the follower decodes every
    active slot, mirroring rank 0's ``engine.step()``)."""

    def __init__(self, seq: int, op: str, payload: Optional[dict] = None):
        self.seq = seq
        self.op = op
        self.payload = payload or {}


class ShardStepResponse:
    """Follower's answer to one :class:`ShardStepRequest`.  ``ok=False``
    (with a diagnostic ``detail`` string) means the shard refused or
    failed the op — rank 0 treats it exactly like a wire death.  A
    successful ``step`` answers ``detail={"digest": ...}``, the sha256
    of the follower's emitted tokens that round — rank 0 may cross-check
    it against its own step digest (:func:`step_digest`) to catch
    silent divergence, not just crashes."""

    def __init__(self, seq: int, ok: bool, detail: Any = None):
        self.seq = seq
        self.ok = ok
        self.detail = detail


def step_digest(tokens: dict) -> str:
    """Order-independent sha256 of one decode round's ``{slot:
    [tokens]}`` — the cross-rank divergence check.  Identical engines
    in lockstep MUST produce identical digests (the token-identity
    oracle, tests/test_tp_serving.py)."""
    items = sorted((int(s), [int(t) for t in ts])
                   for s, ts in tokens.items())
    return hashlib.sha256(repr(items).encode()).hexdigest()


class ShardServer(BasicService):
    """A follower shard rank: one engine behind the HMAC wire,
    executing rank 0's lockstep dispatches.  Host-side KV state (block
    table, prefix index, refcounts, trash discipline) stays
    rank-invariant because every rank applies the same ops in the same
    order — the property the paged pool's shard layout depends on.

    The ``serve`` kill fault's step coordinate fires at the ``step``
    dispatch, mirroring the batcher's decode dispatch: killing a
    follower mid-decode closes the connection with no reply
    (:class:`DropConnection`) — on rank 0 indistinguishable from the
    shard process crashing, which is the drill."""

    def __init__(self, engine, key: bytes, name: str = "serve-shard",
                 host: str = "0.0.0.0", nics: Optional[List[str]] = None):
        super().__init__(name, key, host=host, nics=nics)
        self._engine = engine
        self._lock = threading.Lock()
        self._dead: Optional[str] = None

    def _handle(self, req: Any, client_address) -> Any:
        if isinstance(req, ShardStepRequest):
            return self._dispatch(req)
        return super()._handle(req, client_address)

    def _dispatch(self, req: ShardStepRequest) -> ShardStepResponse:
        with self._lock:
            if self._dead is not None:
                return ShardStepResponse(req.seq, False,
                                         detail=f"shard_dead: {self._dead}")
            try:
                return self._execute(req)
            except DropConnection:
                raise
            except Exception as e:   # defensive: engine bug ≠ hung leader
                return ShardStepResponse(
                    req.seq, False, detail=f"{type(e).__name__}: {e}")

    def _execute(self, req: ShardStepRequest) -> ShardStepResponse:
        if req.op == "start":
            p = req.payload
            sampling = p.get("sampling") or SamplingParams()
            token = self._engine.start(int(p["slot"]),
                                       list(p["prompt"]), sampling)
            return ShardStepResponse(req.seq, True,
                                     detail={"token": int(token)})
        if req.op == "step":
            # The kill fault's event coordinate on follower ranks —
            # same counter the leader's decode dispatch uses, so
            # ``serve:step=N,mode=kill`` kills a shard mid-decode.
            if faults_mod._active is not None \
                    and faults_mod.on_serve_decode():
                self._dead = "injected shard kill mid-decode"
                logger.warning("shard rank dying on the wire: %s",
                               self._dead)
                raise DropConnection()
            tokens = self._engine.step()
            return ShardStepResponse(req.seq, True,
                                     detail={"digest": step_digest(tokens)})
        if req.op == "release":
            self._engine.release(int(req.payload["slot"]))
            return ShardStepResponse(req.seq, True)
        return ShardStepResponse(req.seq, False,
                                 detail=f"unknown_op: {req.op}")


class ShardFollower:
    """Rank 0's handle on the follower shard ranks: the lockstep
    callable the server installs on the batcher
    (``batcher.set_lockstep(ShardFollower(peers, key))``).

    Each dispatch sends one :class:`ShardStepRequest` to EVERY peer,
    single-shot (``RetryPolicy(attempts=1)``, ``idempotent=False``):
    retrying a lockstep op would re-execute its side effect on a shard
    whose ack was merely lost, silently desynchronising the replica —
    any wire ambiguity must surface as :class:`ShardLockstepError` and
    replica death instead.  The per-op deadline is
    ``HVD_TPU_SERVE_TP_STEP_TIMEOUT_S``: a hung shard and a dead shard
    are the same event to the router."""

    def __init__(self, peers: List[Tuple[str, List[Tuple[str, int]]]],
                 key: bytes, *, timeout: Optional[float] = None,
                 probe_timeout: float = 5.0):
        self._timeout = float(timeout if timeout is not None
                              else resolved_config().serve_tp_step_timeout_s)
        self._seq = itertools.count()
        self._clients = [
            BasicClient(name, addresses, key,
                        probe_timeout=probe_timeout,
                        retry_policy=RetryPolicy(attempts=1))
            for name, addresses in peers
        ]

    @property
    def n_shards(self) -> int:
        """Follower count (the replica's TP degree minus rank 0)."""
        return len(self._clients)

    def __call__(self, op: str, payload: Optional[dict] = None) -> list:
        """Dispatch one lockstep op to every follower; returns their
        ``detail`` payloads in peer order.  Raises
        :class:`ShardLockstepError` on ANY wire death, refusal, or
        deadline — partial shard groups never decode."""
        seq = next(self._seq)
        req = ShardStepRequest(seq, op, payload)
        details = []
        for client in self._clients:
            try:
                resp = client.request(req, idempotent=False,
                                      timeout=self._timeout)
            except OSError as e:
                raise ShardLockstepError(
                    f"shard rank {client.name!r} lost at seq {seq} "
                    f"({op}): {e}") from e
            if not isinstance(resp, ShardStepResponse) or not resp.ok:
                detail = getattr(resp, "detail", type(resp).__name__)
                raise ShardLockstepError(
                    f"shard rank {client.name!r} refused seq {seq} "
                    f"({op}): {detail}")
            details.append(resp.detail)
        return details
