"""Continuous-batching scheduler: admission, deadlines, backpressure.

The scheduling loop interleaves prefill and decode over the engine's
slot batch: each :meth:`ContinuousBatcher.step` admits up to
``max_prefill_per_step`` queued requests into free slots (one prefill
each), then runs ONE decode for every active slot.  A long-running
generation therefore never blocks admission, and a fresh request's
TTFT is bounded by one decode's worth of head-of-line blocking — the
continuous-batching property.

Overload policy is **explicit backpressure**: the admission queue is
bounded and a full queue rejects (:class:`QueueFullError`) instead of
queueing unboundedly — at "millions of users" scale an unbounded queue
converts overload into latency collapse and OOM; a reject converts it
into a router-visible signal that shifts load to another replica.

The admission queue is the **weighted-fair QoS scheduler**
(serve/qos/; docs/qos.md): every ``(tenant, class)`` pair is one
stride-scheduled flow, per-tenant token buckets bound sustained
consumption (typed ``BudgetExhaustedError`` rejections), queued
deadline expiry rides a min-heap instead of a queue walk, and an
interactive request about to miss its deadline/TTFT-SLO preempts the
youngest batch generation — its KV parks in the paged prefix cache and
the resumption replays only the non-resident tail, token-identical to
the uninterrupted run.  A single unconfigured flow is exact FIFO, so
default behavior is unchanged.

Fault site ``serve:mode=kill`` fires at the decode dispatch (each
event = one real decode step): the batcher dies mid-decode exactly the
way a preempted replica does, failing queued + in-flight requests so
the router can re-run them on a survivor.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import Dict, List, Optional, Sequence

from .. import faults as faults_mod
from ..obs import flight as flight_mod
from ..obs import instrument as _obs
from ..obs import trace as trace_mod
from ..utils.logging import get_logger
from .engine import (InferenceEngine, PromptTooLongError, SamplingParams,
                     resolved_config)
from .metrics import ServingStats
from .qos import QosPolicy, QosQueue, validate_class
from .qos import preempt as preempt_mod

logger = get_logger(__name__)

_ids = itertools.count()


class QueueFullError(RuntimeError):
    """Admission queue at capacity — reject-when-full backpressure."""


class ReplicaKilledError(RuntimeError):
    """The ``serve:mode=kill`` fault fired mid-decode (or the batcher
    was stopped with requests in flight)."""


class ReplicaDrainingError(RuntimeError):
    """This replica is draining (drain-and-retire lifecycle): in-flight
    work finishes, new admissions answer ``draining`` on the wire so
    the router shifts load elsewhere without striking it."""


@dataclasses.dataclass
class ServeRequest:
    """One in-flight generation; ``done`` fires exactly once, with
    either ``tokens`` complete or ``error`` set."""

    request_id: str
    prompt: List[int]
    sampling: SamplingParams
    deadline: Optional[float] = None       # absolute time.monotonic()
    submitted_at: float = 0.0
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    tokens: List[int] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    # Resident-prefix tokens (admission-time probe, refined to the
    # actual binding at prefill) — the cache-hit/miss signal the bench
    # and the router's affinity layer read.
    prefix_hit_tokens: int = 0
    done: threading.Event = dataclasses.field(
        default_factory=threading.Event)
    # Trace context captured at submit (the server handler's span): the
    # batcher thread reconstructs queued/prefill/decode phase spans
    # against it, so the request's trace crosses the thread handoff.
    trace_ctx: Optional[tuple] = None
    # Disaggregated fleet (serve/fleet/): the decode target the router
    # asked this (prefill) replica to migrate to, the wire-received KV
    # payload on the adopting (decode) side, and the migration outcome
    # the response frame reports.
    migrate_to: Optional[tuple] = None      # (name, [(ip, port), ...])
    kv_import: Optional[tuple] = None       # (manifest, k_blocks, v_blocks)
    migrated: bool = False
    migrate_ms: Optional[float] = None
    # Weight hot-swap (serve/swap.py): the version this request's
    # generation ran under, captured at slot binding — the response
    # must report THIS, not the engine's version at response-build
    # time (a flip can land between the last token and the reply).
    weights_version: Optional[int] = None
    # Multi-tenant QoS (serve/qos/; docs/qos.md): the flow this request
    # rides in the weighted-fair queue, its admission budget charge
    # (refunded pro-rata at completion), and the preemption carry —
    # ``resume_state`` is ``(emitted tokens, engine RNG snapshot)`` set
    # when a batch generation is evicted-and-requeued so resumption
    # replays only the tail, token-identical to the uninterrupted run.
    tenant: str = "default"
    qos_class: str = "standard"
    budget_charged: float = 0.0
    preemptions: int = 0
    resume_state: Optional[tuple] = None

    def finish(self, error: Optional[str] = None) -> None:
        if self.done.is_set():
            return
        self.error = error
        self.finished_at = time.monotonic()
        self.done.set()


class ContinuousBatcher:
    """Slot scheduler over one :class:`InferenceEngine`.

    Drive it synchronously (:meth:`step`, deterministic — what the
    tests and the bench do) or as a daemon thread (:meth:`start` /
    :meth:`stop` — what the server does).
    """

    def __init__(self, engine: InferenceEngine, *,
                 max_queue: Optional[int] = None,
                 max_prefill_per_step: int = 1,
                 default_deadline_s: Optional[float] = None,
                 role: Optional[str] = None,
                 qos_policy: Optional[QosPolicy] = None,
                 qos_preempt: Optional[bool] = None,
                 qos_slo_ttft_ms: Optional[float] = None):
        cfg = resolved_config()
        self.engine = engine
        self.max_queue = int(max_queue if max_queue is not None
                             else cfg.serve_queue_depth)
        self.max_prefill_per_step = max(1, max_prefill_per_step)
        self.default_deadline_s = (
            default_deadline_s if default_deadline_s is not None
            else cfg.serve_deadline_seconds)
        self.max_new_tokens_cap = cfg.serve_max_new_tokens
        # Fleet role (serve/fleet/): a prefill replica hands each
        # request's KV to its decode target after the first token; the
        # role is a scheduling policy, not a capability — every replica
        # can run a full generation (the recompute fallback path).
        self.role = (role or cfg.fleet_role).lower()
        if self.role not in ("prefill", "decode", "unified"):
            raise ValueError(f"unknown fleet role {self.role!r}; "
                             f"expected prefill|decode|unified")
        self._migrator = None    # set by the server on prefill replicas
        self._lockstep = None    # set on TP replica leaders (serve/tp.py)
        self.stats = ServingStats(weights_version=engine.weights_version)
        # Multi-tenant QoS (serve/qos/): flow weights + tenant budgets
        # from the HVD_TPU_QOS_* knobs; the admission queue is the
        # weighted-fair scheduler (a single unconfigured flow is exact
        # FIFO, so default behavior is unchanged), and deadline-aware
        # preemption is gated on the paged cache — eviction is only
        # cheap when the KV survives in the prefix index.
        self._policy = (qos_policy if qos_policy is not None
                        else QosPolicy.from_config(cfg))
        self._preempt_enabled = (
            bool(qos_preempt if qos_preempt is not None
                 else cfg.qos_preempt)
            and engine.kv_mode == "paged")
        # Interactive TTFT SLO (HVD_TPU_QOS_SLO_TTFT_MS): with it set,
        # preemption fires aggressively enough to land interactive
        # first tokens inside the budget; 0 = deadline feasibility only.
        self._slo_ttft_s = float(
            qos_slo_ttft_ms if qos_slo_ttft_ms is not None
            else cfg.qos_slo_ttft_ms) / 1e3
        self._lock = threading.Lock()
        self._queue: QosQueue = QosQueue(self._policy)  # guarded-by: _lock
        self._slots: Dict[int, ServeRequest] = {}    # guarded-by: _lock
        self._killed: Optional[str] = None           # guarded-by: _lock
        self._draining = False                       # guarded-by: _lock
        # Weight hot-swap barrier (serve/swap.py): a pending flip holds
        # admission, lets in-flight generations run dry, then runs at
        # the step boundary — no request ever sees mixed weights.
        self._pending_flip: Optional[tuple] = None   # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._wake = threading.Event()

    # --- admission ----------------------------------------------------------

    @property
    def dead(self) -> bool:
        # Locked read: consulted from RPC handler + router threads
        # while _die() may be flipping it (an hvdsan read-site catch).
        with self._lock:
            return self._killed is not None

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self) -> None:
        """Enter the drain-and-retire lifecycle: stop admitting, let
        queued + in-flight work finish (the fleet controller retires
        the replica once it runs dry)."""
        with self._lock:
            if self._draining or self._killed is not None:
                return
            self._draining = True
        logger.info("serving replica draining (no new admissions)")

    def undrain(self) -> None:
        """Cancel a drain and admit again — the abandon path when a
        retire turns out impossible (e.g. the fleet's last replica): a
        replica left draining with no peers would starve the fleet
        forever."""
        with self._lock:
            if not self._draining:
                return
            self._draining = False
        logger.info("serving replica drain cancelled (admitting again)")

    # --- weight hot-swap barrier (serve/swap.py; docs/hot_swap.md) ----------

    def flip_at_barrier(self, fn, timeout: float = 60.0):
        """Run ``fn`` (the engine's ``commit_staged``) at the next step
        boundary with NO generation in flight, and block until it ran.

        While the flip is pending the scheduler admits nothing (queued
        requests wait — backpressure, never loss) and keeps decoding,
        so in-flight generations finish on the version they started on;
        the moment the slots run dry the flip executes between decode
        bursts and admission resumes.  Returns ``fn``'s result; raises
        ``TimeoutError`` when the slots never drained inside
        ``timeout`` (the flip is withdrawn — old weights keep serving)
        and ``ReplicaKilledError`` when the replica died instead of
        flipping."""
        with self._lock:
            if self._killed is not None:
                raise ReplicaKilledError(self._killed)
            if self._pending_flip is not None:
                raise RuntimeError("a weight flip is already pending on "
                                   "this replica")
            flip = (fn, threading.Event(), {})
            self._pending_flip = flip
        self._wake.set()
        _, event, holder = flip
        if not event.wait(timeout=timeout):
            with self._lock:
                withdrawn = self._pending_flip is flip
                if withdrawn:
                    self._pending_flip = None
            if withdrawn:
                raise TimeoutError(
                    f"swap barrier not reached within {timeout}s "
                    f"(in-flight generations never drained)")
            # The flip was CLAIMED between our wait timing out and the
            # withdraw — it will run (or die); a completed flip must
            # not read as a timeout, and an empty holder must never
            # read as success (int(None) downstream).
            if not event.wait(timeout=60.0):
                raise TimeoutError(
                    "flip claimed at the barrier but still executing "
                    "after 60s")
        if "error" in holder:
            if holder["error"].startswith("flip_failed"):
                raise RuntimeError(holder["error"])
            raise ReplicaKilledError(holder["error"])
        return holder.get("result")

    def _run_flip(self, flip) -> None:
        """Execute a CLAIMED flip (batcher thread, slots empty, already
        removed from ``_pending_flip`` — a timed-out waiter can no
        longer withdraw it).  The ``swap:mode=kill-mid-flip`` fault
        fires here — the last instant before the atomic reference swap,
        so a killed replica is still on exactly one version and fails
        over like any other death."""
        fn, event, holder = flip
        if faults_mod._active is not None and faults_mod.on_swap_flip():
            reason = "injected replica kill mid-flip"
            # The flip is already claimed, so _die cannot see it — the
            # waiter learns here, before the death unwinds.
            holder.setdefault("error", f"replica_killed: {reason}")
            event.set()
            self._die(reason)
            raise ReplicaKilledError(reason)
        try:
            holder["result"] = fn()
            if isinstance(holder["result"], int):
                self.stats.set_weights_version(holder["result"])
        except Exception as e:   # defensive: a failed flip keeps old weights
            holder["error"] = f"flip_failed: {e}"
            logger.exception("weight flip failed; serving continues on "
                             "the old version")
        finally:
            event.set()

    def set_migrator(self, migrator) -> None:
        """Install the prefill→decode handoff callable
        (``migrator(engine, slot, req) -> bool``; the server wires
        ``serve/fleet/migration.migrate_slot`` here on prefill
        replicas)."""
        self._migrator = migrator

    def set_lockstep(self, lockstep) -> None:
        """Install the TP follower-dispatch callable
        (``lockstep(op, payload) -> list``; rank 0 of a tensor-parallel
        replica wires :class:`~horovod_tpu.serve.tp.ShardFollower`
        here).  Every prefill start, decode step, and slot release is
        dispatched to the follower shard ranks BEFORE the local engine
        executes it, so all ranks hold identical host-side KV state at
        each step boundary; any lockstep failure kills the whole
        replica (``shard_rank_lost``) — docs/tp_serving.md."""
        self._lockstep = lockstep

    def _lockstep_dispatch(self, op: str, payload=None) -> None:
        """One follower dispatch; a lost/refusing/hung shard rank is
        replica death — a partial shard group must never keep serving
        (the router re-runs the failed requests on a survivor)."""
        try:
            self._lockstep(op, payload)
        except Exception as e:
            reason = f"shard_rank_lost: {e}"
            self._die(reason)
            raise ReplicaKilledError(reason) from e

    def submit(self, prompt: Sequence[int],
               sampling: Optional[SamplingParams] = None,
               request_id: Optional[str] = None,
               deadline_s: Optional[float] = None,
               migrate_to: Optional[tuple] = None,
               tenant: Optional[str] = None,
               qos_class: Optional[str] = None) -> ServeRequest:
        """Enqueue one generation.  Raises :class:`QueueFullError` at
        capacity, :class:`ReplicaKilledError` on a dead replica,
        :class:`ReplicaDrainingError` on a draining one and
        :class:`~horovod_tpu.serve.qos.BudgetExhaustedError` when the
        tenant's token bucket cannot cover the request; oversized
        prompts raise :class:`PromptTooLongError` up front (admitting
        them would waste a slot to fail later).  ``migrate_to`` is the
        decode target a prefill-role replica hands this request's KV to
        after the first token; ``tenant``/``qos_class`` place the
        request in the weighted-fair scheduler (docs/qos.md)."""
        sampling = sampling or SamplingParams()
        qos_class = validate_class(qos_class)
        if sampling.max_new_tokens > self.max_new_tokens_cap:
            sampling = dataclasses.replace(
                sampling, max_new_tokens=self.max_new_tokens_cap)
        # PromptTooLongError / out-of-vocab ValueError early — a poison
        # prompt must never reach the shared KV pool (engine docstring).
        self.engine.check_prompt_tokens(prompt)
        # Admission-time prefix lookup: how much of this prompt's K/V
        # is already resident (serve/kv/).  Recorded before queueing so
        # backpressure decisions and the bench see the signal even for
        # requests that later expire; the binding at prefill refines it.
        hit = self.engine.prefix_probe(prompt)
        limit = (deadline_s if deadline_s is not None
                 else self.default_deadline_s)
        req = ServeRequest(
            request_id=request_id or f"req-{next(_ids)}",
            prompt=list(prompt), sampling=sampling,
            deadline=(time.monotonic() + limit) if limit and limit > 0
            else None,
            submitted_at=time.monotonic(),
            prefix_hit_tokens=hit,
            trace_ctx=trace_mod.current(),
            migrate_to=migrate_to,
            tenant=(tenant or "default"), qos_class=qos_class)
        self._admit(req)
        return req

    def adopt(self, manifest: dict, k_blocks, v_blocks) -> ServeRequest:
        """Adopt a migrated request (serve/fleet/migration.py): the
        digest-verified KV payload is queued like a submission, and the
        batcher thread binds it into the pool in place of a prefill —
        generation continues token-identically from the sender's
        state.  Same admission contract as :meth:`submit` (queue bound,
        killed/draining refusal, poison-prompt rejection)."""
        s = manifest["sampling"]
        sampling = SamplingParams(
            max_new_tokens=int(s["max_new_tokens"]),
            temperature=float(s["temperature"]), top_k=int(s["top_k"]),
            stop_token=s["stop_token"], spec=bool(s["spec"]))
        prompt = [int(t) for t in manifest["prompt"]]
        if self.engine.kv_mode != "paged":
            raise ValueError("KV adoption requires the paged cache "
                             "(HVD_TPU_SERVE_KV=paged)")
        # Poison defense on the receiving side too: the sender already
        # validated, but a pool-poisoning prompt must die at EVERY
        # admission boundary, not only the first.
        self.engine.check_prompt_tokens(prompt)
        # Mixed-version guard (serve/swap.py): imported KV was computed
        # under the sender's weights; continuing it under different
        # ones would be silently wrong.  The refusal sends the request
        # back to the sender's pristine KV + matching weights.
        sender_v = manifest.get("weights_version")
        if sender_v is not None and int(sender_v) != \
                self.engine.weights_version:
            raise ValueError(
                f"version_mismatch: migrated KV from weights version "
                f"{sender_v}, this replica serves "
                f"{self.engine.weights_version}")
        if not manifest.get("tokens"):
            raise ValueError("migration manifest carries no emitted "
                             "tokens — nothing to continue from")
        limit = manifest.get("deadline_s")
        now = time.monotonic()
        req = ServeRequest(
            request_id=manifest["request_id"], prompt=prompt,
            sampling=sampling,
            deadline=(now + limit) if limit and limit > 0 else None,
            submitted_at=now,
            trace_ctx=trace_mod.current(),
            kv_import=(manifest, k_blocks, v_blocks),
            tenant=manifest.get("tenant", "default"),
            qos_class=validate_class(manifest.get("qos_class")))
        self._admit(req)
        return req

    def _admit(self, req: ServeRequest) -> None:
        # Tenant budget BEFORE the queue bound: an over-budget request
        # must see its typed rejection (retry_after), not be misread as
        # replica backpressure.  The charge is the reservation — prompt
        # plus the generation cap — with the unused part refunded at
        # completion; the `qos:mode=flood` fault waives it (one tenant
        # flooding past its budget, the WFQ-fairness drill).
        need = len(req.prompt) + req.sampling.max_new_tokens
        if faults_mod._active is not None and faults_mod.on_qos_admit():
            need = 0
        if need > 0:
            try:
                req.budget_charged = self._policy.charge(req.tenant, need)
            except Exception:
                self.stats.record_budget_rejected(req.tenant)
                _obs.on_qos_budget_reject(req.tenant)
                raise
        try:
            with self._lock:
                if self._killed is not None:
                    raise ReplicaKilledError(self._killed)
                if self._draining:
                    raise ReplicaDrainingError(
                        "replica draining (no new admissions)")
                if len(self._queue) >= self.max_queue:
                    self.stats.record_rejected()
                    raise QueueFullError(
                        f"admission queue full ({self.max_queue} "
                        f"waiting)")
                self._queue.push(req)
        except Exception:
            # A refused admission must hand the reservation back — the
            # tokens were never going to be served.
            self._policy.refund(req.tenant, req.budget_charged)
            req.budget_charged = 0.0
            raise
        self._wake.set()

    def cancel(self, request_id: str) -> bool:
        """Abandon a queued or in-flight request (router failover: the
        caller re-ran it elsewhere, so finishing it here would only
        burn a slot producing an answer nobody reads).  Returns True
        when something was cancelled."""
        target_slot = None
        with self._lock:
            req = self._queue.remove(request_id)
            if req is None:
                for slot, r in self._slots.items():
                    if r.request_id == request_id:
                        target_slot, req = slot, r
                        break
                if target_slot is not None:
                    del self._slots[target_slot]
        if req is None:
            return False
        if target_slot is not None:
            if self._lockstep is not None:
                self._lockstep_dispatch("release", {"slot": target_slot})
            self.engine.release(target_slot)
        self._settle_budget(req)
        req.finish(error="cancelled")
        return True

    # --- scheduling ---------------------------------------------------------

    def _expire(self, now: float) -> None:
        # Queued expiry is the deadline min-heap (O(expired · log n) —
        # one peek when nothing expired, never a queue walk); in-flight
        # expiry stays a scan, bounded by max_slots.
        with self._lock:
            queued = self._queue.pop_expired(now)
            running = [(s, r) for s, r in self._slots.items()
                       if r.deadline is not None and now > r.deadline]
            for s, r in running:
                del self._slots[s]
                self.engine.release(s)
        if self._lockstep is not None:
            # Outside the lock (_die on a lost shard needs it); the
            # batcher thread owns slot reuse, so the release dispatch
            # still precedes any new "start" for these slots.
            for s, _ in running:
                self._lockstep_dispatch("release", {"slot": s})
        for r in queued + [r for _, r in running]:
            self._settle_budget(r)
            self.stats.record_expired(r.qos_class)
            r.finish(error="deadline_exceeded")

    def _settle_budget(self, req: ServeRequest) -> None:
        """Refund the unused part of the admission reservation exactly
        once (any terminal path: completion, expiry, cancel, death)."""
        charged, req.budget_charged = req.budget_charged, 0.0
        if charged > 0:
            used = len(req.prompt) + len(req.tokens)
            self._policy.refund(req.tenant, charged - used)

    def _record_phase(self, req: ServeRequest, name: str,
                      start_mono: float, end_mono: float, **args) -> None:
        """One reconstructed phase span on the request's trace (the
        batcher thread has no ambient context — phases are parented to
        the context captured at submit, with monotonic timestamps
        re-anchored onto the span clock)."""
        if req.trace_ctx is None or not trace_mod.enabled():
            return
        now_us, now_mono = trace_mod.now_us(), time.monotonic()
        start_us = now_us - (now_mono - start_mono) * 1e6
        trace_mod.record_span(name, parent=req.trace_ctx,
                              start_us=start_us,
                              dur_us=(end_mono - start_mono) * 1e6,
                              args=args or None)

    def _finish_slot(self, slot: int, req: ServeRequest) -> None:
        with self._lock:
            self._slots.pop(slot, None)
        if self._lockstep is not None:
            # TP lockstep: followers free the slot before the leader —
            # the next admission dispatches a "start" for it, and a
            # follower whose slot is still active would refuse it.
            self._lockstep_dispatch("release", {"slot": slot})
        self.engine.release(slot)
        # Stats and trace record BEFORE `done` fires: the instant
        # finish() unblocks the waiting RPC handler, a client can get
        # its response and scrape stats — a request its own caller sees
        # completed must already be counted (the drain test's
        # requests_completed race).
        end = time.monotonic()
        if req.first_token_at is not None:
            # The decode phase of this request's trace: first token to
            # completion (what dominates long generations' latency —
            # the critical-path report should name it).
            self._record_phase(req, "hvd_tpu_serve_decode",
                               req.first_token_at, end,
                               tokens=len(req.tokens))
        self._settle_budget(req)
        self.stats.record_request(
            ttft_s=(req.first_token_at or end) - req.submitted_at,
            n_tokens=len(req.tokens),
            total_s=end - req.submitted_at,
            qos_class=req.qos_class, tenant=req.tenant)
        req.finish()

    def _emit(self, slot: int, req: ServeRequest, token: int,
              now: float, check_full: bool = True) -> None:
        if req.done.is_set():
            return   # cancelled/expired concurrently: drop the token
        if req.first_token_at is None:
            req.first_token_at = now
        req.tokens.append(token)
        stop = req.sampling.stop_token
        # ``check_full`` is False for all but the last token of a
        # speculative burst: the engine advanced the slot position past
        # the whole burst, but every emitted token except the last had
        # cache room by construction (acceptance is capped there).
        if (len(req.tokens) >= req.sampling.max_new_tokens
                or (stop is not None and token == stop)
                or (check_full and self.engine.slot_full(slot))):
            self._finish_slot(slot, req)

    def _prefill_into(self, slot: int, req: ServeRequest) -> int:
        """Bring ``req`` into ``slot`` — local prefill, migrated-KV
        import, or preemption resume — and emit its first token(s);
        returns the tokens emitted.  The caller already placed ``req``
        in ``self._slots[slot]``."""
        emitted = 0
        prefill_t0 = time.monotonic()
        imported = req.kv_import is not None
        resumed = req.resume_state is not None
        if resumed and req.weights_version is not None and \
                req.weights_version != self.engine.weights_version:
            # Mixed-version guard (docs/hot_swap.md): the tokens
            # emitted before the preemption came from the weights the
            # replica served THEN; a hot-swap flip landed while the
            # request sat requeued, and resuming under the new weights
            # would splice two models' outputs into one response.
            # Restart from scratch on the current version — the client
            # sees only the final, single-version stream (the flip
            # already flushed the parked KV, so nothing stale is
            # reused either way).
            req.resume_state = None
            req.tokens.clear()
            resumed = False
        if self._lockstep is not None and not imported and not resumed:
            # TP lockstep: followers prefill the same slot before the
            # leader does — a lost shard here kills the replica, never
            # just this request (partial shard groups don't serve).
            self._lockstep_dispatch("start", {
                "slot": slot, "prompt": list(req.prompt),
                "sampling": req.sampling})
        try:
            if imported:
                # Migrated-in request: bind the wire-received KV in
                # place of a prefill; the sender's emitted tokens
                # replay below so the token stream is seamless.
                manifest, kb, vb = req.kv_import
                req.kv_import = None    # payload freed after binding
                # Re-check the version at BIND time: a weight flip
                # between adoption and this pop would bind KV from
                # the old weights under the new ones — the
                # import_failed answer routes the request to a
                # recompute instead (never wrong tokens).
                sender_v = manifest.get("weights_version")
                if sender_v is not None and int(sender_v) != \
                        self.engine.weights_version:
                    raise ValueError(
                        f"version_mismatch at bind: KV from "
                        f"weights version {sender_v}, replica now "
                        f"serves {self.engine.weights_version}")
                tokens = [int(t) for t in manifest["tokens"]]
                self.engine.import_slot_kv(
                    slot, req.prompt, kb, vb, tokens[-1],
                    req.sampling, rng=manifest.get("rng"))
            elif resumed:
                # Preempted generation coming back (serve/qos/): the
                # prefix cache covers what survived, the engine
                # recomputes the tail, and nothing already emitted is
                # re-sampled — decode continues where it stopped.
                prev, rng = req.resume_state
                req.resume_state = None
                req.prefix_hit_tokens = self.engine.resume_slot(
                    slot, req.prompt, prev, req.sampling, rng=rng)
                tokens = []
            else:
                tokens = [self.engine.start(slot, req.prompt,
                                            req.sampling)]
        except Exception as e:   # defensive: engine bug ≠ wedged slot
            with self._lock:
                self._slots.pop(slot, None)
            if self._lockstep is not None and not imported and not resumed:
                # Followers already prefilled this slot; free it there
                # too or the next admission's "start" finds it active.
                self._lockstep_dispatch("release", {"slot": slot})
            self.engine.release(slot)
            self._settle_budget(req)
            self.stats.record_failed(req.qos_class)
            req.finish(error=(f"import_failed: {e}" if imported
                              else f"prefill_failed: {e}"))
            return 0
        req.weights_version = self.engine.weights_version
        if not imported and not resumed:
            req.prefix_hit_tokens = self.engine.prefix_hit_tokens(slot)
            self.stats.record_prefix(req.prefix_hit_tokens > 0)
        self._record_phase(req, "hvd_tpu_serve_queued",
                           req.submitted_at, prefill_t0)
        self._record_phase(req, "hvd_tpu_serve_prefill", prefill_t0,
                           time.monotonic(),
                           prompt_len=len(req.prompt), slot=slot,
                           prefix_hit=req.prefix_hit_tokens,
                           imported=imported, resumed=resumed)
        if req.done.is_set():
            # Cancelled/expired between admission and prefill
            # completion: cancel() found no active slot to release
            # (engine.start had not activated it yet), so release
            # here or the slot leaks as a ghost forever.
            with self._lock:
                self._slots.pop(slot, None)
            if self._lockstep is not None and not imported and not resumed:
                self._lockstep_dispatch("release", {"slot": slot})
            self.engine.release(slot)
            return emitted
        now2 = time.monotonic()
        for j, token in enumerate(tokens):
            emitted += 1
            self._emit(slot, req, token, now2,
                       check_full=(j == len(tokens) - 1))
            if req.done.is_set():
                break
        if (not imported and not resumed and self.role == "prefill"
                and self._migrator is not None
                and req.migrate_to is not None
                and not req.done.is_set()):
            self._handoff(slot, req)
        return emitted

    def _maybe_preempt(self, now: float) -> int:
        """Deadline-aware preemption (serve/qos/preempt.py): when a
        queued interactive request would miss its deadline waiting for
        a natural slot release, evict the youngest batch generation —
        its KV drops to the prefix cache, not the floor — requeue it
        with resume state, and prefill the interactive request into
        the freed slot NOW.  Returns tokens emitted (the interactive
        prefill's first token)."""
        if not self._preempt_enabled:
            return 0
        with self._lock:
            if self.engine.free_slots():
                return 0    # a slot is free: ordinary admission wins
            urgent = self._queue.urgent("interactive")
            if urgent is None:
                return 0
            active = dict(self._slots)
        _, ireq = urgent
        est = preempt_mod.estimate_slot_wait_s(
            active, self.stats.tpot_estimate_s())
        if not preempt_mod.should_preempt(ireq, now, est,
                                          self._slo_ttft_s):
            return 0
        eligible = {s: r for s, r in active.items()
                    if self.engine.can_resume(len(r.prompt),
                                              len(r.tokens))}
        victim = preempt_mod.pick_victim(eligible)
        if victim is None:
            return 0    # nothing preemptible: the deadline may expire
        slot, vreq = victim
        with self._lock:
            # Re-validate both ends under the lock: the victim may have
            # finished and the interactive request may have been
            # cancelled/dispatched since the snapshot.
            if self._slots.get(slot) is not vreq:
                return 0
            if self._queue.remove(ireq.request_id) is None:
                return 0
            self._slots[slot] = ireq
        rng = self.engine.preempt_slot(slot, vreq.prompt, vreq.tokens)
        vreq.resume_state = (list(vreq.tokens), rng)
        vreq.preemptions += 1
        self.stats.record_preempted()
        _obs.on_qos_preempt()
        flight_mod.record("qos_preempted", request=vreq.request_id,
                          emitted=len(vreq.tokens),
                          for_request=ireq.request_id)
        logger.info("preempted batch request %s (%d tokens in) for "
                    "interactive %s", vreq.request_id, len(vreq.tokens),
                    ireq.request_id)
        # Requeue bypasses the admission bound and the budget charge:
        # the victim's tokens are already paid for, and dropping
        # preempted work would turn a scheduling decision into loss.
        with self._lock:
            self._queue.push(vreq)
        return self._prefill_into(slot, ireq)

    def step(self) -> int:
        """One scheduling iteration; returns the number of tokens
        emitted (0 = idle)."""
        with self._lock:
            if self._killed is not None:
                raise ReplicaKilledError(self._killed)
            flip = self._pending_flip
        now = time.monotonic()
        self._expire(now)
        emitted = 0
        if flip is not None:
            # Swap barrier: admission holds (queued requests WAIT — a
            # swap never drops work), in-flight generations keep
            # decoding below; the moment the slots ran dry the flip
            # runs between decode bursts and admission resumes in this
            # same step.  The flip is CLAIMED under the lock: a waiter
            # whose timeout withdrew it concurrently must never see it
            # commit afterwards (it already reported the swap abandoned
            # and discarded the staged params).
            claimed = None
            with self._lock:
                if not self._slots and self._pending_flip is flip:
                    claimed = flip
                    self._pending_flip = None
            if claimed is not None:
                self._run_flip(claimed)
                flip = None
        # Deadline-aware preemption (serve/qos/): before ordinary
        # admission, an interactive request that would miss its
        # deadline waiting for a natural slot release evicts the
        # youngest batch generation and takes its slot this same step.
        if flip is None:
            emitted += self._maybe_preempt(now)
        # Admit: bounded prefills per step keep decode cadence for the
        # already-running requests (prefill is the expensive phase).
        # Pops come out in weighted-fair order (serve/qos/sched.py).
        for _ in range(self.max_prefill_per_step if flip is None else 0):
            with self._lock:
                free = self.engine.free_slots()
                if not free or not len(self._queue):
                    break
                req = self._queue.pop()
                if req is None:
                    break
                slot = free[0]
                self._slots[slot] = req
            emitted += self._prefill_into(slot, req)
        # Decode: one token for every active request.  The kill fault's
        # event coordinate is this dispatch — guarded so an unarmed
        # plan costs one attribute read.
        with self._lock:
            active = dict(self._slots)
        if active:
            if faults_mod._active is not None and faults_mod.on_serve_decode():
                reason = "injected replica kill mid-decode"
                self._die(reason)
                raise ReplicaKilledError(reason)
            if self._lockstep is not None:
                # TP lockstep: followers decode this round first; their
                # acks carry token digests (serve/tp.py::step_digest)
                # the leader could cross-check — a wire death or
                # deadline here is replica death, single-strike.
                self._lockstep_dispatch("step", {})
            tokens = self.engine.step()
            now = time.monotonic()
            for slot, toks in tokens.items():
                req = active.get(slot)
                if req is None:
                    continue
                # A speculative burst emits several tokens; a finish
                # condition (stop token, max_new_tokens) mid-burst
                # drops the remainder — exactly what plain greedy
                # decode would never have produced.
                for j, token in enumerate(toks):
                    emitted += 1
                    self._emit(slot, req, token, now,
                               check_full=(j == len(toks) - 1))
                    if req.done.is_set():
                        break
        with self._lock:
            self.stats.record_step(active=len(self._slots),
                                   slots=self.engine.max_slots,
                                   queued=len(self._queue))
        return emitted

    def _handoff(self, slot: int, req: ServeRequest) -> None:
        """Prefill→decode handoff: stream ``slot``'s KV to the
        request's decode target, then free the slot and answer the
        router with the migration outcome.  A failed transfer (wire
        death, digest rejection, busy/draining receiver) falls back to
        decoding HERE — the local KV is pristine (a corrupt fault only
        damaged the wire copy), so the request finishes with exactly
        the right tokens and only the disaggregation economics are
        lost.

        The ``serve:mode=kill`` fault's step-dispatch coordinate fires
        at this dispatch too: prefill replicas never dispatch decode,
        so the handoff is their step event — ``serve:step=N,mode=kill``
        kills a prefill replica mid-migration (the fleet failover
        drill)."""
        if faults_mod._active is not None and faults_mod.on_serve_decode():
            reason = "injected replica kill mid-migration"
            self._die(reason)
            raise ReplicaKilledError(reason)
        try:
            ok = self._migrator(self.engine, slot, req)
        except Exception as e:
            logger.warning("KV handoff of %s failed (%s); decoding "
                           "locally", req.request_id, e)
            ok = False
        if not ok:
            return   # local fallback: the slot keeps decoding here
        req.migrated = True
        self._finish_slot(slot, req)

    def _die(self, reason: str) -> None:
        """Fail every queued + in-flight request exactly once and
        refuse new work — replica death as the router observes it."""
        with self._lock:
            self._killed = reason
            pending = self._queue.drain()
            running = list(self._slots.values())
            self._slots.clear()
            flip, self._pending_flip = self._pending_flip, None
        if flip is not None:
            # A subscriber blocked on the barrier must not hang until
            # its timeout on a replica that already died.
            flip[2].setdefault("error", f"replica_killed: {reason}")
            flip[1].set()
        for req in pending + running:
            self._settle_budget(req)
            self.stats.record_failed(req.qos_class)
            req.finish(error="replica_killed")
        n = len(pending) + len(running)
        flight_mod.record("replica_died", reason=reason, failed=n)
        if n:
            logger.warning("serving replica died: %s (%d request(s) "
                           "failed back to the router)", reason, n)
        else:
            logger.info("serving replica retired: %s", reason)

    # --- thread driver ------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    busy = self.step()
                except ReplicaKilledError:
                    return
                except Exception:
                    logger.exception("batcher step failed; replica down")
                    self._die("batcher step raised")
                    return
                if not busy:
                    self._wake.wait(timeout=0.005)
                    self._wake.clear()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serve-batcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            killed = self._killed
        if killed is None:
            self._die("replica stopped")

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def snapshot(self) -> Dict:
        # ``weights_version`` rides the stats snapshot: seeded from the
        # engine at construction, advanced only at the flip — one
        # consistent source, no shadow overwrite here.
        snap = self.stats.snapshot()
        snap.update(self.engine.kv_stats())
        with self._lock:
            snap.update(queue_depth=len(self._queue),
                        queued_by_class=self._queue.depths(),
                        active_slots=len(self._slots),
                        max_slots=self.engine.max_slots,
                        dead=self._killed is not None,
                        role=self.role,
                        draining=self._draining,
                        swap_pending=self._pending_flip is not None)
        return snap
