"""Replicated request router: spread, health, failover.

Horovod's lineage is data-parallel replicas coordinated over
collectives (SURVEY §0); serving maps the same shape onto request
traffic: each replica is a model copy spanning a *process set* of mesh
slots (:func:`replica_slot_groups` partitions the global mesh exactly
the way ``hvd.add_process_set`` expects), and the router spreads
requests across replicas round-robin — the control plane is
collective-aware, the per-token hot path never crosses replicas.

Failure handling mirrors the task-agent liveness design
(``runner/task_agent.py``): consecutive failures accumulate *strikes*;
at the configured limit the replica is benched for a probation window,
after which one half-open attempt may rehabilitate it.  A request that
was in flight on a dying replica is **drained back into the queue**:
the router re-submits it under the shared
:class:`~horovod_tpu.utils.retry.RetryPolicy` (jittered exponential
backoff — synchronized retries from a fleet of routers would re-create
the overload that killed the replica), and a response cache keyed by
``request_id`` guarantees at-most-once delivery to the caller even if
a retry races a late success.

**Prefix affinity** (serve/kv/): requests whose leading prompt block
matches one recently served on a replica prefer that replica — its
paged KV pool already holds the prefix's blocks, so admission there is
a cache hit instead of a full prefill.  Affinity is a preference, not
a pin: a benched replica falls back to the least-loaded spread, so the
failure handling above is unchanged.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import trace as trace_mod
from ..runner.common.network import BasicClient
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy, retry_call
from .engine import resolved_config
from .server import (CancelRequest, GenerateRequest, GenerateResponse,
                     StatsRequest)

logger = get_logger(__name__)

# Wire errors after which the SAME request may safely run elsewhere:
# the replica never produced (or will never deliver) a response.
_RETRYABLE_ERRORS = ("busy", "replica_killed", "replica_dead")


class NoHealthyReplicasError(ConnectionError):
    """Every replica is dead or benched (may clear after probation)."""


class ReplicaUnavailableError(ConnectionError):
    """The chosen replica refused or lost the request; try another."""


def replica_slot_groups(n_replicas: int,
                        world_size: Optional[int] = None) -> List[List[int]]:
    """Partition the mesh's slots into ``n_replicas`` contiguous
    data-parallel groups — the rank lists a deployer feeds to
    ``hvd.add_process_set`` (one set per replica; contiguous keeps each
    replica on an ICI-adjacent block)."""
    from .. import basics

    world = world_size if world_size is not None else basics.size()
    if n_replicas < 1 or world % n_replicas:
        raise ValueError(
            f"cannot split {world} slot(s) into {n_replicas} equal "
            f"replica group(s)")
    per = world // n_replicas
    return [list(range(i * per, (i + 1) * per)) for i in range(n_replicas)]


def register_replica_process_sets(n_replicas: int):
    """Register (or look up) one process set per replica group;
    returns them in replica order.  Idempotent: an already-registered
    identical set is reused, so serving restarts don't collide."""
    from .. import process_sets as ps

    out = []
    for ranks in replica_slot_groups(n_replicas):
        existing = ps._table().find(ranks)
        out.append(existing if existing is not None
                   else ps.add_process_set(ranks))
    return out


class ReplicaSpec:
    """Where one replica answers: candidate addresses + its mesh ranks."""

    def __init__(self, name: str, addresses: List[Tuple[str, int]],
                 ranks: Optional[Sequence[int]] = None):
        self.name = name
        self.addresses = list(addresses)
        self.ranks = list(ranks) if ranks is not None else None


class _ReplicaState:
    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        # Health/load state is owned by the Router that holds this
        # replica entry — all mutation happens under ITS lock.
        self.client: Optional[BasicClient] = None  # guarded-by: Router._lock
        self.strikes = 0                           # guarded-by: Router._lock
        self.dead_until: Optional[float] = None    # guarded-by: Router._lock
        self.inflight = 0                          # guarded-by: Router._lock
        self.completed = 0                         # guarded-by: Router._lock
        self.failed = 0                            # guarded-by: Router._lock


class Router:
    """Client-side request spreader over serving replicas."""

    def __init__(self, replicas: Sequence[ReplicaSpec], key: bytes, *,
                 retry_policy: Optional[RetryPolicy] = None,
                 strikes: Optional[int] = None,
                 probation_s: Optional[float] = None,
                 probe_timeout: float = 5.0,
                 dedupe_window: int = 1024):
        if not replicas:
            raise ValueError("router needs at least one replica")
        cfg = resolved_config()
        self._replicas = [_ReplicaState(r) for r in replicas]
        self._key = key
        self._probe_timeout = probe_timeout
        self._strike_limit = int(strikes if strikes is not None
                                 else cfg.serve_replica_strikes)
        self._probation_s = float(probation_s if probation_s is not None
                                  else cfg.serve_probation_seconds)
        self._default_deadline_s = cfg.serve_deadline_seconds
        # One failover pass visits every replica once; the policy adds
        # backoff'd sweeps on top (half-open probation needs the time).
        self._retry_policy = retry_policy or RetryPolicy(
            attempts=2 * len(self._replicas) + 1,
            base_delay_s=0.05, max_delay_s=2.0)
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._done: "OrderedDict[str, GenerateResponse]" = OrderedDict()  # guarded-by: _lock
        self._dedupe_window = dedupe_window
        # Prefix affinity: leading-block token key -> replica whose KV
        # pool last served it (bounded LRU; serve/kv prefix sharing).
        # The slack is how many MORE in-flight requests than the idlest
        # peer the resident replica may carry before affinity yields to
        # the least-loaded spread — without it, one hot system prompt
        # would pin the whole fleet's traffic to a single replica and
        # serially bench healthy peers through busy-strikes.
        self._affinity_block = int(cfg.serve_kv_block)
        self._affinity_slack = max(1, int(cfg.serve_max_batch))
        self._prefix_map: "OrderedDict[tuple, _ReplicaState]" = OrderedDict()  # guarded-by: _lock
        self._prefix_window = 1024

    # --- health -------------------------------------------------------------

    def _healthy(self, rep: _ReplicaState, now: float) -> bool:
        if rep.dead_until is None:
            return True
        return now >= rep.dead_until    # probation over: half-open try

    def _strike(self, rep: _ReplicaState, fatal: bool = False) -> None:
        with self._lock:
            rep.strikes += 1
            rep.failed += 1
            rep.client = None    # re-probe on next use
            if fatal or rep.strikes >= self._strike_limit:
                rep.dead_until = time.monotonic() + self._probation_s
                logger.warning(
                    "replica %s benched for %.1fs (%d strike(s))",
                    rep.spec.name, self._probation_s, rep.strikes)

    def _mark_ok(self, rep: _ReplicaState) -> None:
        with self._lock:
            rep.strikes = 0
            rep.dead_until = None
            rep.completed += 1

    def _prefix_key(self, prompt: Sequence[int]) -> Optional[tuple]:
        """Affinity key: the prompt's leading KV block's token IDs —
        the same granularity the replica's prefix index shares at, so
        a key match is (at least) a one-block cache hit there."""
        b = self._affinity_block
        if b < 1 or len(prompt) < b:
            return None
        return tuple(int(t) for t in prompt[:b])

    def _note_affinity(self, key: Optional[tuple],
                       rep: _ReplicaState) -> None:
        if key is None:
            return
        with self._lock:
            self._prefix_map[key] = rep
            self._prefix_map.move_to_end(key)
            while len(self._prefix_map) > self._prefix_window:
                self._prefix_map.popitem(last=False)

    def _pick(self, prefix_key: Optional[tuple] = None) -> _ReplicaState:
        """Round-robin over healthy replicas, preferring (1) the
        replica whose KV pool holds this prompt's prefix, then (2) the
        least loaded among the next candidates (spread, not pile-on).

        Expired probation is **half-open**: exactly one request per
        window probes the benched replica (its bench is re-armed under
        the lock before release, so a concurrent wave cannot pile onto
        a possibly-still-dead peer); success rejoins it via
        ``_mark_ok``, failure re-strikes."""
        now = time.monotonic()
        with self._lock:
            half_open = [r for r in self._replicas
                         if r.dead_until is not None
                         and now >= r.dead_until]
            if half_open:
                probe = min(half_open, key=lambda r: r.dead_until)
                probe.dead_until = now + self._probation_s
                return probe
            fully = [r for r in self._replicas if r.dead_until is None]
            if not fully:
                soonest = min(
                    (r.dead_until for r in self._replicas
                     if r.dead_until is not None), default=None)
                raise NoHealthyReplicasError(
                    f"all {len(self._replicas)} replica(s) benched"
                    + (f"; next probation in "
                       f"{max(0.0, soonest - now):.1f}s"
                       if soonest else ""))
            if prefix_key is not None:
                resident = self._prefix_map.get(prefix_key)
                if (resident is not None and resident.dead_until is None
                        and resident.inflight
                        - min(r.inflight for r in fully)
                        <= self._affinity_slack):
                    # Prefer the cache-warm replica while it is not
                    # drastically more loaded than the idlest peer;
                    # beyond the slack the request spills to the
                    # spread (the prefix gets cached there too).
                    return resident
            start = next(self._rr) % len(fully)
            ordered = fully[start:] + fully[:start]
            return min(ordered, key=lambda r: r.inflight)

    def _client(self, rep: _ReplicaState) -> BasicClient:
        with self._lock:
            client = rep.client
        if client is None:
            # Probe outside the lock (network I/O); publish under it so
            # concurrent callers converge on one client instead of
            # racing duplicate probes.
            client = BasicClient(
                rep.spec.name, rep.spec.addresses, self._key,
                probe_timeout=self._probe_timeout,
                # The router owns cross-replica retries; a transparent
                # same-replica retry here would stack policies.
                retry_policy=RetryPolicy(attempts=1))
            with self._lock:
                if rep.client is None:
                    rep.client = client
                else:
                    client = rep.client
        return client

    def _cancel_on(self, rep: _ReplicaState, request_id: str) -> None:
        """Best-effort abandon of a request the router is about to
        re-run elsewhere — without this, a wire error after admission
        leaves the original replica decoding an answer nobody will
        read, and every failover burns two replicas' worth of slots."""
        try:
            self._client(rep).request(CancelRequest(request_id),
                                      idempotent=False, timeout=5.0)
        except OSError:
            pass   # replica truly gone: nothing left to cancel

    # --- request path -------------------------------------------------------

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, stop_token: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 spec: bool = False) -> GenerateResponse:
        """Route one generation; at-most-once per ``request_id``.

        Retryable failures (dead/busy/killed replica, wire errors)
        re-enter the queue under the retry policy and land on another
        replica; terminal errors (deadline, oversized prompt) return
        as-is.  ``spec=True`` opts into speculative decoding on
        replicas that run a drafter."""
        rid = request_id or uuid.uuid4().hex
        with self._lock:
            if rid in self._done:
                return self._done[rid]
        req = GenerateRequest(rid, list(prompt),
                              max_new_tokens=max_new_tokens,
                              temperature=temperature, top_k=top_k,
                              stop_token=stop_token,
                              deadline_s=deadline_s, spec=spec)
        prefix_key = self._prefix_key(prompt)
        # Response-read timeout: a generation legitimately runs for the
        # request's whole deadline — reading it under the snappy probe
        # timeout would misclassify every slow answer as a dead replica
        # (and bench the healthy fleet two requests at a time).
        effective_deadline = (deadline_s if deadline_s is not None
                              else self._default_deadline_s)
        wire_timeout = (effective_deadline * 2 + 30.0
                        if effective_deadline and effective_deadline > 0
                        else 600.0)

        def attempt() -> GenerateResponse:
            # NoHealthyReplicasError is retryable: probation may clear
            # under the policy's backoff.
            rep = self._pick(prefix_key)
            with self._lock:
                rep.inflight += 1
            try:
                client = self._client(rep)
                resp = client.request(req, idempotent=False,
                                      timeout=wire_timeout)
            except OSError as e:
                self._strike(rep)
                self._cancel_on(rep, rid)
                raise ReplicaUnavailableError(
                    f"replica {rep.spec.name}: {e}") from e
            finally:
                with self._lock:
                    rep.inflight -= 1
            if resp.error in _RETRYABLE_ERRORS:
                self._strike(rep, fatal=resp.error != "busy")
                raise ReplicaUnavailableError(
                    f"replica {rep.spec.name}: {resp.error}")
            self._mark_ok(rep)
            # The replica now holds this prompt's prefix blocks: later
            # requests sharing the leading block prefer it (cache hit).
            self._note_affinity(prefix_key, rep)
            return resp

        # One trace per request, rooted at admission (docs/tracing.md):
        # the failover attempts' RPC client spans, the replica's server
        # span, and the batcher's queued/prefill/decode phases all
        # parent under it, so the merged trace answers "where did this
        # request's latency go" across processes.
        with trace_mod.span("hvd_tpu_serve_request", root=True,
                            args={"request_id": rid,
                                  "max_new_tokens": max_new_tokens}):
            resp = retry_call(
                attempt, policy=self._retry_policy,
                retry_on=(ReplicaUnavailableError, NoHealthyReplicasError),
                describe=f"serve generate {rid}")
        with self._lock:
            self._done[rid] = resp
            while len(self._done) > self._dedupe_window:
                self._done.popitem(last=False)
        return resp

    # --- observability ------------------------------------------------------

    def replica_stats(self, timeout: float = 5.0) -> Dict[str, dict]:
        """Live ``StatsRequest`` snapshot per reachable replica, plus
        the router's own health view."""
        out: Dict[str, dict] = {}
        now = time.monotonic()
        for idx, rep in enumerate(self._replicas):
            entry: Dict[str, object] = {
                "healthy": self._healthy(rep, now),
                "strikes": rep.strikes,
                "inflight": rep.inflight,
                "completed": rep.completed,
                "failed": rep.failed,
            }
            try:
                resp = self._client(rep).request(StatsRequest(),
                                                 idempotent=False,
                                                 timeout=timeout)
                entry["stats"] = resp.stats
            except OSError as e:
                entry["stats_error"] = str(e)
            key = rep.spec.name
            if key in out:   # duplicate display names stay visible
                key = f"{key}[{idx}]"
            out[key] = entry
        return out
