"""Replicated request router: spread, health, failover.

Horovod's lineage is data-parallel replicas coordinated over
collectives (SURVEY §0); serving maps the same shape onto request
traffic: each replica is a model copy spanning a *process set* of mesh
slots (:func:`replica_slot_groups` partitions the global mesh exactly
the way ``hvd.add_process_set`` expects), and the router spreads
requests across replicas round-robin — the control plane is
collective-aware, the per-token hot path never crosses replicas.

Failure handling mirrors the task-agent liveness design
(``runner/task_agent.py``): consecutive failures accumulate *strikes*;
at the configured limit the replica is benched for a probation window,
after which one half-open attempt may rehabilitate it.  A request that
was in flight on a dying replica is **drained back into the queue**:
the router re-submits it under the shared
:class:`~horovod_tpu.utils.retry.RetryPolicy` (jittered exponential
backoff — synchronized retries from a fleet of routers would re-create
the overload that killed the replica), and a response cache keyed by
``request_id`` guarantees at-most-once delivery to the caller even if
a retry races a late success.

**Global prefix directory** (serve/fleet/directory.py): requests whose
leading prompt block is resident on some replica's paged KV pool route
there — admission is a cache hit instead of a full prefill.  The
directory subsumes the single-replica affinity map: it tracks every
replica a prefix is resident on (migration leaves it on both ends),
drops a replica's entries when it is benched, and consumes eviction
notifications piggybacked on response frames.  Residency is a
preference, not a pin: a benched or saturated resident falls back to
the least-loaded spread, so the failure handling above is unchanged.

**Role-aware dispatch** (serve/fleet/): when the fleet carries both
``prefill`` and ``decode`` replicas, a directory-miss request runs the
admit→prefill→migrate→decode pipeline — the router sends the request
to a prefill replica with its decode target attached, the prefill
replica streams the KV over the wire after the first token, and the
router collects the finished generation from the decode replica.  Any
pipeline failure (prefill death mid-migration, digest rejection, lost
continuation) re-routes to a unified full-generation recompute path on
whatever healthy replica remains — requests are never lost and tokens
are never wrong, the disaggregation only ever costs economics.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import instrument as _obs
from ..obs import trace as trace_mod
from ..runner.common.network import (BasicClient, CollectRequest,
                                     DrainRequest)
from ..utils.logging import get_logger
from ..utils.retry import RetryPolicy, retry_call
from .engine import resolved_config
from .fleet.directory import PrefixDirectory
from .qos import QosGate, validate_class
from .server import (CancelRequest, GenerateRequest, GenerateResponse,
                     RollbackRequest, StatsRequest, SwapRequest)

logger = get_logger(__name__)

# Wire errors after which the SAME request may safely run elsewhere:
# the replica never produced (or will never deliver) a response.
_RETRYABLE_ERRORS = ("busy", "replica_killed", "replica_dead")


class NoHealthyReplicasError(ConnectionError):
    """Every replica is dead or benched (may clear after probation)."""


class ReplicaUnavailableError(ConnectionError):
    """The chosen replica refused or lost the request; try another."""


def replica_slot_groups(n_replicas: int,
                        world_size: Optional[int] = None) -> List[List[int]]:
    """Partition the mesh's slots into ``n_replicas`` contiguous
    data-parallel groups — the rank lists a deployer feeds to
    ``hvd.add_process_set`` (one set per replica; contiguous keeps each
    replica on an ICI-adjacent block)."""
    from .. import basics

    world = world_size if world_size is not None else basics.size()
    if n_replicas < 1 or world % n_replicas:
        raise ValueError(
            f"cannot split {world} slot(s) into {n_replicas} equal "
            f"replica group(s)")
    per = world // n_replicas
    return [list(range(i * per, (i + 1) * per)) for i in range(n_replicas)]


def register_replica_process_sets(n_replicas: int):
    """Register (or look up) one process set per replica group;
    returns them in replica order.  Idempotent: an already-registered
    identical set is reused, so serving restarts don't collide."""
    from .. import process_sets as ps

    out = []
    for ranks in replica_slot_groups(n_replicas):
        existing = ps._table().find(ranks)
        out.append(existing if existing is not None
                   else ps.add_process_set(ranks))
    return out


class ReplicaSpec:
    """Where one replica answers: candidate addresses, its mesh ranks,
    and its fleet role (``prefill`` / ``decode`` / ``unified`` — the
    replica class the disaggregated dispatch schedules by)."""

    def __init__(self, name: str, addresses: List[Tuple[str, int]],
                 ranks: Optional[Sequence[int]] = None,
                 role: str = "unified"):
        self.name = name
        self.addresses = list(addresses)
        self.ranks = list(ranks) if ranks is not None else None
        if role not in ("prefill", "decode", "unified"):
            raise ValueError(f"unknown replica role {role!r}; expected "
                             f"prefill|decode|unified")
        self.role = role


class _ReplicaState:
    def __init__(self, spec: ReplicaSpec):
        self.spec = spec
        # Health/load state is owned by the Router that holds this
        # replica entry — all mutation happens under ITS lock.
        self.client: Optional[BasicClient] = None  # guarded-by: Router._lock
        self.strikes = 0                           # guarded-by: Router._lock
        self.dead_until: Optional[float] = None    # guarded-by: Router._lock
        self.draining = False                      # guarded-by: Router._lock
        self.inflight = 0                          # guarded-by: Router._lock
        self.completed = 0                         # guarded-by: Router._lock
        self.failed = 0                            # guarded-by: Router._lock
        # Last weights version observed on a response from this replica
        # (serve/swap.py) — None until one reported.
        self.weights_version: Optional[int] = None  # guarded-by: Router._lock


class Router:
    """Client-side request spreader over serving replicas."""

    def __init__(self, replicas: Sequence[ReplicaSpec], key: bytes, *,
                 retry_policy: Optional[RetryPolicy] = None,
                 strikes: Optional[int] = None,
                 probation_s: Optional[float] = None,
                 probe_timeout: float = 5.0,
                 dedupe_window: int = 1024,
                 clock=None,
                 client_factory=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        cfg = resolved_config()
        self._replicas = [_ReplicaState(r) for r in replicas]
        self._key = key
        self._probe_timeout = probe_timeout
        # Injectable monotonic clock: probation windows and stats
        # deadlines read THIS, so the fleet simulator (serve/fleet/sim
        # .py) can run health policy under a virtual clock.  Default is
        # the real clock — production behavior unchanged.
        self._clock = clock if clock is not None else time.monotonic
        # Transport seam: builds the per-replica client instead of
        # BasicClient.  A deterministic in-process transport (the sim's
        # replicas, a unit test's fake) answers the same wire frames
        # without sockets; with a factory installed, stats snapshots
        # poll serially — there is no network I/O to overlap, and
        # thread scheduling would perturb a simulation's determinism.
        self._client_factory = client_factory
        self._strike_limit = int(strikes if strikes is not None
                                 else cfg.serve_replica_strikes)
        self._probation_s = float(probation_s if probation_s is not None
                                  else cfg.serve_probation_seconds)
        self._default_deadline_s = cfg.serve_deadline_seconds
        # One failover pass visits every replica once; the policy adds
        # backoff'd sweeps on top (half-open probation needs the time).
        self._retry_policy = retry_policy or RetryPolicy(
            attempts=2 * len(self._replicas) + 1,
            base_delay_s=0.05, max_delay_s=2.0)
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._done: "OrderedDict[str, GenerateResponse]" = OrderedDict()  # guarded-by: _lock
        self._dedupe_window = dedupe_window
        # Global prefix directory: leading-block token key -> replicas
        # with resident blocks (serve/fleet/directory.py — the
        # router-tier promotion of the per-replica radix index).  The
        # slack is how many MORE in-flight requests than the idlest
        # peer a resident replica may carry before residency yields to
        # the least-loaded spread — without it, one hot system prompt
        # would pin the whole fleet's traffic to a single replica and
        # serially bench healthy peers through busy-strikes.
        self._affinity_block = int(cfg.serve_kv_block)
        self._affinity_slack = max(1, int(cfg.serve_max_batch))
        self._directory = PrefixDirectory(self._affinity_block,
                                          max_entries=1024)
        # Multi-tenant QoS gate (serve/qos/brownout.py): per-tenant
        # rate limits + the brownout shed ladder, consulted BEFORE any
        # replica is touched.  None = no router-tier policy (the
        # batcher tier may still enforce budgets).
        self._qos_gate: Optional[QosGate] = None

    def attach_qos(self, gate: QosGate) -> None:
        """Install the router-tier QoS gate (docs/qos.md): every
        ``generate`` runs its shed/budget checks first, and the fleet
        controller feeds it the overload signals each control round."""
        self._qos_gate = gate

    @property
    def qos_gate(self) -> Optional[QosGate]:
        return self._qos_gate

    # --- health -------------------------------------------------------------

    def _healthy(self, rep: _ReplicaState, now: float) -> bool:
        if rep.dead_until is None:
            return True
        return now >= rep.dead_until    # probation over: half-open try

    def _strike(self, rep: _ReplicaState, fatal: bool = False) -> None:
        benched = False
        with self._lock:
            rep.strikes += 1
            rep.failed += 1
            rep.client = None    # re-probe on next use
            if fatal or rep.strikes >= self._strike_limit:
                rep.dead_until = self._clock() + self._probation_s
                benched = True
                logger.warning(
                    "replica %s benched for %.1fs (%d strike(s))",
                    rep.spec.name, self._probation_s, rep.strikes)
        if benched:
            # Directory consistency on replica death: a benched replica
            # may have lost its pool (crash/restart), so its residency
            # entries are dropped — a stale route would only cost a
            # cache miss, but a prompt drop here keeps the directory
            # honest through failover storms.
            self._directory.invalidate_replica(rep)

    def _mark_ok(self, rep: _ReplicaState) -> None:
        with self._lock:
            rep.strikes = 0
            rep.dead_until = None
            rep.completed += 1

    def _prefix_key(self, prompt: Sequence[int]) -> Optional[tuple]:
        """Directory key: the prompt's leading KV block's token IDs —
        the same granularity the replicas' prefix indexes share at, so
        a key match is (at least) a one-block cache hit there."""
        return self._directory.key_for(prompt)

    def _note_affinity(self, key: Optional[tuple], rep: _ReplicaState,
                       version: Optional[int] = None) -> None:
        """Record residency: ``rep`` now holds this prompt's leading
        blocks (it served the request, or adopted its migration).
        ``version`` is the weights version the response reported — the
        KV those blocks were computed under."""
        if key is not None:
            self._directory.record(key, rep, version=version)

    def _ingest_evictions(self, rep: _ReplicaState, resp) -> None:
        """Apply eviction notifications piggybacked on a response frame
        to the directory (the replica no longer holds these keys)."""
        for key in (getattr(resp, "evicted_prefixes", None) or ()):
            self._directory.discard(tuple(key), rep)

    def _note_version(self, rep: _ReplicaState,
                      version: Optional[int]) -> None:
        """Track ``rep``'s weights version from a response/stats frame.
        A CHANGE drops the replica's prefix-directory entries: its KV
        pool was flushed at the flip, so every recorded residency is
        stale — and even a missed notification is caught by the
        version tag ``_resident_locked`` checks (mixed-version routing
        rule, docs/hot_swap.md)."""
        if version is None:
            return
        with self._lock:
            changed = (rep.weights_version is not None
                       and rep.weights_version != version)
            rep.weights_version = int(version)
        if changed:
            self._directory.invalidate_replica(rep)

    def _pick(self, prefix_key: Optional[tuple] = None) -> _ReplicaState:
        """Round-robin over healthy replicas, preferring (1) the
        replica whose KV pool holds this prompt's prefix, then (2) the
        least loaded among the next candidates (spread, not pile-on).

        Expired probation is **half-open**: exactly one request per
        window probes the benched replica (its bench is re-armed under
        the lock before release, so a concurrent wave cannot pile onto
        a possibly-still-dead peer); success rejoins it via
        ``_mark_ok``, failure re-strikes."""
        now = self._clock()
        with self._lock:
            half_open = [r for r in self._replicas
                         if r.dead_until is not None
                         and now >= r.dead_until and not r.draining]
            if half_open:
                probe = min(half_open, key=lambda r: r.dead_until)
                probe.dead_until = now + self._probation_s
                return probe
            fully = [r for r in self._replicas
                     if r.dead_until is None and not r.draining]
            if not fully:
                soonest = min(
                    (r.dead_until for r in self._replicas
                     if r.dead_until is not None), default=None)
                raise NoHealthyReplicasError(
                    f"all {len(self._replicas)} replica(s) benched or "
                    f"draining"
                    + (f"; next probation in "
                       f"{max(0.0, soonest - now):.1f}s"
                       if soonest else ""))
            resident = self._resident_locked(prefix_key, fully)
            if resident is not None:
                return resident
            start = next(self._rr) % len(fully)
            ordered = fully[start:] + fully[:start]
            return min(ordered, key=lambda r: r.inflight)

    def _resident_locked(self, prefix_key: Optional[tuple],
                         fully: List[_ReplicaState]
                         ) -> Optional[_ReplicaState]:
        """Caller holds the lock; ``fully`` is its healthy,
        non-draining pool.  Returns the most recently confirmed
        resident replica within the load slack, or None.  ONE
        definition of the residency rule: prefer the cache-warm replica
        while it is not drastically more loaded than the idlest peer;
        beyond the slack the request spills to the spread (the prefix
        gets cached there too)."""
        if prefix_key is None or not fully:
            return None
        floor = min(r.inflight for r in fully)
        for resident, version in self._directory.lookup_versioned(
                prefix_key):
            if resident not in fully:
                continue
            if version is not None and resident.weights_version is not None \
                    and version != resident.weights_version:
                # Mixed-version rule (docs/hot_swap.md): the recorded
                # residency predates a weight flip — the KV it points
                # at was computed under OLD weights, so the hit must
                # fall back to a recompute, never serve stale blocks.
                continue
            if resident.inflight - floor <= self._affinity_slack:
                return resident
        return None

    def _directory_pick(self,
                        prefix_key: Optional[tuple]
                        ) -> Optional[_ReplicaState]:
        """The global-prefix-directory route: a healthy, non-draining
        replica with this prompt's leading block resident (and within
        the load slack), or None — the fleet dispatch's first choice
        before the prefill/decode pipeline."""
        with self._lock:
            fully = [r for r in self._replicas
                     if r.dead_until is None and not r.draining]
            return self._resident_locked(prefix_key, fully)

    def _pick_role(self, role: str) -> Optional[_ReplicaState]:
        """Least-loaded healthy, non-draining replica of ``role``
        (None when the role has no healthy member — the caller falls
        back to the unified path)."""
        with self._lock:
            pool = [r for r in self._replicas
                    if r.spec.role == role and r.dead_until is None
                    and not r.draining]
            if not pool:
                return None
            return min(pool, key=lambda r: r.inflight)

    def _client(self, rep: _ReplicaState) -> BasicClient:
        with self._lock:
            client = rep.client
        if client is None:
            # Probe outside the lock (network I/O); publish under it so
            # concurrent callers converge on one client instead of
            # racing duplicate probes.
            if self._client_factory is not None:
                client = self._client_factory(rep.spec)
            else:
                client = BasicClient(
                    rep.spec.name, rep.spec.addresses, self._key,
                    probe_timeout=self._probe_timeout,
                    # The router owns cross-replica retries; a
                    # transparent same-replica retry here would stack
                    # policies.
                    retry_policy=RetryPolicy(attempts=1))
            with self._lock:
                if rep.client is None:
                    rep.client = client
                else:
                    client = rep.client
        return client

    def _cancel_on(self, rep: _ReplicaState, request_id: str) -> None:
        """Best-effort abandon of a request the router is about to
        re-run elsewhere — without this, a wire error after admission
        leaves the original replica decoding an answer nobody will
        read, and every failover burns two replicas' worth of slots."""
        try:
            self._client(rep).request(CancelRequest(request_id),
                                      idempotent=False, timeout=5.0)
        except OSError:
            pass   # replica truly gone: nothing left to cancel

    # --- fleet membership (serve/fleet/controller.py drives these) ----------

    def _find(self, name: str) -> Optional[_ReplicaState]:
        with self._lock:
            return next((r for r in self._replicas
                         if r.spec.name == name), None)

    def add_replica(self, spec: ReplicaSpec) -> None:
        """Register a freshly-launched replica (elastic scale-out)."""
        with self._lock:
            self._replicas.append(_ReplicaState(spec))
        logger.info("router: +replica %s (%s)", spec.name, spec.role)

    def remove_replica(self, name: str) -> None:
        """Deregister ``name`` (drain completed / replica retired) and
        release its prefix-directory entries.  The router refuses to
        remove its last replica — an empty fleet serves nothing."""
        with self._lock:
            rep = next((r for r in self._replicas
                        if r.spec.name == name), None)
            if rep is None:
                return
            if len(self._replicas) <= 1:
                raise ValueError(
                    "cannot remove the last replica from the router")
            self._replicas.remove(rep)
        self._directory.invalidate_replica(rep)
        logger.info("router: -replica %s", name)

    def drain_replica(self, name: str, timeout: float = 5.0) -> None:
        """Start drain-and-retire for ``name``: mark it locally (picks
        skip it immediately) and tell the replica to stop admitting."""
        rep = self._find(name)
        if rep is None:
            return
        self._mark_draining(rep)
        try:
            self._client(rep).request(DrainRequest(), idempotent=False,
                                      timeout=timeout)
        except OSError as e:
            logger.warning("drain request to %s failed (%s); the local "
                           "draining mark still shields it from new "
                           "traffic", name, e)

    def undrain_replica(self, name: str, timeout: float = 5.0) -> None:
        """Reverse a drain (the controller's abandon path): clear the
        local mark so picks see the replica again and tell it to admit
        — a replica left draining with no retire coming would starve
        the fleet."""
        rep = self._find(name)
        if rep is None:
            return
        with self._lock:
            rep.draining = False
        try:
            self._client(rep).request(DrainRequest(cancel=True),
                                      idempotent=False, timeout=timeout)
        except OSError as e:
            logger.warning("undrain request to %s failed (%s); the "
                           "replica keeps refusing until reachable",
                           name, e)

    def _mark_draining(self, rep: _ReplicaState) -> None:
        with self._lock:
            rep.draining = True

    # --- weight hot-swap (serve/swap.py; docs/hot_swap.md) ------------------

    def replica_names(self) -> List[str]:
        with self._lock:
            return [r.spec.name for r in self._replicas]

    def swap_replica(self, name: str, step: int, *,
                     rollback: bool = False, timeout: float = 120.0):
        """Tell one replica to hot-swap (or roll back) to ``step``;
        returns its ``SwapResponse``.  A refused/failed swap is NOT a
        health event — the replica answered, and it is still serving
        its old weights — so nothing here strikes it; only a wire
        death does (via the normal strike path)."""
        rep = self._find(name)
        if rep is None:
            raise ValueError(f"unknown replica {name!r}")
        frame = (RollbackRequest(step) if rollback
                 else SwapRequest(step))
        try:
            resp = self._client(rep).request(frame, idempotent=False,
                                             timeout=timeout)
        except OSError as e:
            self._strike(rep)
            raise ReplicaUnavailableError(
                f"replica {name}: {e}") from e
        self._note_version(rep, getattr(resp, "weights_version", None))
        return resp

    def rollback_replica(self, name: str, step: int, *,
                         timeout: float = 120.0):
        return self.swap_replica(name, step, rollback=True,
                                 timeout=timeout)

    # --- request path -------------------------------------------------------

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: int = 16, temperature: float = 0.0,
                 top_k: int = 0, stop_token: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 spec: bool = False,
                 tenant: Optional[str] = None,
                 qos_class: Optional[str] = None) -> GenerateResponse:
        """Route one generation; at-most-once per ``request_id``.

        Retryable failures (dead/busy/killed replica, wire errors)
        re-enter the queue under the retry policy and land on another
        replica; terminal errors (deadline, oversized prompt) return
        as-is.  ``spec=True`` opts into speculative decoding on
        replicas that run a drafter.  ``tenant``/``qos_class`` place
        the request in the QoS scheduler (docs/qos.md); with a gate
        attached, a brownout shed or an exhausted tenant budget raises
        the typed retriable rejection BEFORE any replica is touched."""
        rid = request_id or uuid.uuid4().hex
        qos_class = validate_class(qos_class)
        tenant = tenant or "default"
        with self._lock:
            if rid in self._done:
                return self._done[rid]
        gate_charge = 0.0
        if self._qos_gate is not None:
            # Raises RequestShedError / BudgetExhaustedError — typed,
            # retriable by the CLIENT after retry_after_s, and costing
            # the fleet nothing (no replica ever sees the request).
            gate_charge = self._qos_gate.admit(
                tenant, qos_class, len(prompt) + max_new_tokens)
        prefix_key = self._prefix_key(prompt)
        # Response-read timeout: a generation legitimately runs for the
        # request's whole deadline — reading it under the snappy probe
        # timeout would misclassify every slow answer as a dead replica
        # (and bench the healthy fleet two requests at a time).
        effective_deadline = (deadline_s if deadline_s is not None
                              else self._default_deadline_s)
        wire_timeout = (effective_deadline * 2 + 30.0
                        if effective_deadline and effective_deadline > 0
                        else 600.0)

        def mk_req(migrate_to=None) -> GenerateRequest:
            return GenerateRequest(rid, list(prompt),
                                   max_new_tokens=max_new_tokens,
                                   temperature=temperature, top_k=top_k,
                                   stop_token=stop_token,
                                   deadline_s=deadline_s, spec=spec,
                                   migrate_to=migrate_to,
                                   tenant=tenant, qos_class=qos_class)

        # A collect failure means the decode replica lost the migrated
        # continuation — later attempts recompute on the unified path
        # instead of re-entering the pipeline (never wrong tokens, at
        # worst one redundant prefill).
        state = {"force_unified": False}

        def run_on(rep: _ReplicaState, wire_req) -> GenerateResponse:
            with self._lock:
                rep.inflight += 1
            try:
                client = self._client(rep)
                resp = client.request(wire_req, idempotent=False,
                                      timeout=wire_timeout)
            except OSError as e:
                self._strike(rep)
                self._cancel_on(rep, rid)
                raise ReplicaUnavailableError(
                    f"replica {rep.spec.name}: {e}") from e
            finally:
                with self._lock:
                    rep.inflight -= 1
            if resp.error == "draining":
                # Voluntary refusal (drain-and-retire), not a failure:
                # shield the replica from picks without striking it.
                self._mark_draining(rep)
                raise ReplicaUnavailableError(
                    f"replica {rep.spec.name}: draining")
            if resp.error in _RETRYABLE_ERRORS:
                self._strike(rep, fatal=resp.error != "busy")
                raise ReplicaUnavailableError(
                    f"replica {rep.spec.name}: {resp.error}")
            self._mark_ok(rep)
            self._ingest_evictions(rep, resp)
            self._note_version(rep, getattr(resp, "weights_version",
                                            None))
            return resp

        def attempt() -> GenerateResponse:
            # 1. Global prefix directory: a resident prefix anywhere in
            # the fleet (prefill source, decode target after an earlier
            # migration, or a unified peer) beats a cold pipeline — the
            # hit replica runs the whole request against warm KV.
            rep = self._directory_pick(prefix_key)
            if rep is not None:
                resp = run_on(rep, mk_req())
                # Counted only on success: a failed route is a failover,
                # not a cache hit, and retries must not recount.
                _obs.on_fleet_directory_hit()
                self._note_affinity(prefix_key, rep,
                                    getattr(resp, "weights_version",
                                            None))
                return resp
            # 2. Disaggregated pipeline: admit→prefill→migrate→decode
            # when both role classes have healthy members.
            if not state["force_unified"]:
                pre = self._pick_role("prefill")
                dec = self._pick_role("decode")
                if pre is not None and dec is not None:
                    # Reserve the decode target for the whole
                    # prefill+migrate window.  ``inflight`` on the
                    # decode otherwise only rises when the collect
                    # starts — so N concurrent submits all see the same
                    # least-loaded decode and the fleet convoys its
                    # migrations into one receiver (found at simulated
                    # scale by serve/fleet/sim.py's no_migration_convoy
                    # invariant).  Inbound migration is load from the
                    # moment the target is chosen.
                    with self._lock:
                        dec.inflight += 1
                    reserved = True
                    try:
                        resp = run_on(pre, mk_req(
                            migrate_to=(dec.spec.name,
                                        dec.spec.addresses)))
                        pre_v = getattr(resp, "weights_version", None)
                        if getattr(resp, "migrated_to", None) is None:
                            # Migration fell back (digest rejection,
                            # wire drop, busy receiver): the prefill
                            # replica finished the generation itself.
                            self._note_affinity(prefix_key, pre, pre_v)
                            return resp
                        self._note_affinity(prefix_key, pre, pre_v)
                        # Hand the reservation off to the collect: from
                        # here ``run_on(dec, …)`` carries the count.
                        with self._lock:
                            dec.inflight -= 1
                        reserved = False
                        try:
                            final = run_on(dec, CollectRequest(rid))
                        except ReplicaUnavailableError:
                            state["force_unified"] = True
                            raise
                    finally:
                        if reserved:
                            with self._lock:
                                dec.inflight -= 1
                    if final.error == "unknown_request" or (
                            final.error or "").startswith("import_failed"):
                        # The decode replica lost the continuation
                        # (restart / cancel race) or could not bind the
                        # adopted KV (pool exhausted at deferred import
                        # time — adopt() only checks the queue): both
                        # are recoverable by recomputing elsewhere, and
                        # returning them to the caller would lose a
                        # request every replica could still serve.
                        state["force_unified"] = True
                        raise ReplicaUnavailableError(
                            f"replica {dec.spec.name}: {final.error} "
                            f"for migrated request {rid}")
                    # The caller-visible response is the collect frame;
                    # carry the prefill half's migration metadata onto
                    # it (which replica carried the decode, what the
                    # transfer cost — the bench's overhead signal) AND
                    # the prefill-side TTFT: the collect frame's own
                    # ttft_ms covers only adoption→first-replayed-token
                    # (~0), while the first token was really produced on
                    # the prefill replica after its queueing + prefill —
                    # the same submit→first-token definition the unified
                    # path reports, so fleet and unified TTFT compare
                    # like for like.
                    final.migrated_to = resp.migrated_to
                    final.migrate_ms = resp.migrate_ms
                    final.ttft_ms = resp.ttft_ms
                    self._note_affinity(prefix_key, dec,
                                        getattr(final, "weights_version",
                                                None))
                    return final
            # 3. Unified spread (also the recompute fallback when the
            # pipeline cannot run or lost a continuation).
            # NoHealthyReplicasError is retryable: probation may clear
            # under the policy's backoff.
            rep = self._pick(prefix_key)
            resp = run_on(rep, mk_req())
            # The replica now holds this prompt's prefix blocks: later
            # requests sharing the leading block prefer it (cache hit).
            self._note_affinity(prefix_key, rep,
                                getattr(resp, "weights_version", None))
            return resp

        # One trace per request, rooted at admission (docs/tracing.md):
        # the failover attempts' RPC client spans, the replica's server
        # span, and the batcher's queued/prefill/decode phases all
        # parent under it, so the merged trace answers "where did this
        # request's latency go" across processes.
        try:
            with trace_mod.span("hvd_tpu_serve_request", root=True,
                                args={"request_id": rid,
                                      "max_new_tokens": max_new_tokens}):
                resp = retry_call(
                    attempt, policy=self._retry_policy,
                    retry_on=(ReplicaUnavailableError,
                              NoHealthyReplicasError),
                    describe=f"serve generate {rid}")
        except Exception:
            if self._qos_gate is not None and gate_charge > 0:
                # A lost request served nothing: hand the whole
                # reservation back, or a few fleet outages would drain
                # the tenant's bucket and convert replica failures
                # into budget_exhausted rejections.
                self._qos_gate.refund(tenant, gate_charge)
            raise
        if self._qos_gate is not None and gate_charge > 0:
            # Refund the unused reservation: the charge covered prompt
            # + the generation cap, the tenant pays prompt + delivered.
            used = len(prompt) + len(resp.tokens or ())
            self._qos_gate.refund(tenant, gate_charge - used)
        with self._lock:
            self._done[rid] = resp
            while len(self._done) > self._dedupe_window:
                self._done.popitem(last=False)
        return resp

    # --- observability ------------------------------------------------------

    def replica_stats(self, timeout: float = 5.0) -> Dict[str, dict]:
        """Live ``StatsRequest`` snapshot per reachable replica, plus
        the router's own health view.

        Replicas are polled CONCURRENTLY under one overall deadline:
        an unreachable replica costs the snapshot one ``timeout``, not
        one timeout EACH — the fleet controller reads this every
        control round, and with serial polling an N-replica snapshot
        over dead peers stalled N×timeout (the satellite fix this PR
        pins with a dead-replica test)."""
        now = self._clock()
        entries: List[Dict[str, object]] = []
        with self._lock:
            # Snapshot the health fields UNDER the lock: swap/strike
            # threads mutate them concurrently (an hvdsan read-site
            # catch — the swap suite runs instrumented).
            reps = list(self._replicas)
            for rep in reps:
                entries.append({
                    "name": rep.spec.name,
                    "role": rep.spec.role,
                    "healthy": self._healthy(rep, now),
                    "draining": rep.draining,
                    "strikes": rep.strikes,
                    "inflight": rep.inflight,
                    "completed": rep.completed,
                    "failed": rep.failed,
                    "weights_version": rep.weights_version,
                })

        # Fetch threads write into their own holders, NOT the returned
        # entries: a thread that outlives the deadline must not mutate
        # a snapshot the caller is already iterating (the controller
        # reads these mid-control-round).
        holders: List[Dict[str, object]] = [{} for _ in reps]

        def fetch(rep: _ReplicaState, holder: Dict[str, object]) -> None:
            try:
                resp = self._client(rep).request(StatsRequest(),
                                                 idempotent=False,
                                                 timeout=timeout)
                holder["stats"] = resp.stats
                # Stats are a second version source beside responses —
                # an idle replica's flip becomes router-visible on the
                # next controller poll, not only on its next request.
                self._note_version(rep,
                                   resp.stats.get("weights_version"))
            except OSError as e:
                holder["stats_error"] = str(e)

        if self._client_factory is not None:
            # In-process transport (simulation/tests): the "wire" is a
            # deterministic method call, so there is nothing to overlap
            # and thread interleaving would only cost reproducibility —
            # at 1000 simulated replicas per control round, it would
            # also dominate the simulator's CPU budget.
            for rep, holder in zip(reps, holders):
                fetch(rep, holder)
            for entry, holder in zip(entries, holders):
                entry.update(holder)
        else:
            threads = [threading.Thread(target=fetch, args=(rep, holder),
                                        daemon=True,
                                        name=f"stats-{rep.spec.name}")
                       for rep, holder in zip(reps, holders)]
            for t in threads:
                t.start()
            # One overall deadline (timeout + connect grace), not per
            # replica: the snapshot returns when the fleet answered or
            # the clock ran out, whichever is first.
            deadline = self._clock() + timeout + 1.0
            for t in threads:
                t.join(max(0.0, deadline - self._clock()))
            for entry, holder, t in zip(entries, holders, threads):
                if t.is_alive():
                    entry["stats_error"] = f"timeout after {timeout}s"
                else:
                    entry.update(holder)
        out: Dict[str, dict] = {}
        for idx, entry in enumerate(entries):
            key = str(entry["name"])
            if key in out:   # duplicate display names stay visible
                key = f"{key}[{idx}]"
            out[key] = entry
        return out
