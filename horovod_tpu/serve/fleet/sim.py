"""Fleet-scale discrete-event chaos simulator for the serving control
plane.

Every control-plane policy in the serving stack — QoS brownout, per-
role autoscaling, prefix-directory routing, health probation, rolling
swaps — is verified at 2–4 real replicas by the test suites, but its
production failure modes (shed/scale oscillation, staleness storms,
migration convoys, swap-vs-autoscaler races) only emerge at fleet
sizes CPU cannot run for real.  This module is the serving tier's
``topo/simulate.py`` move: model the scale regime, drive the REAL
policy objects through it, and assert the SLO properties as
first-class invariants.

**What is real:** the :class:`~horovod_tpu.serve.router.Router` (picks,
strikes, probation, the prefix :class:`~horovod_tpu.serve.fleet
.directory.PrefixDirectory`, version-matched routing), the
:class:`~horovod_tpu.serve.fleet.controller.FleetController` (scale
out/in, drain lifecycle, rolling swaps), the
:class:`~horovod_tpu.serve.qos.brownout.QosGate`/
``BrownoutController`` ladder, and each replica's
:class:`~horovod_tpu.serve.qos.sched.QosQueue` — the simulator calls
their methods, it does not reimplement them.  The fault hooks that
live inside those code paths (``qos:invert`` in the WFQ pop,
``qos:flood`` in the gate's charge, ``swap:partial-fleet`` at the
roll's batch boundary) fire through the REAL ``faults.py`` plan.

**What is simulated:** wall time (a virtual clock the injected
``clock`` seams read), the wire (:class:`~horovod_tpu.serve.fleet
.sim_replica.LocalClient` through the router's ``client_factory``
seam), and the data plane — token generation becomes a seeded
lognormal latency draw from measured artifacts
(:mod:`~horovod_tpu.serve.fleet.traces`).  Fault sites with hooks
inside UN-driven code (``serve:kill``/``migrate-*``, ``dcn:*``,
``swap:stall``) are interpreted by the simulator against the same
parsed :class:`~horovod_tpu.config.FaultClause` plan — one grammar,
two interpreters (docs/fleet_sim.md).

No threads, no wall-clock reads in the event loop: same seed + trace
⇒ byte-identical event log, the replay/debugging contract
``tests/test_fleet_sim.py`` pins.
"""

from __future__ import annotations

import heapq
import json
import random
from typing import Dict, List, Optional, Sequence, Tuple

from ... import faults as faults_mod
from ...obs import instrument as _obs
from ...utils.logging import get_logger
from ...utils.retry import RetryPolicy
from ..qos.brownout import MAX_LEVEL, BrownoutController, QosGate
from ..qos.policy import RequestShedError
from ..router import NoHealthyReplicasError, Router
from .controller import FleetController, ReplicaLauncher
from .sim_replica import SWAP_PULL_BYTES, LocalClient, SimReplica
from .traces import ReplicaProfile, SimRequest, load_profile

logger = get_logger(__name__)

# A request that cannot land after this many routing attempts is LOST —
# the invariant, not a quiet drop.  Generous: a full fleet bench clears
# within a few probation windows of retries.
MAX_ROUTE_ATTEMPTS = 60

_PCTS = (0.50, 0.99)


def _pct(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1,
                       int(q * (len(ordered) - 1) + 0.999))]


class InvariantBook:
    """The SLO invariant catalog as checkable properties: every check
    is counted, every violation recorded with the event context that
    produced it (the postmortem the replay contract re-derives)."""

    NAMES = ("never_shed_interactive", "no_ladder_oscillation",
             "bounded_directory_staleness", "no_migration_convoy",
             "swap_autoscaler_non_interference", "at_most_once",
             "no_lost_requests")

    def __init__(self) -> None:
        self.checks: Dict[str, int] = {n: 0 for n in self.NAMES}
        self.violations: List[dict] = []

    def check(self, name: str, ok: bool, t: float, **detail) -> bool:
        self.checks[name] += 1
        if not ok:
            self.violations.append(
                {"invariant": name, "t": round(t, 6), **detail})
        return ok

    def summary(self) -> dict:
        return {"checks": dict(self.checks),
                "checks_total": sum(self.checks.values()),
                "violations_total": len(self.violations),
                "violations": list(self.violations)}


class _SimLauncher(ReplicaLauncher):
    """The controller's deployment interface, backed by the sim."""

    def __init__(self, sim: "FleetSim") -> None:
        self._sim = sim

    def launch(self, role: str, host: Optional[str] = None):
        return self._sim._launch(role).spec

    def retire(self, name: str) -> None:
        self._sim._retire(name)


class FleetSim:
    """Seeded discrete-event simulation of one serving fleet."""

    def __init__(self, *, replicas: int = 4, seed: int = 0,
                 roles: Optional[Dict[str, int]] = None,
                 profile: Optional[ReplicaProfile] = None,
                 max_slots: int = 8,
                 queue_capacity: int = 64,
                 brownout_high: float = 0.75,
                 brownout_low: float = 0.25,
                 brownout_hold_s: float = 5.0,
                 slo_ttft_ms: float = 0.0,
                 strikes: int = 2,
                 probation_s: float = 10.0,
                 min_per_role: int = 1,
                 max_replicas: Optional[int] = None,
                 scale_out_queue: float = 4.0,
                 scale_out_ttft_ms: float = 0.0,
                 scale_in_idle_s: float = 30.0,
                 drain_deadline_s: float = 60.0,
                 control_period_s: float = 1.0,
                 oscillation_window_s: Optional[float] = None,
                 oscillation_bound: int = 2 * MAX_LEVEL + 2,
                 staleness_bound_s: Optional[float] = None,
                 convoy_bound: Optional[int] = None,
                 record_events: bool = True) -> None:
        self.seed = int(seed)
        self._rng = random.Random(self.seed)
        self.profile = profile if profile is not None else load_profile()
        self.max_slots = int(max_slots)
        self.control_period_s = float(control_period_s)
        self.record_events = bool(record_events)
        # Invariant bounds: oscillation is judged over ten hold windows
        # (hysteresis permits at most one down-step per hold), a stale
        # directory route must die within two control rounds of the
        # invalidating event, and a decode target may absorb at most
        # two slots' worth of concurrent migrations.
        self.oscillation_window_s = float(
            oscillation_window_s if oscillation_window_s is not None
            else 10.0 * brownout_hold_s)
        self.oscillation_bound = int(oscillation_bound)
        self.staleness_bound_s = float(
            staleness_bound_s if staleness_bound_s is not None
            else 2.0 * control_period_s + 1.0)
        self.convoy_bound = int(convoy_bound if convoy_bound is not None
                                else 2 * max_slots)

        self._now = 0.0
        self._seq = 0
        self._heap: List[tuple] = []
        self.events: List[dict] = []
        self.invariants = InvariantBook()

        # --- the fleet -------------------------------------------------------
        self._weights_step = 1   # what a fresh launch deploys
        self._replicas: Dict[str, SimReplica] = {}
        self._retired: Dict[str, SimReplica] = {}
        self._role_seq: Dict[str, int] = {}
        role_counts = dict(roles) if roles else {"unified": int(replicas)}
        specs = []
        for role in sorted(role_counts):
            for _ in range(role_counts[role]):
                specs.append(self._launch(role, register=False).spec)
        self.has_roles = ("prefill" in role_counts
                         and "decode" in role_counts)

        # --- the REAL control-plane objects, under the virtual clock --------
        self.router = Router(
            specs, key=b"sim",
            retry_policy=RetryPolicy(attempts=1, base_delay_s=0.0,
                                     max_delay_s=0.0, jitter=0.0),
            strikes=strikes, probation_s=probation_s,
            clock=self.now,
            client_factory=lambda spec: LocalClient(self, spec.name))
        self.gate = QosGate(brownout=BrownoutController(
            queue_capacity=queue_capacity, high=brownout_high,
            low=brownout_low, hold_s=brownout_hold_s,
            slo_ttft_ms=slo_ttft_ms, clock=self.now))
        self.router.attach_qos(self.gate)
        self.controller = FleetController(
            self.router, _SimLauncher(self),
            min_per_role=min_per_role,
            max_replicas=(max_replicas if max_replicas is not None
                          else len(specs) + 8),
            scale_out_queue=scale_out_queue,
            scale_out_ttft_ms=scale_out_ttft_ms,
            scale_in_idle_s=scale_in_idle_s,
            drain_deadline_s=drain_deadline_s,
            stats_timeout_s=1.0, clock=self.now)

        # --- per-request bookkeeping ----------------------------------------
        self._key_of: Dict[str, tuple] = {}
        self._req_of: Dict[str, SimRequest] = {}
        self._attempts: Dict[str, int] = {}
        self._force_unified: set = set()
        self._outcome: Dict[str, str] = {}   # rid -> delivered|shed|expired
        self._delivered_at: Dict[str, float] = {}
        self._ttft_by_class: Dict[str, List[float]] = {}
        self._migrating_to: Dict[str, int] = {}
        self._level_transitions: List[Tuple[float, int, int]] = []
        self._last_level = 0
        self._pending_roll: Optional[dict] = None
        self._flood_seq = 0
        self._state_cache: Dict[str, object] = {}
        # Telemetry plane (attach_telemetry): the REAL obs/collector
        # objects, advanced by "collect" events on the virtual clock.
        self._telemetry = None
        self.alerts: List[dict] = []
        self._shed_interactive = 0
        self._drains_started = 0
        self._spiral_onset_t: Optional[float] = None
        self._convoy_skip: set = set()   # rids the control:convoy fault
        #   admitted WITHOUT a decode reservation (the pre-fix bug)
        self.counters: Dict[str, int] = {
            "arrivals": 0, "delivered": 0, "shed": 0, "expired": 0,
            "retries": 0, "kills": 0, "migrations_ok": 0,
            "migrations_failed": 0, "stale_directory_hits": 0,
            "duplicates_suppressed": 0, "faults_fired": 0,
            "scale_out": 0, "scale_in": 0,
        }

    # --- virtual clock (the seam the real objects read) ----------------------

    def now(self) -> float:
        return self._now

    # --- replica registry ----------------------------------------------------

    def _launch(self, role: str, register: bool = True) -> SimReplica:
        idx = self._role_seq.get(role, 0)
        self._role_seq[role] = idx + 1
        # A fresh launch deploys the fleet's CURRENT target step (the
        # launcher pulls from the checkpoint store) — scale-out during
        # a roll's convergence window must not look like divergence.
        rep = SimReplica(f"sim-{role}-{idx:04d}", role, self.profile,
                         self._rng.randrange(1 << 31),
                         max_slots=self.max_slots,
                         weights_version=self._weights_step)
        self._replicas[rep.name] = rep
        if register:
            self._log("launch", replica=rep.name, role=role)
        return rep

    def _retire(self, name: str) -> None:
        rep = self._replicas.pop(name, None)
        self._state_cache.pop(name, None)
        if rep is not None:
            rep.alive = False
            self._retired[name] = rep
            self._log("retire", replica=name)

    def live_replica(self, name: str) -> Optional[SimReplica]:
        """The transport's liveness lookup (None ⇒ ConnectionError up
        the stack — the closed socket of the simulation)."""
        rep = self._replicas.get(name)
        return rep if rep is not None and rep.alive else None

    def _router_state(self, name: str):
        """The router's ``_ReplicaState`` for ``name``, cached —
        ``Router._find`` is a linear scan, and the event loop touches
        replica state several times per request at 1000 replicas."""
        state = self._state_cache.get(name)
        if state is None or state.spec.name != name:
            state = self.router._find(name)
            if state is not None:
                self._state_cache[name] = state
        return state

    def _mirror_inflight(self, name: str, delta: int) -> None:
        """Keep the router's load view current: real traffic would move
        ``inflight`` inside ``Router.generate``; the sim's event-driven
        data plane mirrors it under the SAME lock."""
        state = self._router_state(name)
        if state is None:
            return
        with self.router._lock:
            state.inflight = max(0, state.inflight + delta)

    # --- event plumbing ------------------------------------------------------

    def _schedule(self, t: float, kind: str, **data) -> None:
        self._seq += 1
        heapq.heappush(self._heap, (max(t, self._now), self._seq, kind,
                                    data))

    def _log(self, kind: str, **fields) -> None:
        if self.record_events:
            self.events.append({"t": round(self._now, 6), "kind": kind,
                                **fields})

    def event_log_text(self) -> str:
        """The canonical serialization the determinism tests compare
        byte-for-byte."""
        return "\n".join(json.dumps(e, sort_keys=True)
                         for e in self.events)

    # --- fault interpretation (sim-side sites) -------------------------------

    def _consult_fault(self, site: str, modes: Tuple[str, ...]):
        """Consult the armed fault plan for a site whose real hook
        lives in code the sim does not drive — same clause grammar,
        counters, seeded RNG and firing history as the real hooks
        (``faults.py``); returns the clause when it fires."""
        plan = faults_mod._active
        if plan is None:
            return None
        st = plan.site(site)
        if st is None or (st.clause.mode or modes[0]) not in modes:
            return None
        at = st.counter
        if st.should_fire():
            mode = st.clause.mode or modes[0]
            plan.fire(site, mode, at)
            self.counters["faults_fired"] += 1
            self._log("fault", site=site, mode=mode, at=at)
            return st.clause
        return None

    # --- the run -------------------------------------------------------------

    def run(self, trace: Sequence[SimRequest], *,
            fault_spec: Optional[str] = None,
            swap_rolls: Sequence[Tuple[float, int]] = (),
            horizon_s: Optional[float] = None) -> dict:
        """Replay ``trace`` to completion (or ``horizon_s``); returns
        the report dict (metrics + invariant summary).  ``swap_rolls``
        schedules ``(virtual_time, step)`` rolling weight swaps;
        ``fault_spec`` arms the standard fault grammar for the run."""
        for req in trace:
            self._schedule(req.arrival_s, "arrive", req=req)
        horizon = float(horizon_s) if horizon_s is not None else (
            trace[-1].arrival_s + 120.0 if trace else 0.0)
        t_ctl = 0.0
        while t_ctl <= horizon:
            self._schedule(t_ctl, "control")
            t_ctl += self.control_period_s
        if self._telemetry is not None:
            # One collection round per plane period; scheduled AFTER
            # the control events at the same timestamp, so the
            # controller reads the PREVIOUS round (the production
            # ordering: the plane scrapes on its own cadence).
            t_col = self._telemetry.period_s
            while t_col <= horizon:
                self._schedule(t_col, "collect")
                t_col += self._telemetry.period_s
        for t_roll, step in swap_rolls:
            self._schedule(t_roll, "swap_roll", step=int(step))

        if fault_spec:
            with faults_mod.inject(fault_spec):
                self._drain_heap(horizon)
        else:
            self._drain_heap(horizon)
        report = self._report(horizon)
        _obs.on_sim_run(events=report["events"],
                        checks=report["invariants"]["checks_total"],
                        violations=report["invariants"]
                        ["violations_total"])
        return report

    def _drain_heap(self, horizon: float) -> None:
        handlers = {
            "arrive": self._on_arrive, "retry": self._on_retry,
            "dispatch": self._on_dispatch,
            "first_token": self._on_first_token,
            "finish": self._on_finish,
            "migrate_done": self._on_migrate_done,
            "control": self._on_control,
            "collect": self._on_collect,
            "swap_roll": self._on_swap_roll,
        }
        while self._heap:
            t, _, kind, data = heapq.heappop(self._heap)
            if t > horizon:
                break
            self._now = t
            handlers[kind](**data)

    # --- request lifecycle ---------------------------------------------------

    def _on_arrive(self, req: SimRequest) -> None:
        self.counters["arrivals"] += 1
        self._req_of[req.request_id] = req
        self._key_of[req.request_id] = self.router._prefix_key(req.prompt)
        # qos:flood — a synthetic burst of batch traffic from a flood
        # tenant (the gate's budget-waiver hook needs wall-clock token
        # buckets, which a deterministic sim cannot run; the sim's
        # interpretation of the same clause is the flood itself).
        if req.tenant != "flood" \
                and self._consult_fault("qos", ("flood",)) is not None:
            for _ in range(100):
                self._flood_seq += 1
                flood = SimRequest(
                    request_id=f"flood-{self._flood_seq:05d}",
                    arrival_s=self._now, tenant="flood",
                    qos_class="batch", prompt=req.prompt,
                    max_new_tokens=req.max_new_tokens, deadline=None)
                self._schedule(self._now, "arrive", req=flood)
        try:
            # The REAL gate: brownout shed (and the qos:flood fault's
            # budget waiver) fire inside this call.
            self.gate.admit(req.tenant, req.qos_class, 0.0)
        except RequestShedError:
            self.counters["shed"] += 1
            if req.qos_class == "interactive":
                self._shed_interactive += 1
            self._outcome[req.request_id] = "shed"
            self.invariants.check(
                "never_shed_interactive",
                req.qos_class != "interactive", self._now,
                request=req.request_id, qos_class=req.qos_class,
                level=self.gate.brownout.level)
            self._log("shed", request=req.request_id,
                      qos_class=req.qos_class)
            return
        self._route(req)

    def _on_retry(self, req: SimRequest) -> None:
        self.counters["retries"] += 1
        self._route(req)

    def _fail_over(self, req: SimRequest) -> None:
        attempt = self._attempts.get(req.request_id, 0) + 1
        self._attempts[req.request_id] = attempt
        if not self.invariants.check(
                "no_lost_requests", attempt <= MAX_ROUTE_ATTEMPTS,
                self._now, request=req.request_id, attempts=attempt):
            self._outcome[req.request_id] = "lost"
            self._log("lost", request=req.request_id)
            return
        # Deterministic capped backoff standing in for the router's
        # jittered RetryPolicy (jitter would break replay): quick first
        # sweeps, then probation-scale waits.
        delay = min(2.0, 0.02 * (1 << min(attempt, 7)))
        self._schedule(self._now + delay, "retry", req=req)

    def _route(self, req: SimRequest) -> None:
        rid = req.request_id
        key = self._key_of.get(rid)
        # 1. The real directory route (warm KV anywhere in the fleet).
        state = self.router._directory_pick(key)
        via = "directory"
        if state is not None:
            rep = self._replicas.get(state.spec.name)
            # Ground truth vs the directory's belief: a route to a
            # replica that no longer holds the blocks (flushed, killed,
            # retired) is STALE — tolerated briefly (it only costs a
            # cache miss or one failover), a violation once the
            # invalidation machinery has had two control rounds to
            # catch up.
            if rep is None or not rep.alive or key not in rep.resident:
                self.counters["stale_directory_hits"] += 1
                invalidated = rep.invalidated_at if rep is not None \
                    else None
                since = (self._now - invalidated
                         if invalidated is not None else 0.0)
                self.invariants.check(
                    "bounded_directory_staleness",
                    since <= self.staleness_bound_s, self._now,
                    request=rid, replica=state.spec.name,
                    stale_for_s=round(since, 3))
        # 2. The disaggregated pipeline when both role tiers are live.
        if state is None and self.has_roles \
                and rid not in self._force_unified:
            pre = self.router._pick_role("prefill")
            dec = self.router._pick_role("decode")
            if pre is not None and dec is not None:
                self._admit(req, pre.spec.name, via="pipeline",
                            decode_to=dec.spec.name)
                return
        # 3. The unified spread (and the recompute fallback).
        if state is None:
            try:
                state = self.router._pick(key)
                via = "spread"
            except NoHealthyReplicasError:
                self._log("no_healthy", request=rid)
                self._fail_over(req)
                return
        rep = self._replicas.get(state.spec.name)
        if rep is None or not rep.alive:
            # The pick landed on a dead replica (a half-open probe, or
            # a kill the router has not yet observed): strike it for
            # real — this is exactly the failover path — and re-route.
            self.router._strike(state, fatal=True)
            self._log("probe_dead", request=rid, replica=state.spec.name)
            self._fail_over(req)
            return
        self._admit(req, rep.name, via=via)

    def _admit(self, req: SimRequest, name: str, via: str,
               decode_to: Optional[str] = None) -> None:
        rep = self._replicas[name]
        rep.queue.push(req)          # the REAL WFQ admission
        self._mirror_inflight(name, +1)
        if decode_to is not None:
            # Mirror the router's migration reservation: the decode
            # target carries the inbound load from pick time, so
            # concurrent pipeline picks spread instead of convoying
            # into one receiver.  Released on migration failure /
            # expiry / kill; a successful adoption converts it into
            # the active count.  control:mode=convoy re-introduces the
            # pre-fix bug: the reservation is deferred to adoption
            # time, so concurrent picks all see the target as idle —
            # the exact convoy the telemetry plane's detector pages on.
            if self._consult_fault("control", ("convoy",)) is not None:
                self._convoy_skip.add(req.request_id)
            else:
                self._mirror_inflight(decode_to, +1)
        self._outcome.pop(req.request_id, None)
        rep.pipeline_to[req.request_id] = decode_to
        if via == "directory":
            _obs.on_fleet_directory_hit()
        self._log("admit", request=req.request_id, replica=name,
                  via=via)
        self._schedule(self._now, "dispatch", replica=name)

    def _on_dispatch(self, replica: str) -> None:
        rep = self._replicas.get(replica)
        if rep is None or not rep.alive:
            return
        # The real deadline machinery: expired queued work dies here.
        for dead in rep.queue.pop_expired(self._now):
            self.counters["expired"] += 1
            self._outcome[dead.request_id] = "expired"
            self._mirror_inflight(replica, -1)
            reserved = rep.pipeline_to.pop(dead.request_id, None)
            if reserved is not None \
                    and dead.request_id not in self._convoy_skip:
                self._mirror_inflight(reserved, -1)
            self._convoy_skip.discard(dead.request_id)
            self._log("expired", request=dead.request_id,
                      replica=replica)
        while rep.alive and len(rep.active) < rep.max_slots:
            req = rep.queue.pop()    # the REAL WFQ pop (qos:invert
            if req is None:          # fires inside, when armed)
                break
            # serve:kill — replica death at the dispatch boundary, the
            # batcher-step analog of the real site.
            if self._consult_fault("serve", ("kill",)) is not None:
                rep.active[req.request_id] = req
                self._kill(rep)
                return
            rep.active[req.request_id] = req
            ttft_ms = rep.sample_ttft_ms()
            self._schedule(self._now + ttft_ms / 1e3, "first_token",
                           replica=rep.name, epoch=rep.epoch,
                           rid=req.request_id, ttft_ms=ttft_ms)

    def _on_first_token(self, replica: str, epoch: int, rid: str,
                        ttft_ms: float) -> None:
        rep = self._replicas.get(replica)
        if rep is None or rep.epoch != epoch or rid not in rep.active:
            return   # stale: the replica died after scheduling this
        req = rep.active[rid]
        ttft = (self._now - req.arrival_s) * 1e3
        rep.record_ttft(req.qos_class, ttft)
        self._ttft_by_class.setdefault(req.qos_class, []).append(ttft)
        decode_to = rep.pipeline_to.get(rid)
        if decode_to is not None:
            self._start_migration(rep, req, decode_to)
            return
        self._schedule(
            self._now + rep.sample_decode_ms(req.max_new_tokens) / 1e3,
            "finish", replica=rep.name, epoch=epoch, rid=rid)

    def _on_finish(self, replica: str, epoch: int, rid: str) -> None:
        rep = self._replicas.get(replica)
        if rep is None or rep.epoch != epoch or rid not in rep.active:
            return
        req = rep.active.pop(rid)
        rep.pipeline_to.pop(rid, None)
        rep.completed += 1
        self._mirror_inflight(replica, -1)
        state = self._router_state(replica)
        key = self._key_of.get(rid)
        if state is not None:
            self.router._mark_ok(state)
            # The real directory learns the residency; the sim's ground
            # truth learns it too (the staleness oracle).
            self.router._note_affinity(key, state, rep.weights_version)
        if key is not None:
            rep.resident.add(key)
        self._deliver(req)
        self._schedule(self._now, "dispatch", replica=replica)

    def _deliver(self, req: SimRequest) -> None:
        rid = req.request_id
        dup = rid in self._delivered_at
        self.invariants.check("at_most_once", not dup, self._now,
                              request=rid)
        if dup:
            self.counters["duplicates_suppressed"] += 1
            return
        self._delivered_at[rid] = self._now
        self._outcome[rid] = "delivered"
        self.counters["delivered"] += 1
        self._log("deliver", request=rid)

    # --- disaggregated pipeline ----------------------------------------------

    def _start_migration(self, pre: SimReplica, req: SimRequest,
                         decode_to: str) -> None:
        rid = req.request_id
        ms = pre.sample_migrate_ms()
        ok = True
        clause = self._consult_fault(
            "serve", ("migrate-drop", "migrate-delay"))
        if clause is not None:
            if (clause.mode or "") == "migrate-drop":
                ok = False
            else:
                ms += max(0.0, clause.delay_ms)
        dcn = self._consult_fault("dcn", ("drop", "delay", "partition"))
        if dcn is not None:
            if (dcn.mode or "drop") in ("drop", "partition"):
                ok = False
            else:
                ms += max(0.0, dcn.delay_ms)
        conc = self._migrating_to.get(decode_to, 0) + 1
        self._migrating_to[decode_to] = conc
        self.invariants.check("no_migration_convoy",
                              conc <= self.convoy_bound, self._now,
                              decode=decode_to, concurrent=conc)
        self._log("migrate", request=rid, source=pre.name,
                  target=decode_to, ok=ok)
        self._schedule(self._now + ms / 1e3, "migrate_done",
                       pre=pre.name, epoch=pre.epoch, rid=rid,
                       decode_to=decode_to, ok=ok, ms=ms)

    def _on_migrate_done(self, pre: str, epoch: int, rid: str,
                         decode_to: str, ok: bool, ms: float) -> None:
        self._migrating_to[decode_to] = max(
            0, self._migrating_to.get(decode_to, 0) - 1)
        rep = self._replicas.get(pre)
        if rep is None or rep.epoch != epoch or rid not in rep.active:
            return   # prefill died mid-transfer: the kill path retried
        req = rep.active.pop(rid)
        rep.pipeline_to.pop(rid, None)
        self._mirror_inflight(pre, -1)
        _obs.on_fleet_migration(len(req.prompt) * 8, ok, ms)
        state = self._router_state(pre)
        dec = self._replicas.get(decode_to)
        key = self._key_of.get(rid)
        if not ok or dec is None or not dec.alive:
            self.counters["migrations_failed"] += 1
            rep.failed += 1
            if rid in self._convoy_skip:
                self._convoy_skip.discard(rid)   # never reserved
            else:
                self._mirror_inflight(decode_to, -1)   # drop the reservation
            # The router's semantics: a lost transfer recomputes on the
            # unified path — never wrong tokens, at worst one redundant
            # prefill.
            self._force_unified.add(rid)
            self._log("migrate_failed", request=rid, source=pre,
                      target=decode_to)
            self._fail_over(req)
            return
        self.counters["migrations_ok"] += 1
        rep.completed += 1
        if state is not None:
            self.router._mark_ok(state)
            self.router._note_affinity(key, state, rep.weights_version)
        if key is not None:
            rep.resident.add(key)
        # Decode adopts directly (the real adopt path bypasses the
        # admission queue); the reservation taken at pick time now
        # counts the adopted generation, so no further increment —
        # _on_finish releases it.  Under control:convoy the count only
        # appears NOW (too late for pick spread — the bug).
        if rid in self._convoy_skip:
            self._convoy_skip.discard(rid)
            self._mirror_inflight(decode_to, +1)
        dec.active[rid] = req
        dec.pipeline_to[rid] = None
        self._schedule(
            self._now + dec.sample_decode_ms(req.max_new_tokens) / 1e3,
            "finish", replica=decode_to, epoch=dec.epoch, rid=rid)

    # --- faults --------------------------------------------------------------

    def _kill(self, rep: SimReplica) -> None:
        self.counters["kills"] += 1
        rep.invalidated_at = self._now
        pipes = dict(rep.pipeline_to)   # kill() clears it
        orphans = rep.kill()
        self._log("kill", replica=rep.name, orphans=len(orphans))
        for req in orphans:
            self._mirror_inflight(rep.name, -1)
            reserved = pipes.get(req.request_id)
            if reserved is not None \
                    and req.request_id not in self._convoy_skip:
                self._mirror_inflight(reserved, -1)
            self._convoy_skip.discard(req.request_id)
            self._fail_over(req)

    # --- telemetry plane -----------------------------------------------------

    def attach_telemetry(self, *, slo_spec: Optional[str] = None,
                         period_s: Optional[float] = None,
                         stale_after_s: Optional[float] = None,
                         journal_path: Optional[str] = None,
                         detect_overrides: Optional[dict] = None):
        """Wire the live telemetry plane into the simulated fleet: the
        SAME :class:`~horovod_tpu.obs.collector.FleetCollector`/
        ``SloBook``/``DetectorBook`` objects production runs, scraping
        through the ``LocalClient`` transport under the virtual clock
        (the acceptance rig: detectors proven against the REAL control
        plane at 1000 replicas — docs/observability.md).  ``run``
        schedules one "collect" event per plane period; fired alerts
        land in the event log and ``self.alerts``.  The controller is
        re-pointed at the collector's rounds — one fleet fan-out per
        period, shared by scaling and alerting."""
        from ...obs.collector import (FleetCollector, Target,
                                      TelemetryPlane)

        period = float(period_s if period_s is not None
                       else self.control_period_s)
        collector = FleetCollector(
            lambda: [Target(name=name, role=rep.role)
                     for name, rep in sorted(self._replicas.items())],
            clock=self.now,
            client_factory=lambda tg: LocalClient(self, tg.name),
            timeout_s=1.0)
        overrides = {
            "convoy_bound": float(self.convoy_bound),
            "oscillation_bound": self.oscillation_bound,
            "oscillation_window_s": self.oscillation_window_s,
        }
        overrides.update(detect_overrides or {})
        self._telemetry = TelemetryPlane(
            collector, slo_spec=slo_spec,
            control_probe=self._control_probe, period_s=period,
            stale_after_s=(stale_after_s if stale_after_s is not None
                           else max(10.0, self.staleness_bound_s)),
            journal_path=journal_path, detect_overrides=overrides)
        self.controller._collector = collector
        return self._telemetry

    def _control_probe(self) -> dict:
        """The detector book's control-plane signals from the sim's
        own state (a production wiring reads the same fields off the
        router/controller/QoS gate — obs/detect.py module docstring).
        ``scale_in_total`` counts DRAIN starts: the drain is when
        capacity leaves the load balancer, which is the round the
        death-spiral signature must be caught in."""
        return {
            "brownout_level": self.gate.brownout.level,
            "scale_in_total": self._drains_started,
            "shed_interactive_total": self._shed_interactive,
            "swap_target_version": self._weights_step,
            "directory_replicas": self.router._directory.replicas(),
        }

    def _on_collect(self) -> None:
        # One plane round on the virtual clock: scrape (serial through
        # LocalClient — deterministic), SLO burn rates, detectors,
        # alert edges.
        if self._telemetry is None:
            return
        for alert in self._telemetry.run_round(now=self._now):
            self.alerts.append(alert)
            self._log("alert", alert=alert["alert"],
                      severity=alert["severity"])

    # --- control plane -------------------------------------------------------

    def _on_control(self) -> None:
        # The REAL policy loop: serial stats through the LocalClient
        # transport, brownout observe, scale out/in, drain completion.
        actions = self.controller.poll_once(now=self._now)
        level = self.gate.brownout.level
        if level != self._last_level:
            self._level_transitions.append(
                (self._now, self._last_level, level))
            self._last_level = level
            window = [tr for tr in self._level_transitions
                      if tr[0] > self._now - self.oscillation_window_s]
            self.invariants.check(
                "no_ladder_oscillation",
                len(window) <= self.oscillation_bound, self._now,
                transitions_in_window=len(window),
                window_s=self.oscillation_window_s)
            self._log("brownout", level=level)
        for action in actions:
            self._log("scale", **action)
            if action["action"] == "scale_out":
                self.counters["scale_out"] += 1
            elif action["action"] == "drain":
                self._drains_started += 1
                # Ground truth for the death-spiral drill: the first
                # drain issued while the ladder sheds is the onset the
                # ladder_oscillation detector races against.
                if level > 0 and self._spiral_onset_t is None:
                    self._spiral_onset_t = self._now
            elif action["action"] == "retire":
                self.counters["scale_in"] += 1
            if self._pending_roll is not None \
                    and action["action"] in ("drain", "retire"):
                # Interference: the autoscaler shrank the fleet while a
                # swap roll was still converging.
                self._pending_roll["scale_in_during_roll"] += 1
        self._check_roll_convergence()

    def _on_swap_roll(self, step: int) -> None:
        self._weights_step = int(step)
        # max_concurrent=1 serializes the roll's worker threads — the
        # only thread use in a sim run, one at a time and joined before
        # the next, so the event log stays deterministic.  The
        # swap:partial-fleet fault fires inside the REAL roll_swap.
        outcomes = self.controller.roll_swap(step, max_concurrent=1,
                                             timeout=5.0)
        ok = sum(1 for o in outcomes if o.get("ok"))
        aborted = any(o.get("skipped") for o in outcomes)
        self._log("swap_roll", step=step, ok=ok, total=len(outcomes),
                  aborted=aborted)
        self._pending_roll = {
            "step": step, "t": self._now, "aborted": aborted,
            # Only replicas whose swap SUCCEEDED owe convergence: a
            # stalled/failed pull keeps old weights by design, and the
            # version-matched routing rule keeps the mixed fleet
            # correct (docs/hot_swap.md).
            "flipped": [o["replica"] for o in outcomes if o.get("ok")],
            "scale_in_during_roll": 0,
            "deadline": self._now + 3.0 * self.control_period_s}

    def swap_replica_sim(self, rep: SimReplica, step: int, *,
                         rollback: bool = False):
        """The transport's swap handler: sampled pull+flip latency,
        ``swap:stall`` interpreted as virtual delay (the real hook
        sleeps — a sim must not), KV flushed on flip so the directory's
        version rule is exercised for real."""
        from types import SimpleNamespace
        ms = rep.sample_swap_ms()
        clause = self._consult_fault("swap", ("stall",))
        if clause is not None:
            # The real hook wall-sleeps ``delay_ms`` inside the pull;
            # the sim interprets the same clause as virtual delay past
            # the pull deadline — abandoned, old weights keep serving
            # (serve/swap.py semantics).
            self._log("swap_stalled", replica=rep.name, step=step)
            return SimpleNamespace(
                error="pull_stalled_past_deadline",
                weights_version=rep.weights_version,
                swap_ms=None, pulled_bytes=0)
        rep.weights_version = int(step)
        rep.flush_kv()
        rep.invalidated_at = self._now
        self._log("swap", replica=rep.name, step=step,
                  rollback=rollback)
        return SimpleNamespace(error=None, weights_version=int(step),
                               swap_ms=ms,
                               pulled_bytes=SWAP_PULL_BYTES)

    def _check_roll_convergence(self) -> None:
        roll = self._pending_roll
        if roll is None or self._now < roll["deadline"]:
            return
        self._pending_roll = None
        if roll["aborted"]:
            return   # a fault-aborted roll converges by design later
        converged = all(
            rep.weights_version == roll["step"]
            for rep in (self._replicas.get(name)
                        for name in roll["flipped"])
            if rep is not None and rep.alive and not rep.draining)
        self.invariants.check(
            "swap_autoscaler_non_interference",
            converged and roll["scale_in_during_roll"] == 0, self._now,
            step=roll["step"], converged=converged,
            scale_in_during_roll=roll["scale_in_during_roll"])

    # --- reporting -----------------------------------------------------------

    def _report(self, horizon: float) -> dict:
        # Requests with no terminal outcome at the horizon are still in
        # flight (queued/active/retrying) — legitimate for an open-loop
        # trace cut off mid-stream, and reported so the bench can bound
        # it; a VANISHED request would have tripped no_lost_requests.
        unresolved = sum(1 for rid in self._req_of
                         if rid not in self._outcome)
        ttft = {}
        for cls, samples in sorted(self._ttft_by_class.items()):
            ttft[f"{cls}_ttft_ms_p50"] = _pct(samples, 0.50)
            ttft[f"{cls}_ttft_ms_p99"] = _pct(samples, 0.99)
        all_samples = [s for v in self._ttft_by_class.values()
                       for s in v]
        report = {
            "horizon_s": horizon,
            "replicas_final": len(self._replicas),
            "events": len(self.events) if self.record_events
            else self._seq,
            "requests": self.counters["arrivals"],
            "in_flight_at_horizon": unresolved,
            "ttft_ms_p50": _pct(all_samples, 0.50),
            "ttft_ms_p99": _pct(all_samples, 0.99),
            **ttft,
            **{k: v for k, v in self.counters.items()
               if k != "arrivals"},
            "brownout_level_max": max(
                [tr[2] for tr in self._level_transitions], default=0),
            "level_transitions": len(self._level_transitions),
            "invariants": self.invariants.summary(),
        }
        if self._spiral_onset_t is not None:
            report["spiral_onset_t"] = round(self._spiral_onset_t, 6)
        if self._telemetry is not None:
            report["alerts_fired"] = len(self.alerts)
            report["alerts"] = [
                {"alert": a["alert"], "t": round(a["t"], 6),
                 "severity": a["severity"]} for a in self.alerts]
        return report
