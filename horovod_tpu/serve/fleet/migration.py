"""Live KV migration: prefill→decode block streaming with digests.

The disaggregated fleet's data handoff: a prefill replica runs the
prompt, then streams the request's paged KV blocks to a decode replica
over the existing HMAC ``BasicService`` wire
(``runner/common/network.py::KvMigrateRequest``).  The slot's block
table is the transfer manifest — only live, non-trash chain blocks
move — and every block carries a sha256 digest computed over its
``[n_layer, block, H, D]`` K and V payload, so the receiver verifies
the transfer before binding anything into its own pool: a corrupted
block fails the digest check and the request finishes on a correct
path (the sender's pristine KV, or a full recompute elsewhere) — never
with wrong tokens.

Chunking: frames stay under ``HVD_TPU_FLEET_MIGRATE_CHUNK`` bytes
(block-granular — a block is the atomic unit of both transfer and
verification), so one migration is a short burst of bounded frames
instead of one giant allocation on both ends.

Fault site ``serve`` modes ``migrate`` / ``migrate-drop`` /
``migrate-delay`` fire here, at the KV-transfer boundary: ``migrate``
corrupts one block AFTER the digests were computed (the
detect-and-recover drill), ``migrate-drop`` fails the transfer on the
wire, ``migrate-delay`` stretches it (a congested DCN link).
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ... import faults as faults_mod
from ...obs import instrument as _obs
from ...obs import trace as trace_mod
from ...runner.common.network import BasicClient, KvMigrateRequest
from ...utils.logging import get_logger
from ...utils.retry import RetryPolicy
from ..engine import resolved_config

logger = get_logger(__name__)


class MigrationError(RuntimeError):
    """The KV transfer failed (wire death, digest mismatch, receiver
    refusal) — the sender falls back to decoding locally; the request
    is never lost and never continues from damaged state."""


def block_digests(k: np.ndarray, v: np.ndarray) -> List[str]:
    """Per-block sha256 over the concatenated K then V bytes of all
    layers (``k``/``v`` are ``[n_layer, n_blocks, block, H, D]``) —
    the digest format docs/serving.md documents."""
    return [hashlib.sha256(np.ascontiguousarray(k[:, j]).tobytes()
                           + np.ascontiguousarray(v[:, j]).tobytes())
            .hexdigest()
            for j in range(k.shape[1])]


def shard_digests(k: np.ndarray, v: np.ndarray, tp: int) -> List[List[str]]:
    """Per-shard, per-block digest lists for a tensor-parallel
    migration (docs/tp_serving.md): shard ``s`` owns the contiguous
    head range ``[s*H/tp, (s+1)*H/tp)`` of every block, and its digest
    list covers exactly the bytes its wire stream carries — each stream
    verifies independently, so one damaged shard fails the transfer
    without waiting for the others."""
    hs = k.shape[3] // tp
    return [block_digests(k[:, :, :, s * hs:(s + 1) * hs],
                          v[:, :, :, s * hs:(s + 1) * hs])
            for s in range(tp)]


def _check_digests(digests: List[str], n_blocks, k: np.ndarray,
                   v: np.ndarray, what: str) -> None:
    if k.shape[1] != n_blocks or len(digests) != k.shape[1]:
        raise MigrationError(
            f"migration shape mismatch: {k.shape[1]} block(s) received "
            f"{what}, manifest declares {n_blocks}")
    got = block_digests(k, v)
    for j, (want, have) in enumerate(zip(digests, got)):
        if want != have:
            raise MigrationError(f"digest_mismatch: block {j} of "
                                 f"{len(digests)} failed verification "
                                 f"{what}")


def verify_digests(manifest: dict, k: np.ndarray, v: np.ndarray) -> None:
    """Receiver-side transfer verification; raises
    :class:`MigrationError` on any mismatch — nothing unverified ever
    reaches the receiving pool."""
    _check_digests(manifest.get("digests") or [],
                   manifest.get("n_blocks"), k, v, "")


def verify_shard_digests(manifest: dict, shard: int, k: np.ndarray,
                         v: np.ndarray) -> None:
    """Per-stream verification of one shard's head slice against the
    manifest's ``shard_digests`` entry."""
    per_shard = manifest.get("shard_digests") or []
    if shard >= len(per_shard):
        raise MigrationError(
            f"shard {shard} not covered by the manifest's "
            f"{len(per_shard)} shard digest list(s)")
    _check_digests(per_shard[shard], manifest.get("n_blocks"), k, v,
                   f"(shard {shard})")


def plan_frames(n_blocks: int, per_block_bytes: int,
                chunk_bytes: int) -> List[Tuple[int, int]]:
    """Split ``n_blocks`` into ``[j0, j1)`` frame ranges so each frame
    stays under ``chunk_bytes`` (always >= 1 block per frame)."""
    per = max(1, chunk_bytes // max(1, per_block_bytes))
    return [(j, min(j + per, n_blocks)) for j in range(0, n_blocks, per)]


def migrate_slot(engine, slot: int, req, target, key: bytes, *,
                 chunk_bytes: Optional[int] = None,
                 probe_timeout: float = 5.0,
                 wire_timeout: float = 30.0) -> bool:
    """Export ``slot``'s KV from ``engine`` and stream it to ``target
    = (name, addresses)``.  Returns True once the receiver verified the
    digests and adopted the request; raises :class:`MigrationError` on
    any failure (after best-effort cancelling a partially-adopted copy
    on the receiver, so a local fallback cannot double-execute)."""
    name, addresses = target
    cfg = resolved_config()
    chunk = int(chunk_bytes or cfg.fleet_migrate_chunk)
    t0 = time.monotonic()
    nb, k, v = engine.export_slot_kv(slot)
    s = req.sampling
    manifest = {
        "request_id": req.request_id,
        "prompt": list(req.prompt),
        "tokens": list(req.tokens),
        "block_tokens": engine.kv_block,
        "n_blocks": nb,
        "digests": block_digests(k, v),
        "sampling": {"max_new_tokens": s.max_new_tokens,
                     "temperature": s.temperature, "top_k": s.top_k,
                     "stop_token": s.stop_token, "spec": s.spec},
        "deadline_s": (max(0.1, req.deadline - time.monotonic())
                       if req.deadline is not None else None),
        # Sender's post-prefill PRNG key: an idle importer adopts it so
        # temperature sampling stays bit-identical across the handoff.
        "rng": engine.export_rng(),
        # Weight hot-swap guard (serve/swap.py): the KV was computed
        # under THIS version; a receiver serving different weights must
        # refuse the adoption — decoding v(N) KV under v(N+1) weights
        # would emit silently wrong tokens.  The sender then decodes
        # locally on its own matching weights (economics lost, tokens
        # right).
        "weights_version": engine.weights_version,
        # Multi-tenant QoS (serve/qos/): the flow identity travels with
        # the request so the decode replica's weighted-fair scheduler
        # and per-class stats see the same tenant/class the router
        # admitted.
        "tenant": req.tenant,
        "qos_class": req.qos_class,
    }
    tp = int(getattr(engine, "tp", 1) or 1)
    if tp > 1:
        # Tensor-parallel sender (docs/tp_serving.md): the manifest
        # carries one digest list PER SHARD beside the whole-block
        # list, so each head-sliced wire stream verifies independently
        # on the receiver before heads are concatenated back.
        manifest["tp_degree"] = tp
        manifest["shard_digests"] = shard_digests(k, v, tp)
    nbytes = int(k.nbytes + v.nbytes)
    mode = (faults_mod.on_serve_migrate()
            if faults_mod._active is not None else None)
    sent = False
    try:
        with trace_mod.span("hvd_tpu_kv_migrate",
                            args={"request_id": req.request_id,
                                  "blocks": nb, "bytes": nbytes,
                                  "target": name}):
            if mode == "migrate-drop":
                raise MigrationError(
                    "injected migrate drop at the KV-transfer boundary")
            if mode == "migrate":
                # Corrupt AFTER digesting: the manifest describes the
                # true content, so the receiver's digest check MUST
                # reject this payload — the wrong-tokens-never drill.
                k = k.copy()
                k.reshape(-1).view(np.uint8)[:16] ^= 0xFF
            if tp > 1:
                sent = True
                _stream_shards(req.request_id, k, v, tp, manifest,
                               name, addresses, key, nb, chunk,
                               probe_timeout, wire_timeout)
            else:
                client = BasicClient(name, addresses, key,
                                     probe_timeout=probe_timeout,
                                     retry_policy=RetryPolicy(attempts=1))
                per_block = (int(k[:, :1].nbytes) + int(v[:, :1].nbytes)
                             if nb else 0)
                frames = plan_frames(nb, per_block, chunk)
                for seq, (j0, j1) in enumerate(frames):
                    sent = True
                    resp = client.request(
                        KvMigrateRequest(
                            req.request_id, seq, len(frames),
                            np.ascontiguousarray(k[:, j0:j1]),
                            np.ascontiguousarray(v[:, j0:j1]),
                            manifest=manifest if seq == 0 else None),
                        idempotent=False, timeout=wire_timeout)
                    err = getattr(resp, "error", None)
                    if err:
                        raise MigrationError(
                            f"decode replica {name}: {err}")
        ms = (time.monotonic() - t0) * 1e3
        _obs.on_fleet_migration(nbytes, True, ms)
        req.migrate_ms = round(ms, 3)
        return True
    except (OSError, MigrationError) as e:
        _obs.on_fleet_migration(nbytes, False, 0.0)
        if sent:
            # The receiver may hold a partial (or even fully adopted)
            # copy; the sender is about to decode locally, so a second
            # live generation of the same request would only burn the
            # decode replica's slots producing an answer nobody reads.
            _cancel_on_target(name, addresses, key, req.request_id)
        logger.warning("KV migration of %s to %s failed: %s",
                       req.request_id, name, e)
        raise MigrationError(str(e)) from e


def _stream_shards(request_id: str, k: np.ndarray, v: np.ndarray,
                   tp: int, manifest: dict, name, addresses, key: bytes,
                   nb: int, chunk: int, probe_timeout: float,
                   wire_timeout: float) -> None:
    """Stream a TP sender's KV shard-to-shard in parallel: one thread
    and one wire connection per head shard, each carrying only its
    ``H/tp`` heads of every block (so TP cuts per-stream migration
    bytes AND wall time ~linearly).  The manifest rides every shard's
    first frame — streams race, and the receiver needs it no matter
    which lands first.  Any shard failure fails the whole transfer
    (the sender falls back to decoding locally; a half-headed adoption
    is never possible because the receiver binds nothing until every
    shard verified)."""
    hs = k.shape[3] // tp
    errors: List[Optional[Exception]] = [None] * tp

    def run(shard: int) -> None:
        try:
            ks = np.ascontiguousarray(k[:, :, :, shard * hs:(shard + 1) * hs])
            vs = np.ascontiguousarray(v[:, :, :, shard * hs:(shard + 1) * hs])
            client = BasicClient(name, addresses, key,
                                 probe_timeout=probe_timeout,
                                 retry_policy=RetryPolicy(attempts=1))
            per_block = (int(ks[:, :1].nbytes) + int(vs[:, :1].nbytes)
                         if nb else 0)
            frames = plan_frames(nb, per_block, chunk)
            for seq, (j0, j1) in enumerate(frames):
                resp = client.request(
                    KvMigrateRequest(
                        request_id, seq, len(frames),
                        np.ascontiguousarray(ks[:, j0:j1]),
                        np.ascontiguousarray(vs[:, j0:j1]),
                        manifest=manifest if seq == 0 else None,
                        shard=shard, n_shards=tp),
                    idempotent=False, timeout=wire_timeout)
                err = getattr(resp, "error", None)
                if err:
                    raise MigrationError(f"decode replica {name}: {err}")
        except (OSError, MigrationError) as e:
            errors[shard] = e

    threads = [threading.Thread(target=run, args=(s,), daemon=True,
                                name=f"kv-migrate-shard{s}")
               for s in range(tp)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise MigrationError(str(e)) from e


def _cancel_on_target(name, addresses, key, request_id) -> None:
    from ..server import CancelRequest  # function-level: server imports us

    try:
        BasicClient(name, addresses, key, probe_timeout=2.0,
                    retry_policy=RetryPolicy(attempts=1)).request(
                        CancelRequest(request_id), idempotent=False,
                        timeout=5.0)
    except OSError:
        pass   # receiver truly gone: nothing left to cancel


class MigrationBuffer:
    """Receiver-side frame assembly: one per serving endpoint.

    Frames of one migration arrive in order on one sender connection
    loop but interleave with other migrations; entries older than
    ``ttl_s`` are garbage-collected on the next ``add`` (a sender that
    died mid-stream must not leak buffered blocks forever).
    """

    def __init__(self, ttl_s: float = 60.0) -> None:
        self.ttl_s = ttl_s
        self._lock = threading.Lock()
        self._pending: Dict[str, dict] = {}   # guarded-by: _lock

    def add(self, frame) -> Optional[Tuple[dict, np.ndarray, np.ndarray]]:
        """Buffer one frame; returns the digest-verified ``(manifest,
        k, v)`` when the transfer completed, None while frames are
        still missing.  Raises :class:`MigrationError` (and drops the
        buffer) on digest mismatch.

        Tensor-parallel transfers interleave ``n_shards`` independent
        streams (frames keyed by ``(shard, seq)``): each shard's head
        slice assembles and digest-verifies on its own, then heads
        concatenate back in shard order — so the returned ``k``/``v``
        are always the full-head arrays regardless of the sender's TP
        degree, and a single damaged shard fails the whole transfer
        before anything reaches the pool."""
        now = time.monotonic()
        rid = frame.request_id
        shard = int(getattr(frame, "shard", 0) or 0)
        n_shards = int(getattr(frame, "n_shards", 1) or 1)
        with self._lock:
            for stale in [r for r, e in self._pending.items()
                          if now - e["t0"] > self.ttl_s]:
                del self._pending[stale]
            ent = self._pending.setdefault(
                rid, {"frames": {}, "manifest": None, "t0": now,
                      "totals": {}, "n_shards": n_shards})
            ent["n_shards"] = max(ent["n_shards"], n_shards)
            ent["totals"][shard] = int(frame.total)
            ent["frames"][(shard, int(frame.seq))] = (frame.k_blocks,
                                                      frame.v_blocks)
            if frame.manifest is not None:
                ent["manifest"] = frame.manifest
            done = (ent["manifest"] is not None
                    and len(ent["totals"]) == ent["n_shards"]
                    and all(
                        sum(1 for (s, _) in ent["frames"] if s == sh) >= tot
                        for sh, tot in ent["totals"].items()))
            if not done:
                return None
            del self._pending[rid]
        shards_kv = []
        for sh in range(ent["n_shards"]):
            tot = ent["totals"][sh]
            if tot == 1:
                k_s, v_s = ent["frames"][(sh, 0)]
            else:
                k_s = np.concatenate([ent["frames"][(sh, s)][0]
                                      for s in range(tot)], axis=1)
                v_s = np.concatenate([ent["frames"][(sh, s)][1]
                                      for s in range(tot)], axis=1)
            shards_kv.append((k_s, v_s))
        if ent["n_shards"] == 1:
            k, v = shards_kv[0]
            verify_digests(ent["manifest"], k, v)
        else:
            for sh, (k_s, v_s) in enumerate(shards_kv):
                verify_shard_digests(ent["manifest"], sh, k_s, v_s)
            k = np.concatenate([ks for ks, _ in shards_kv], axis=3)
            v = np.concatenate([vs for _, vs in shards_kv], axis=3)
        return ent["manifest"], k, v

    def discard(self, request_id: str) -> None:
        with self._lock:
            self._pending.pop(request_id, None)
