"""Disaggregated prefill/decode serving fleet.

The tier above the single-replica engine (ROADMAP item 3): prefill is
compute-bound, decode is memory-bound, so the fleet splits them onto
separate replica classes with different batching and hardware
economics per phase:

* **roles + live KV migration** (:mod:`.migration`) — replicas declare
  ``prefill`` / ``decode`` / ``unified``; a prefill replica runs the
  prompt, then streams the resulting paged KV blocks to a decode
  replica over the HMAC ``BasicService`` wire, the per-slot block
  table as the transfer manifest and per-block sha256 digests
  verifying the transfer — the decode replica binds the blocks into
  its own pool and continues generation token-identically.
* **global prefix directory** (:mod:`.directory`) — the router-tier
  promotion of ``serve/kv/prefix.py``: leading block keys → replicas
  with resident blocks, so a system-prompt hit *anywhere* in the fleet
  routes to resident KV; entries invalidate on replica death and on
  eviction notifications piggybacked on response frames.
* **elastic autoscaling** (:mod:`.controller`) — per-role replica
  counts driven by queue-depth/TTFT signals through the ``elastic/``
  host-discovery machinery: scale out the saturated role,
  drain-and-retire when idle.

``serve/router.py`` owns the role-aware dispatch
(admit→prefill→migrate→decode pipeline); this package owns the data
handoff, the directory, and the control loop.
"""

from .controller import FleetController, ReplicaLauncher, ROLES  # noqa: F401
from .directory import PrefixDirectory  # noqa: F401
from .migration import (  # noqa: F401
    MigrationBuffer, MigrationError, block_digests, migrate_slot,
    shard_digests, verify_digests, verify_shard_digests,
)
