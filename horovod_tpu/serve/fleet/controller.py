"""Elastic fleet autoscaling: per-role replica counts from live load.

The control loop above the router: each :meth:`FleetController.
poll_once` snapshots every replica's serving stats (queue depth, slot
occupancy, TTFT p99 — the ``obs``-derived signals ``ServingStats``
aggregates) and drives per-role replica counts:

* **scale out** — a role whose replicas' mean queue depth exceeds
  ``HVD_TPU_FLEET_SCALE_OUT_QUEUE`` (or whose p99 TTFT exceeds
  ``HVD_TPU_FLEET_SCALE_OUT_TTFT_MS``, when set) is saturated: the
  controller asks its :class:`ReplicaLauncher` for a new replica of
  that role (placement rides the ``elastic/`` ``HostDiscovery``
  machinery: an :class:`~horovod_tpu.elastic.driver.ElasticDriver`
  supplies discovered, non-blacklisted hosts and the controller
  reserves a slot there) and registers it with the router.
* **drain-and-retire** — a role idle (no queued or in-flight work on
  any replica) for ``HVD_TPU_FLEET_SCALE_IN_IDLE_S`` shrinks by one:
  the victim stops admitting (``DrainRequest`` → ``draining`` on the
  wire, so the router shifts load), finishes its in-flight requests,
  releases its directory entries, and only then retires —
  ``HVD_TPU_FLEET_DRAIN_DEADLINE_S`` bounds a wedged drain.

Prefill and decode replicas scale independently — prefill is
compute-bound, decode is memory-bound, so a bursty prompt-heavy load
grows the prefill tier while a long-generation load grows decode
(the role-heterogeneous economics the disaggregation exists for).

``scale_out`` / ``drain_and_retire`` are public: chaos drills and
operators force cycles directly; ``poll_once`` is the policy loop that
calls them.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ... import faults as faults_mod
from ...obs import flight as flight_mod
from ...obs import instrument as _obs
from ...utils.logging import get_logger
from ..engine import resolved_config

logger = get_logger(__name__)

ROLES = ("prefill", "decode", "unified")


def _interactive_p99(entry: dict) -> Optional[float]:
    """Interactive-class p99 TTFT from one replica's stats snapshot
    (None before that class completed anything there)."""
    return (entry["stats"].get("qos", {}).get("interactive", {})
            .get("ttft_ms_p99"))


class ReplicaLauncher:
    """Deployment interface the controller scales through: ``launch``
    brings up one replica of ``role`` (on ``host`` when placement is
    driven by discovery) and returns its router
    :class:`~horovod_tpu.serve.router.ReplicaSpec`; ``retire`` tears
    one down AFTER its drain completed."""

    def launch(self, role: str, host: Optional[str] = None):
        raise NotImplementedError

    def retire(self, name: str) -> None:
        raise NotImplementedError


class FleetController:
    """Per-role elastic scaling over one router + launcher."""

    def __init__(self, router, launcher: ReplicaLauncher, *,
                 driver=None, min_per_role: int = 1,
                 max_replicas: int = 16,
                 scale_out_queue: Optional[float] = None,
                 scale_out_ttft_ms: Optional[float] = None,
                 scale_in_idle_s: Optional[float] = None,
                 drain_deadline_s: Optional[float] = None,
                 stats_timeout_s: float = 2.0,
                 qos_gate=None, clock=None, collector=None) -> None:
        cfg = resolved_config()
        self._router = router
        self._launcher = launcher
        # Optional obs/collector.FleetCollector: when wired, poll_once
        # reads the telemetry plane's last scrape round instead of
        # issuing its own StatsRequest fan-out — one scrape path serves
        # both alerting and scaling, and a wedged fleet costs ONE
        # timeout per collection round rather than one per consumer.
        self._collector = collector
        # Injectable monotonic clock: drain timers, idle clocks and
        # swap-roll deadlines read THIS so the fleet simulator
        # (serve/fleet/sim.py) can run the policy loop under virtual
        # time; default is the real clock — behavior unchanged.
        self._clock = clock if clock is not None else time.monotonic
        self._driver = driver   # elastic ElasticDriver (placement), optional
        self.min_per_role = int(min_per_role)
        self.max_replicas = int(max_replicas)
        self.scale_out_queue = float(
            scale_out_queue if scale_out_queue is not None
            else cfg.fleet_scale_out_queue)
        self.scale_out_ttft_ms = float(
            scale_out_ttft_ms if scale_out_ttft_ms is not None
            else cfg.fleet_scale_out_ttft_ms)
        self.scale_in_idle_s = float(
            scale_in_idle_s if scale_in_idle_s is not None
            else cfg.fleet_scale_in_idle_s)
        self.drain_deadline_s = float(
            drain_deadline_s if drain_deadline_s is not None
            else cfg.fleet_drain_deadline_s)
        self.stats_timeout_s = float(stats_timeout_s)
        # QoS brownout (serve/qos/brownout.py): the controller feeds
        # the router's shed ladder the SAME signals it scales on —
        # fleet-mean queue depth and interactive p99 TTFT.  None when
        # the router runs ungated (falls back to the router's own gate
        # so one wiring suffices).
        self._qos_gate = (qos_gate if qos_gate is not None
                          else getattr(router, "qos_gate", None))
        self._lock = threading.Lock()
        self._draining: Dict[str, float] = {}   # name -> drain start  guarded-by: _lock
        self._placement: Dict[str, str] = {}    # name -> reserved host  guarded-by: _lock
        self._idle_since: Dict[str, float] = {}  # role -> first idle ts  guarded-by: _lock
        self._seq = 0                           # guarded-by: _lock
        self.events: List[dict] = []            # guarded-by: _lock (bounded action log)

    # --- forced actions (the policy loop calls these; drills may too) -------

    def scale_out(self, role: str) -> Optional[object]:
        """Launch + register one ``role`` replica; returns its spec, or
        None when no placement capacity exists."""
        if role not in ROLES:
            raise ValueError(f"unknown role {role!r}; expected one of "
                             f"{ROLES}")
        host = None
        if self._driver is not None:
            host = self._driver.reserve_slot()
            if host is None:
                logger.warning("scale-out of %s declined: no discovered "
                               "host has free capacity", role)
                return None
        try:
            spec = self._launcher.launch(role, host)
        except Exception:
            if self._driver is not None and host is not None:
                self._driver.release_slot(host)
            raise
        self._router.add_replica(spec)
        with self._lock:
            if host is not None:
                self._placement[spec.name] = host
            self._log_locked("scale_out", role=role, replica=spec.name,
                             host=host)
        _obs.on_fleet_scale_event("out")
        logger.info("fleet scale-out: +%s (%s%s)", spec.name, role,
                    f" on {host}" if host else "")
        return spec

    def drain_and_retire(self, name: str) -> None:
        """Begin the drain-and-retire lifecycle for replica ``name``:
        stop admitting now; the retire completes on a later
        :meth:`poll_once` once in-flight work finished (or the drain
        deadline passed)."""
        self._router.drain_replica(name)
        with self._lock:
            self._draining.setdefault(name, self._clock())
            self._log_locked("drain", replica=name)
        logger.info("fleet drain started: %s", name)

    # --- zero-downtime weight hot-swap (serve/swap.py; docs/hot_swap.md) ----

    def roll_swap(self, step: int, *, rollback: bool = False,
                  max_concurrent: Optional[int] = None,
                  timeout: float = 120.0) -> List[dict]:
        """Rolling fleet swap: tell every replica to hot-swap (or roll
        back) to ``step``, at most ``HVD_TPU_SWAP_MAX_CONCURRENT``
        flipping at once — the rest keep serving the OLD weights, so
        fleet capacity never drops below ``N - max_concurrent`` replicas
        mid-deployment.  Returns one outcome row per replica:
        ``{replica, ok, error, weights_version, swap_ms,
        pulled_bytes}``.

        A per-replica failure (rejected pull, abandoned stall, wire
        death) is recorded and the roll CONTINUES — the fleet converges
        as far as it can, and the version-matched routing rule keeps a
        mixed fleet correct.  The ``swap:mode=partial-fleet`` fault
        fires at each replica boundary and aborts the remainder of the
        roll — the deliberately-mixed-fleet drill."""
        cfg = resolved_config()
        bound = max(1, int(max_concurrent if max_concurrent is not None
                           else cfg.swap_max_concurrent))
        names = self._router.replica_names()
        outcomes: List[dict] = []
        aborted = False
        for i in range(0, len(names), bound):
            batch = names[i:i + bound]
            if faults_mod._active is not None and faults_mod.on_swap_roll():
                aborted = True
                flight_mod.record("swap_roll_aborted", step=int(step),
                                  done=len(outcomes),
                                  remaining=len(names) - len(outcomes))
                logger.warning(
                    "rolling swap to step %d aborted before %s "
                    "(partial fleet: %d/%d replicas flipped)", step,
                    batch, len(outcomes), len(names))
                break
            holders = [dict() for _ in batch]

            def swap_one(name: str, holder: dict) -> None:
                try:
                    resp = self._router.swap_replica(
                        name, step, rollback=rollback, timeout=timeout)
                    holder.update(
                        ok=resp.error is None, error=resp.error,
                        weights_version=resp.weights_version,
                        swap_ms=resp.swap_ms,
                        pulled_bytes=resp.pulled_bytes)
                except Exception as e:   # wire death / unknown replica
                    holder.update(ok=False, error=str(e),
                                  weights_version=None, swap_ms=None,
                                  pulled_bytes=0)

            threads = [threading.Thread(target=swap_one,
                                        args=(name, holder), daemon=True,
                                        name=f"swap-{name}")
                       for name, holder in zip(batch, holders)]
            for t in threads:
                t.start()
            # ONE deadline for the whole batch: hung replicas must not
            # serially stack a full timeout each.
            batch_deadline = self._clock() + timeout + 10.0
            for t in threads:
                t.join(timeout=max(0.0,
                                   batch_deadline - self._clock()))
            for name, holder in zip(batch, holders):
                if not holder:
                    holder.update(ok=False,
                                  error="swap_hung_past_timeout",
                                  weights_version=None, swap_ms=None,
                                  pulled_bytes=0)
                outcomes.append(dict(holder, replica=name))
        for name in names[len(outcomes):]:
            outcomes.append({"replica": name, "ok": False,
                             "error": "roll_aborted", "skipped": True,
                             "weights_version": None, "swap_ms": None,
                             "pulled_bytes": 0})
        with self._lock:
            self._log_locked("rollback" if rollback else "swap",
                             step=int(step),
                             ok=sum(1 for o in outcomes if o["ok"]),
                             total=len(outcomes), aborted=aborted)
        return outcomes

    def rollback(self, step: int, *,
                 max_concurrent: Optional[int] = None,
                 timeout: float = 120.0) -> List[dict]:
        """Fleet-wide instant rollback: re-point every replica at a
        journaled ``step`` through the same staged-flip path (the
        ``RollbackRequest`` wire frame)."""
        return self.roll_swap(step, rollback=True,
                              max_concurrent=max_concurrent,
                              timeout=timeout)

    # --- policy loop --------------------------------------------------------

    def poll_once(self, now: Optional[float] = None,
                  stats: Optional[Dict[str, dict]] = None) -> List[dict]:
        """One control round; returns the actions taken (for logs and
        drills).  Cheap by construction: the stats snapshot polls
        replicas concurrently under one deadline — or, when a
        telemetry-plane collector is wired, reuses ITS last round so
        the fleet is scraped once per period, not once per consumer.
        A stale collector round (older than the stats timeout plus one
        collect period) falls back to a direct poll: scaling on old
        numbers re-creates the exact oscillations the detectors page
        on."""
        now = self._clock() if now is None else now
        if stats is None and self._collector is not None:
            max_age = self.stats_timeout_s + float(
                getattr(self._collector, "timeout_s", 0.0))
            stats = self._collector.latest_stats(max_age_s=max_age,
                                                 now=now)
        if stats is None:
            stats = self._router.replica_stats(
                timeout=self.stats_timeout_s)
        actions: List[dict] = []
        self._feed_brownout(stats, now)
        # Brownout counts as fleet-wide busyness (a simulator-found
        # death spiral, pinned by tests/test_fleet_sim.py): at level >
        # 0 the ladder is actively hiding demand — queues look calm
        # precisely BECAUSE traffic is being shed, so an "idle" role is
        # an artifact of the shed, not spare capacity.  Scaling in here
        # shrinks the fleet the un-shed backlog is about to re-flood,
        # re-tripping the ladder: shed → scale-in → overload → shed,
        # forever.  While the ladder is up no role's idle clock runs.
        shed_active = bool(getattr(
            getattr(self._qos_gate, "brownout", None), "level", 0))
        if faults_mod._active is not None \
                and faults_mod.on_control("spiral"):
            # Fault site "control:mode=spiral": run this round with the
            # pre-fix policy (idle clocks tick during a shed) so the
            # telemetry plane's ladder-oscillation detector can be
            # proven against the REAL controller re-entering the death
            # spiral — not against a synthetic trace.
            shed_active = False
        actions += self._finish_drains(stats, now)
        by_role: Dict[str, List[dict]] = {}
        with self._lock:
            draining = set(self._draining)
        for name, entry in stats.items():
            if name in draining or entry.get("draining"):
                continue
            by_role.setdefault(entry.get("role", "unified"),
                               []).append(entry)
        total = sum(len(v) for v in by_role.values()) + len(draining)
        for role in sorted(by_role):
            entries = by_role[role]
            live = [e for e in entries if "stats" in e]
            occ = [e["stats"]["active_slots"] / max(1, e["stats"]
                                                    ["max_slots"])
                   for e in live]
            _obs.on_fleet_role_occupancy(
                role, sum(occ) / len(occ) if occ else 0.0, len(entries))
            if not live:
                continue
            queues = [e["stats"]["queue_depth"] for e in live]
            ttfts = [e["stats"].get("ttft_ms_p99") for e in live]
            ttfts = [t for t in ttfts if t is not None]
            # Per-class scale signal (serve/qos/): the INTERACTIVE tail
            # triggers scale-out on its own — a batch-dominated
            # aggregate can look calm while the SLO class is drowning,
            # and capacity (not shedding) is the right first answer.
            ittfts = [_interactive_p99(e) for e in live]
            ittfts = [t for t in ittfts if t is not None]
            saturated = (sum(queues) / len(queues) > self.scale_out_queue
                         or (self.scale_out_ttft_ms > 0 and ttfts
                             and max(ttfts) > self.scale_out_ttft_ms)
                         or (self.scale_out_ttft_ms > 0 and ittfts
                             and max(ittfts) > self.scale_out_ttft_ms))
            busy = (shed_active
                    or any(q > 0 or e["stats"]["active_slots"] > 0
                           for q, e in zip(queues, live)))
            with self._lock:
                if busy:
                    self._idle_since.pop(role, None)
                else:
                    self._idle_since.setdefault(role, now)
                idle_for = (now - self._idle_since[role]
                            if role in self._idle_since else 0.0)
            if saturated and total < self.max_replicas:
                spec = self.scale_out(role)
                if spec is not None:
                    total += 1
                    actions.append({"action": "scale_out", "role": role,
                                    "replica": spec.name})
            elif (not busy and idle_for >= self.scale_in_idle_s
                  and len(entries) > self.min_per_role):
                victim = entries[-1]["name"]
                self.drain_and_retire(victim)
                actions.append({"action": "drain", "role": role,
                                "replica": victim})
        return actions

    def _feed_brownout(self, stats: Dict[str, dict],
                       now: float) -> None:
        """Feed the QoS gate's brownout ladder one control round's
        signals: fleet-mean queue depth and the worst interactive p99
        TTFT — the same obs-derived numbers the scale policy reads."""
        if self._qos_gate is None:
            return
        live = [e for e in stats.values() if "stats" in e]
        if not live:
            return
        queues = [e["stats"]["queue_depth"] for e in live]
        ittfts = [_interactive_p99(e) for e in live]
        ittfts = [t for t in ittfts if t is not None]
        self._qos_gate.observe(sum(queues) / len(queues),
                               max(ittfts) if ittfts else None, now=now)

    def _finish_drains(self, stats: Dict[str, dict],
                       now: float) -> List[dict]:
        """Retire every draining replica whose in-flight work finished
        (or whose drain deadline passed — a wedged replica must not
        block the scale-in forever)."""
        actions = []
        with self._lock:
            draining = dict(self._draining)
        for name, started in draining.items():
            entry = stats.get(name)
            if entry is None:
                idle = True    # already deregistered: nothing to wait on
            elif "stats" in entry:
                idle = (entry["stats"]["queue_depth"] == 0
                        and entry["stats"]["active_slots"] == 0)
            else:
                # Unreachable THIS poll (stats_error/timeout) is not
                # evidence the drain ran dry — a transient blip must
                # not retire a replica with work in flight; only the
                # drain deadline may force that.
                idle = False
            expired = now - started > self.drain_deadline_s
            if not (idle or expired):
                continue
            if expired and not idle:
                logger.warning("drain deadline passed for %s; forcing "
                               "retire with work in flight", name)
            try:
                self._router.remove_replica(name)
            except ValueError as e:
                # The router refuses to drop its last replica; a wedged
                # draining entry must not poison every later control
                # round — clear it, UN-drain the replica (left draining
                # it would refuse work forever with no peers to carry
                # it), and keep it registered.
                logger.error("cannot retire %s (%s); abandoning the "
                             "drain and re-admitting", name, e)
                self._router.undrain_replica(name)
                with self._lock:
                    self._draining.pop(name, None)
                continue
            try:
                self._launcher.retire(name)
            except Exception:
                logger.exception("launcher failed to retire %s", name)
            with self._lock:
                self._draining.pop(name, None)
                host = self._placement.pop(name, None)
                self._log_locked("retire", replica=name, forced=expired)
            if self._driver is not None and host is not None:
                self._driver.release_slot(host)
            _obs.on_fleet_scale_event("in")
            logger.info("fleet scale-in: -%s%s", name,
                        " (forced)" if expired else "")
            actions.append({"action": "retire", "replica": name,
                            "forced": expired})
        return actions

    def draining(self) -> List[str]:
        with self._lock:
            return sorted(self._draining)

    def _log_locked(self, action: str, **kw) -> None:
        self._seq += 1  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        self.events.append({"seq": self._seq, "action": action, **kw})  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
        del self.events[:-256]  # hvdlint: disable=unguarded-mutation -- _locked suffix contract: every caller holds _lock
