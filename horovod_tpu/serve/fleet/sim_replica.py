"""Simulated serving replicas + the in-process wire they answer on.

A :class:`SimReplica` stands where a real replica process would: it
owns a REAL :class:`~horovod_tpu.serve.qos.sched.QosQueue` (so WFQ
ordering — and the ``qos:invert`` fault that fires inside its ``pop``
— is the production code path), per-class TTFT windows for the stats
snapshots the fleet controller polls, and seeded service-time samplers
from a measured :class:`~horovod_tpu.serve.fleet.traces
.ReplicaProfile`.  What is simulated is only the DATA plane (token
generation becomes a sampled latency instead of a matmul); every
control-plane decision made about the replica — routing, health
strikes, probation, drain, directory consistency, brownout — runs
through the real ``Router``/``FleetController``/``QosGate`` objects
the simulator drives (serve/fleet/sim.py).

:class:`LocalClient` is the transport the router's ``client_factory``
seam installs: it answers the same wire frames ``BasicClient`` carries
(stats, drain, swap/rollback, cancel) as deterministic in-process
calls — a dead replica raises ``ConnectionError`` exactly where a
closed socket would, so the router's strike/bench machinery fires for
real.
"""

from __future__ import annotations

import random
from types import SimpleNamespace
from typing import Dict, List, Optional, Tuple

from ...runner.common.network import DrainRequest
from ..qos.policy import QosPolicy
from ..qos.sched import QosQueue
from ..router import ReplicaSpec
from ..server import (CancelRequest, RollbackRequest, StatsRequest,
                      SwapRequest)
from .traces import ReplicaProfile, SimRequest

# Bytes a simulated swap "pulls" (the recorded SERVING_r14 roll moved
# 32 KiB per replica — the exact value only feeds a counter).
SWAP_PULL_BYTES = 32768

_TTFT_WINDOW = 256   # per-class samples kept for the p99 the stats report


def _p99(samples: List[float]) -> Optional[float]:
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1,
                       int(0.99 * (len(ordered) - 1) + 0.999))]


class SimReplica:
    """One simulated replica: real admission queue, sampled service."""

    def __init__(self, name: str, role: str, profile: ReplicaProfile,
                 seed: int, *, max_slots: int = 8,
                 weights_version: int = 1) -> None:
        self.name = name
        self.role = role
        self.spec = ReplicaSpec(name, [("sim", 0)], role=role)
        self.profile = profile
        self.rng = random.Random(seed)
        self.max_slots = int(max_slots)
        # The REAL weighted-fair queue (default class weights, no
        # budgets — budgets are the router gate's job in this wiring).
        self.queue = QosQueue(QosPolicy())
        self.active: Dict[str, SimRequest] = {}
        self.alive = True
        self.draining = False
        self.weights_version = int(weights_version)
        # Epoch fences stale events: a kill bumps it, and any
        # first-token/finish event scheduled against the old epoch is
        # dropped by the simulator when it fires.
        self.epoch = 0
        self.completed = 0
        self.failed = 0
        # Ground truth for the directory-staleness invariant: prefix
        # keys whose KV blocks this replica actually holds.
        self.resident: set = set()
        # Virtual time of the last event that invalidated this
        # replica's directory entries (kill, weight flip) — the
        # staleness invariant's clock anchor; None = never.
        self.invalidated_at: Optional[float] = None
        # request_id -> decode-replica name for requests admitted on
        # the prefill tier (None = serve locally, unified path).
        self.pipeline_to: Dict[str, Optional[str]] = {}
        self._ttft_all: List[float] = []
        self._ttft_by_class: Dict[str, List[float]] = {}

    # --- service sampling ----------------------------------------------------

    def sample_ttft_ms(self) -> float:
        return self.profile.ttft_ms.sample(self.rng)

    def sample_decode_ms(self, n_tokens: int) -> float:
        return sum(self.profile.tpot_ms.sample(self.rng)
                   for _ in range(max(0, int(n_tokens))))

    def sample_migrate_ms(self) -> float:
        return self.profile.migrate_ms.sample(self.rng)

    def sample_swap_ms(self) -> float:
        return self.profile.swap_ms.sample(self.rng)

    # --- lifecycle -----------------------------------------------------------

    def kill(self) -> List[SimRequest]:
        """Replica death: bump the epoch (in-flight events become
        stale), flush state, and hand back everything that was queued
        or active so the simulator can fail it over."""
        self.alive = False
        self.epoch += 1
        orphans = list(self.queue.drain()) + list(self.active.values())
        self.active.clear()
        self.resident.clear()
        self.pipeline_to.clear()
        return orphans

    def flush_kv(self) -> None:
        """A weight flip drops the KV pool (serve/swap.py semantics):
        resident prefixes are gone whatever the directory still says."""
        self.resident.clear()

    def record_ttft(self, qos_class: str, ttft_ms: float) -> None:
        for bucket in (self._ttft_all,
                       self._ttft_by_class.setdefault(qos_class, [])):
            bucket.append(ttft_ms)
            del bucket[:-_TTFT_WINDOW]

    # --- the stats snapshot the controller polls -----------------------------

    def stats(self) -> dict:
        qos = {cls: {"ttft_ms_p99": _p99(samples)}
               for cls, samples in self._ttft_by_class.items() if samples}
        return {
            "queue_depth": len(self.queue),
            "active_slots": len(self.active),
            "max_slots": self.max_slots,
            "ttft_ms_p99": _p99(self._ttft_all),
            "weights_version": self.weights_version,
            "qos": qos,
        }


class LocalClient:
    """In-process replica transport for the router's ``client_factory``
    seam: same frames, no sockets, deterministic answers."""

    def __init__(self, sim, name: str) -> None:
        self._sim = sim
        self._name = name

    def request(self, frame, idempotent: bool = False,
                timeout: Optional[float] = None):
        rep = self._sim.live_replica(self._name)
        if rep is None:
            raise ConnectionError(f"sim replica {self._name} is dead")
        if isinstance(frame, StatsRequest):
            return SimpleNamespace(stats=rep.stats())
        if isinstance(frame, DrainRequest):
            rep.draining = not frame.cancel
            return SimpleNamespace(error=None)
        if isinstance(frame, (SwapRequest, RollbackRequest)):
            return self._sim.swap_replica_sim(
                rep, frame.step,
                rollback=isinstance(frame, RollbackRequest))
        if isinstance(frame, CancelRequest):
            rep.queue.remove(frame.request_id)
            rep.active.pop(frame.request_id, None)
            return SimpleNamespace(error=None)
        raise ConnectionError(
            f"sim transport: unsupported frame "
            f"{type(frame).__name__} (the simulator drives the data "
            f"plane through events, not GenerateRequest)")
