"""Global prefix directory: leading-block keys → resident replicas.

The router-tier promotion of ``serve/kv/prefix.py``'s radix index: the
per-replica trie answers "which of MY blocks hold this prefix"; this
directory answers "which REPLICA holds this prefix", so a system-prompt
hit anywhere in the fleet routes to resident KV instead of a cold
prefill.  It subsumes the single-replica affinity map the router
carried before (PR 10): entries now track *every* replica a prefix is
resident on (a migration leaves the prefix on both the prefill source
and the decode target), most-recently-confirmed first.

Consistency rules (docs/serving.md):

* entries are **hints**, never correctness — a stale route costs one
  cache miss (the prefix recomputes), so the directory can be lossy in
  both directions;
* a replica's entries are dropped when the router benches it (replica
  death) and when an eviction notification for the key arrives
  piggybacked on one of its response frames
  (``GenerateResponse.evicted_prefixes`` ←
  ``BlockPool.drain_evicted_keys``);
* capacity is bounded LRU — at "millions of users" scale the directory
  must not grow with distinct-prefix count.

Keys are the first ``block_tokens`` token IDs of a prompt — the same
granularity every replica's prefix index shares at, so a key match is
(at least) a one-block cache hit on the resident replica.  Replicas
are opaque hashable handles (the router passes its internal replica
states); the directory never touches the wire.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

_MISSING = object()   # stored values may legitimately be None (no version)


class PrefixDirectory:
    """Bounded, thread-safe leading-block-key → replicas map."""

    def __init__(self, block_tokens: int, max_entries: int = 4096) -> None:
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        self.block = int(block_tokens)
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        # key -> OrderedDict(replica -> True); both levels LRU (last =
        # most recently confirmed resident).
        self._entries: "OrderedDict[tuple, OrderedDict]" = OrderedDict()  # guarded-by: _lock
        self.records_total = 0        # guarded-by: _lock
        self.hits_total = 0           # guarded-by: _lock
        self.invalidations_total = 0  # guarded-by: _lock

    def key_for(self, prompt) -> Optional[Tuple[int, ...]]:
        """Directory key for ``prompt``: its leading block's token IDs
        (None for prompts shorter than one block — nothing block-sized
        to share)."""
        if len(prompt) < self.block:
            return None
        return tuple(int(t) for t in prompt[:self.block])

    def record(self, key: tuple, replica, version=None) -> None:
        """Confirm ``key`` resident on ``replica`` (served a request
        whose prefix starts with it, or adopted a migration of it).
        ``version`` tags which weights the resident KV was computed
        under (serve/swap.py): a later lookup must only route to the
        replica while it still serves that version — resident KV from
        OLD weights served against NEW weights would be silently wrong,
        the one failure mode a hot-swap may never trade for its TTFT
        win."""
        if key is None:
            return
        with self._lock:
            reps = self._entries.get(key)
            if reps is None:
                reps = self._entries[key] = OrderedDict()
            reps[replica] = version
            reps.move_to_end(replica)
            self._entries.move_to_end(key)
            self.records_total += 1
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)

    def lookup(self, key: Optional[tuple]) -> List:
        """Replicas with ``key`` resident, most recently confirmed
        first (the caller filters for health/load)."""
        return [rep for rep, _ in self.lookup_versioned(key)]

    def lookup_versioned(self, key: Optional[tuple]) -> List[tuple]:
        """``[(replica, recorded weights version), ...]``, most
        recently confirmed first — the router's mixed-version routing
        rule compares the recorded version against the replica's
        CURRENT one and falls back to a recompute on mismatch."""
        if key is None:
            return []
        with self._lock:
            reps = self._entries.get(key)
            if not reps:
                return []
            self._entries.move_to_end(key)
            self.hits_total += 1
            return [(rep, reps[rep]) for rep in reversed(reps)]

    def discard(self, key: tuple, replica) -> None:
        """Eviction notification: ``replica`` no longer holds ``key``
        (its depth-0 block was evicted)."""
        with self._lock:
            reps = self._entries.get(key)
            if reps is None:
                return
            if reps.pop(replica, _MISSING) is not _MISSING:
                self.invalidations_total += 1
            if not reps:
                del self._entries[key]

    def invalidate_replica(self, replica) -> int:
        """Drop every entry naming ``replica`` (replica death /
        retirement); returns how many entries were dropped."""
        n = 0
        with self._lock:
            for key in list(self._entries):
                reps = self._entries[key]
                if reps.pop(replica, _MISSING) is not _MISSING:
                    n += 1
                if not reps:
                    del self._entries[key]
            self.invalidations_total += n
        return n

    def replicas(self) -> List[str]:
        """Names of every replica the directory currently references —
        the telemetry plane's ``directory_staleness`` detector compares
        this roster against the collector's last-successful-scrape
        times (obs/detect.py)."""
        names = set()
        with self._lock:
            for reps in self._entries.values():
                for rep in reps:
                    spec = getattr(rep, "spec", None)
                    names.add(getattr(spec, "name", None) or str(rep))
        return sorted(names)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> Dict:
        with self._lock:
            return {
                "directory_keys": len(self._entries),
                "directory_records_total": self.records_total,
                "directory_hits_total": self.hits_total,
                "directory_invalidations_total": self.invalidations_total,
            }
