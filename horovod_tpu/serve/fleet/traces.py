"""Trace generation + measured latency profiles for the fleet simulator.

Two inputs parameterize :mod:`~horovod_tpu.serve.fleet.sim`:

* **Traces** — seeded open-loop request streams (:func:`make_trace`):
  burst-modulated Poisson arrivals over a tenant × QoS-class mix, with
  a Zipf-skewed prefix pool at the directory's block granularity so
  prefix-directory routing has real hit structure to exercise.
  Open-loop matters: arrivals never wait for completions, so overload
  actually overloads (a closed loop self-throttles and can never trip
  the brownout ladder).

* **Replica profiles** — service-time distributions fitted from the
  RECORDED serving benchmark artifacts (``SERVING_r11.json`` fleet
  TTFT/migration, ``SERVING_r14.json`` swap latency,
  ``SERVING_r15.json`` per-class TPOT), so a simulated replica costs
  what a measured CPU replica cost.  Fits are lognormal — the standard
  long-tail shape for service latency — recovered from the recorded
  p50/p99 (or mean/p99) pairs in closed form.  Everything is sampled
  through ``random.Random(seed)``: same seed ⇒ identical trace and
  identical service draws, the determinism contract the replay tests
  pin (docs/fleet_sim.md).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import random
from typing import Dict, List, Optional, Sequence, Tuple

# z-score of the 99th percentile of the standard normal: the lognormal
# fit solves  p99 = exp(mu + Z_P99 * sigma)  against  p50 = exp(mu).
Z_P99 = 2.326

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


@dataclasses.dataclass(frozen=True)
class LatencyDist:
    """A fitted lognormal service-time distribution (milliseconds)."""

    p50_ms: float
    p99_ms: float

    @property
    def mu(self) -> float:
        return math.log(max(1e-6, self.p50_ms))

    @property
    def sigma(self) -> float:
        return max(0.0, (math.log(max(1e-6, self.p99_ms)) - self.mu)
                   / Z_P99)

    @classmethod
    def from_mean_p99(cls, mean_ms: float, p99_ms: float) -> "LatencyDist":
        """Fit from a recorded (mean, p99) pair: with
        ``mean = exp(mu + sigma²/2)`` and ``p99 = exp(mu + Z·sigma)``,
        sigma solves the quadratic ``sigma²/2 − Z·sigma + ln(p99/mean)
        = 0`` (smaller root — the tail-consistent branch)."""
        mean_ms = max(1e-6, float(mean_ms))
        p99_ms = max(mean_ms, float(p99_ms))
        gap = math.log(p99_ms / mean_ms)
        disc = max(0.0, Z_P99 * Z_P99 - 2.0 * gap)
        sigma = Z_P99 - math.sqrt(disc)
        p50 = mean_ms * math.exp(-sigma * sigma / 2.0)
        return cls(p50_ms=p50, p99_ms=p50 * math.exp(Z_P99 * sigma))

    def sample(self, rng: random.Random) -> float:
        """One draw in milliseconds (always > 0)."""
        return math.exp(self.mu + self.sigma * rng.gauss(0.0, 1.0))


@dataclasses.dataclass(frozen=True)
class ReplicaProfile:
    """What one simulated replica costs, per operation."""

    ttft_ms: LatencyDist        # queue-free first-token service time
    tpot_ms: LatencyDist        # per-token decode time
    migrate_ms: LatencyDist     # prefill→decode KV transfer
    swap_ms: LatencyDist        # weight hot-swap pull+flip
    source: str = "defaults"


# Fallback when no artifacts are on disk (fresh checkout): round
# numbers in the same regime the recorded CPU benches measured.
DEFAULT_PROFILE = ReplicaProfile(
    ttft_ms=LatencyDist(120.0, 4500.0),
    tpot_ms=LatencyDist(2.4, 2.8),
    migrate_ms=LatencyDist(80.0, 420.0),
    swap_ms=LatencyDist(950.0, 3600.0),
)


def _summary(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc.get("summary", doc) if isinstance(doc, dict) else None


def load_profile(root: Optional[str] = None) -> ReplicaProfile:
    """Build the replica profile from the recorded ``SERVING_r*``
    artifacts under ``root`` (repo root by default); any missing
    artifact falls back to :data:`DEFAULT_PROFILE`'s numbers for its
    fields — the sim must run on a fresh checkout too."""
    root = root or _REPO
    r11 = _summary(os.path.join(root, "SERVING_r11.json")) or {}
    r14 = _summary(os.path.join(root, "SERVING_r14.json")) or {}
    r15 = _summary(os.path.join(root, "SERVING_r15.json")) or {}
    used = [name for name, doc in (("SERVING_r11", r11),
                                   ("SERVING_r14", r14),
                                   ("SERVING_r15", r15)) if doc]
    ttft = DEFAULT_PROFILE.ttft_ms
    if "unified_ttft_ms_p50" in r11:
        # The unified tier's measured submit→first-token distribution —
        # the per-replica service cost the fleet policies sit on top of.
        ttft = LatencyDist(float(r11["unified_ttft_ms_p50"]),
                           float(r11["unified_ttft_ms_p99"]))
    tpot = DEFAULT_PROFILE.tpot_ms
    if "batch_tpot_ms_p99" in r15:
        # r15 records per-class TPOT p99s only; the p50 estimate rides
        # the lower class p99 (TPOT is tight on CPU — the classes'
        # p99s bracket a narrow band, see docs/fleet_sim.md).
        hi = max(float(r15.get("interactive_tpot_ms_p99", 0.0)),
                 float(r15["batch_tpot_ms_p99"]))
        lo = min(float(r15.get("interactive_tpot_ms_p99", hi)),
                 float(r15["batch_tpot_ms_p99"]))
        tpot = LatencyDist(0.9 * lo, hi)
    migrate = DEFAULT_PROFILE.migrate_ms
    if "migrate_ms_mean" in r11:
        migrate = LatencyDist.from_mean_p99(float(r11["migrate_ms_mean"]),
                                            float(r11["migrate_ms_p99"]))
    swap = DEFAULT_PROFILE.swap_ms
    if "swap_latency_ms_mean" in r14:
        swap = LatencyDist.from_mean_p99(
            float(r14["swap_latency_ms_mean"]),
            float(r14["swap_latency_ms_max"]))
    return ReplicaProfile(ttft_ms=ttft, tpot_ms=tpot, migrate_ms=migrate,
                          swap_ms=swap,
                          source=",".join(used) if used else "defaults")


@dataclasses.dataclass(frozen=True)
class SimRequest:
    """One traced request.  Field names duck-type the ``ServeRequest``
    shape :class:`~horovod_tpu.serve.qos.sched.QosQueue` schedules
    (``request_id``/``tenant``/``qos_class``/``deadline``);
    ``deadline`` is ABSOLUTE virtual time (arrival + the class's
    relative deadline), None for batch."""

    request_id: str
    arrival_s: float
    tenant: str
    qos_class: str
    prompt: Tuple[int, ...]
    max_new_tokens: int
    deadline: Optional[float]

    @property
    def submitted_at(self) -> float:
        return self.arrival_s


# Relative deadlines per class (virtual seconds): interactive is SLO
# traffic, batch rides without one (preemption fodder).
CLASS_DEADLINE_S = {"interactive": 10.0, "standard": 60.0, "batch": None}

DEFAULT_CLASS_MIX = (("interactive", 0.2), ("standard", 0.3),
                     ("batch", 0.5))
DEFAULT_TENANTS = ("alice", "bob", "bulk")


def make_trace(n_requests: int, *, seed: int = 0,
               rate_rps: float = 200.0,
               burst_factor: float = 4.0,
               burst_period_s: float = 10.0,
               burst_duty: float = 0.3,
               class_mix: Sequence[Tuple[str, float]] = DEFAULT_CLASS_MIX,
               tenants: Sequence[str] = DEFAULT_TENANTS,
               prefix_pool: int = 64,
               prefix_skew: float = 3.0,
               block_tokens: int = 16,
               suffix_tokens: int = 16,
               max_new_tokens: int = 16) -> List[SimRequest]:
    """A seeded bursty open-loop trace of ``n_requests``.

    Arrivals are a burst-modulated Poisson process: for the first
    ``burst_duty`` of every ``burst_period_s`` window the rate is
    ``rate_rps × burst_factor``, else ``rate_rps`` — the on/off bursts
    that trip (and must then calmly un-trip) the brownout ladder.
    Prompts share leading blocks drawn from a ``prefix_pool`` with
    power-law skew ``prefix_skew`` (higher = hotter head), at the
    directory's ``block_tokens`` granularity.
    """
    if n_requests <= 0:
        raise ValueError(f"trace needs n_requests > 0, got {n_requests}")
    rng = random.Random(seed)
    classes = [c for c, _ in class_mix]
    weights = [w for _, w in class_mix]
    out: List[SimRequest] = []
    t = 0.0
    for i in range(n_requests):
        in_burst = (t % burst_period_s) < burst_period_s * burst_duty
        rate = rate_rps * (burst_factor if in_burst else 1.0)
        t += rng.expovariate(max(1e-9, rate))
        qos_class = rng.choices(classes, weights=weights)[0]
        tenant = tenants[i % len(tenants)]
        # Zipf-ish head: u**skew concentrates mass near index 0.
        hot = int(prefix_pool * (rng.random() ** prefix_skew))
        hot = min(prefix_pool - 1, hot)
        prefix = tuple(7000 + hot * block_tokens + j
                       for j in range(block_tokens))
        suffix = tuple(rng.randrange(1, 4096)
                       for _ in range(suffix_tokens))
        rel = CLASS_DEADLINE_S.get(qos_class)
        out.append(SimRequest(
            request_id=f"r{i:07d}",
            arrival_s=t,
            tenant=tenant,
            qos_class=qos_class,
            prompt=prefix + suffix,
            max_new_tokens=max_new_tokens,
            deadline=(t + rel) if rel is not None else None,
        ))
    return out
