"""TPU-native inference serving: continuous batching + replica routing.

The serving stack opens the inference workload the ROADMAP's north star
implies ("serves heavy traffic from millions of users") on top of the
training framework's existing layers:

* :mod:`~horovod_tpu.serve.engine` — jitted, length-bucketed prefill +
  slot-batched single-token decode over ``models.transformer.GPT``
  (preallocated KV cache, greedy/temperature/top-k sampling,
  Timeline phases ``SERVE_PREFILL``/``SERVE_DECODE``)
* :mod:`~horovod_tpu.serve.batcher` — continuous-batching scheduler
  (bounded admission queue, per-request deadlines, reject-when-full
  backpressure)
* :mod:`~horovod_tpu.serve.server` — replica endpoint on the runner's
  HMAC-authenticated RPC stack
* :mod:`~horovod_tpu.serve.router` — spreads requests across
  data-parallel replica groups (``process_sets``), task-agent-style
  strike/probation health, and drains a dead replica's in-flight
  requests back through :class:`~horovod_tpu.utils.retry.RetryPolicy`
* :mod:`~horovod_tpu.serve.metrics` — TTFT/TPOT/occupancy snapshots
* :mod:`~horovod_tpu.serve.kv` — paged block-pool KV cache: refcounted
  fixed-size token blocks with copy-on-write prefix sharing (radix
  trie over token IDs), LRU eviction, and speculative decoding
  (drafter + one-forward batched verification, token-identical to
  plain greedy decode)
* :mod:`~horovod_tpu.serve.fleet` — the disaggregated prefill/decode
  tier: role-split replicas with live KV migration over the HMAC wire
  (per-block digests, token-identical continuation), a router-tier
  global prefix directory, and a :class:`FleetController` driving
  per-role elastic scale-out / drain-and-retire from queue-depth and
  TTFT signals
* :mod:`~horovod_tpu.serve.swap` — zero-downtime weight hot-swap from
  the checkpoint store (``ckpt/``): a :class:`WeightSubscriber` per
  replica diff-pulls only changed shards (digest-verified), stages
  them beside the live params, and flips atomically at the batcher's
  swap barrier; rolling fleet swaps + instant journaled rollback ride
  the ``SwapRequest``/``RollbackRequest`` frames (docs/hot_swap.md)

* :mod:`~horovod_tpu.serve.qos` — SLO-aware multi-tenant QoS
  scheduling (docs/qos.md): service classes with per-tenant
  token-bucket budgets, weighted-fair (stride) admission replacing the
  FIFO queue, deadline-aware preemption of batch generations to the
  paged-KV prefix cache (token-identical resumption), and router-level
  rate limits with a graceful-brownout shed ladder (batch first, then
  standard, never interactive)

Chaos: the ``serve`` fault site (``HVD_TPU_FAULT_SPEC``) drops/delays
requests at the endpoint, kills a replica mid-decode or mid-migration,
and damages KV transfers at the migration boundary; the ``qos`` site
drills priority inversion and budget floods (docs/serving.md and
docs/qos.md have recipes).
"""

from .batcher import (  # noqa: F401
    ContinuousBatcher, QueueFullError, ReplicaDrainingError,
    ReplicaKilledError, ServeRequest,
)
from .engine import (  # noqa: F401
    InferenceEngine, PromptTooLongError, SamplingParams,
)
from .fleet import (  # noqa: F401
    FleetController, MigrationError, PrefixDirectory, ReplicaLauncher,
)
from .kv import (  # noqa: F401
    BlockPool, KVPoolExhaustedError, PrefixIndex,
)
from .metrics import ServingStats, percentile  # noqa: F401
from .qos import (  # noqa: F401
    BrownoutController, BudgetExhaustedError, QosGate, QosPolicy,
    QosQueue, RequestShedError,
)
from .router import (  # noqa: F401
    NoHealthyReplicasError, ReplicaSpec, ReplicaUnavailableError, Router,
    register_replica_process_sets, replica_slot_groups,
)
from .server import (  # noqa: F401
    CancelRequest, GenerateRequest, GenerateResponse, InferenceServer,
    RollbackRequest, StatsRequest, StatsResponse, SwapRequest,
    SwapResponse,
)
from .swap import (  # noqa: F401
    SwapAbandonedError, SwapFailedError, SwapRejectedError,
    WeightSubscriber,
)
from .tp import (  # noqa: F401
    ShardFollower, ShardLockstepError, ShardServer, ShardStepRequest,
    ShardStepResponse,
)
