"""TPU-native inference serving: continuous batching + replica routing.

The serving stack opens the inference workload the ROADMAP's north star
implies ("serves heavy traffic from millions of users") on top of the
training framework's existing layers:

* :mod:`~horovod_tpu.serve.engine` — jitted, length-bucketed prefill +
  slot-batched single-token decode over ``models.transformer.GPT``
  (preallocated KV cache, greedy/temperature/top-k sampling,
  Timeline phases ``SERVE_PREFILL``/``SERVE_DECODE``)
* :mod:`~horovod_tpu.serve.batcher` — continuous-batching scheduler
  (bounded admission queue, per-request deadlines, reject-when-full
  backpressure)
* :mod:`~horovod_tpu.serve.server` — replica endpoint on the runner's
  HMAC-authenticated RPC stack
* :mod:`~horovod_tpu.serve.router` — spreads requests across
  data-parallel replica groups (``process_sets``), task-agent-style
  strike/probation health, and drains a dead replica's in-flight
  requests back through :class:`~horovod_tpu.utils.retry.RetryPolicy`
* :mod:`~horovod_tpu.serve.metrics` — TTFT/TPOT/occupancy snapshots
* :mod:`~horovod_tpu.serve.kv` — paged block-pool KV cache: refcounted
  fixed-size token blocks with copy-on-write prefix sharing (radix
  trie over token IDs), LRU eviction, and speculative decoding
  (drafter + one-forward batched verification, token-identical to
  plain greedy decode)

Chaos: the ``serve`` fault site (``HVD_TPU_FAULT_SPEC``) drops/delays
requests at the endpoint and kills a replica mid-decode
(docs/serving.md has recipes).
"""

from .batcher import (  # noqa: F401
    ContinuousBatcher, QueueFullError, ReplicaKilledError, ServeRequest,
)
from .engine import (  # noqa: F401
    InferenceEngine, PromptTooLongError, SamplingParams,
)
from .kv import (  # noqa: F401
    BlockPool, KVPoolExhaustedError, PrefixIndex,
)
from .metrics import ServingStats, percentile  # noqa: F401
from .router import (  # noqa: F401
    NoHealthyReplicasError, ReplicaSpec, ReplicaUnavailableError, Router,
    register_replica_process_sets, replica_slot_groups,
)
from .server import (  # noqa: F401
    CancelRequest, GenerateRequest, GenerateResponse, InferenceServer,
    StatsRequest, StatsResponse,
)
