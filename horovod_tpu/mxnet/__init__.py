"""horovod_tpu.mxnet — MXNet binding (import-gated).

Reference: ``horovod/mxnet/`` (``DistributedTrainer``, NDArray mpi_ops
through the MXNet engine — SURVEY.md §2.3/§2.4, mount empty,
unverified).  Structure mirrors the torch tier: NDArrays bridge to
numpy and ride the shared host-binding core (:mod:`horovod_tpu.hostops`).

MXNet reached end-of-life upstream (retired by Apache in 2023) and is
not installable in this image, so the binding cannot be exercised
against real mxnet here; its bridge logic is covered by
``tests/test_mxnet_api.py`` with a minimal NDArray/gluon API shim
(waiver recorded in README.md).
"""

from __future__ import annotations

try:
    import mxnet  # noqa: F401
except ImportError as _e:
    raise ImportError(
        "horovod_tpu.mxnet requires mxnet (end-of-life upstream; not "
        "bundled in this environment) — use horovod_tpu.torch, "
        "horovod_tpu.tensorflow, or the pure-JAX API instead"
    ) from _e

from ..basics import (  # noqa: F401
    init, shutdown, is_initialized,
    rank, size, local_rank, local_size, cross_rank, cross_size,
    mpi_built, nccl_built, gloo_built, ccl_built, cuda_built, rocm_built,
)
from ..process_sets import (  # noqa: F401
    ProcessSet, add_process_set, remove_process_set, global_process_set,
)
from .mpi_ops import (  # noqa: F401
    Average, Sum, Adasum, Min, Max, Product,
    allreduce, allreduce_, allreduce_async_,
    grouped_allreduce, grouped_allreduce_, grouped_allreduce_async_,
    allgather, broadcast, broadcast_, alltoall, reducescatter,
    barrier, synchronize, poll, join, Handle,
)
from .functions import broadcast_parameters, broadcast_object  # noqa: F401
from .trainer import DistributedTrainer  # noqa: F401
