"""horovod_tpu.mxnet — MXNet binding (gated).

Reference: ``horovod/mxnet/`` (``DistributedTrainer``, per-dtype mpi_ops
through the MXNet engine — SURVEY.md §2.3/§2.4, mount empty,
unverified).  MXNet reached end-of-life upstream (retired by Apache in
2023) and is not installable in this environment; the binding surface
is declared for reference parity and raises with guidance.  The
implementation recipe, should it ever be needed, is the same as the
torch binding: bridge ``mx.nd.NDArray`` host tensors through
:mod:`horovod_tpu.hostops` and wrap ``gluon.Trainer`` the way
``horovod_tpu.torch.DistributedOptimizer`` wraps torch optimizers.
"""

from __future__ import annotations

_MSG = ("horovod_tpu.mxnet requires mxnet, which is end-of-life and not "
        "bundled in this environment; use horovod_tpu.torch, "
        "horovod_tpu.tensorflow, or the pure-JAX API instead")


def _unavailable(name: str):
    try:
        import mxnet  # noqa: F401
    except ImportError as e:
        raise ImportError(_MSG) from e
    # mxnet importable but the binding is deliberately not implemented —
    # never fall through silently (a no-op broadcast would let ranks
    # train from divergent state).
    raise NotImplementedError(
        f"horovod_tpu.mxnet.{name} is not implemented (mxnet is "
        "end-of-life); see the module docstring for the porting recipe")


def init(*args, **kwargs):
    _unavailable("init")


def DistributedTrainer(*args, **kwargs):
    """Reference: ``hvd.DistributedTrainer(params, opt)``."""
    _unavailable("DistributedTrainer")


def broadcast_parameters(*args, **kwargs):
    _unavailable("broadcast_parameters")


def allreduce(*args, **kwargs):
    _unavailable("allreduce")
