"""MXNet-NDArray collective API — reference parity with ``horovod.mxnet``.

Reference surface (``horovod/mxnet/mpi_ops.py`` + the C extension
``horovod/mxnet/mpi_ops.cc`` pushing ops onto the MXNet engine — paths
per SURVEY.md §2.3/§2.4, mount empty, unverified): ``allreduce[_]``,
``grouped_allreduce[_]``, ``allgather``, ``broadcast[_]``, ``alltoall``,
with op/prescale/postscale/process_set args.

TPU-native redesign: as with the torch tier, an MXNet worker is a
*controller process*; its NDArray is bridged to numpy and the shared
host-binding core (:mod:`horovod_tpu.hostops`) maps the process-level op
onto slot-stack SPMD collectives.  There is no engine-callback half —
XLA's async dispatch replaces the MXNet engine's dependency tracking,
and in-place variants write back through NDArray slice assignment.

MXNet reached end-of-life upstream (retired by Apache in 2023) and is
not installable in this image; the binding is import-gated and its
bridge logic is exercised against a minimal API shim in
``tests/test_mxnet_api.py`` (see the waiver note in README.md).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

import mxnet as mx  # gated by horovod_tpu/mxnet/__init__.py

from .. import hostops as H

Average = H.Average
Sum = H.Sum
Adasum = H.Adasum
Min = H.Min
Max = H.Max
Product = H.Product


# --- NDArray <-> numpy bridge ------------------------------------------------

def _to_numpy(t) -> np.ndarray:
    return t.asnumpy()


def _like(t, a: np.ndarray):
    """Construct an NDArray like ``t`` holding ``a``."""
    kwargs = {}
    ctx = getattr(t, "context", None)
    if ctx is not None:
        kwargs["ctx"] = ctx
    return mx.nd.array(a, dtype=a.dtype, **kwargs)


def _write_back(t, a: np.ndarray):
    t[:] = a
    return t


# --- handles -----------------------------------------------------------------

class Handle:
    """Async handle (reference: engine-tracked write dependency of the
    pushed op).  Wraps the in-flight host handle and the NDArray
    write-back applied at ``synchronize`` time."""

    def __init__(self, host: H.HostHandle, finish, name: str = ""):
        self._host = host
        self._finish = finish
        self._result = None
        self._done_flag = False
        self.name = name

    def wait(self):
        if not self._done_flag:
            self._result = self._finish(self._host.wait())
            self._done_flag = True
        return self._result

    def done(self) -> bool:
        return self._done_flag or self._host.done()


def synchronize(handle: Handle):
    return handle.wait()


def poll(handle: Handle) -> bool:
    return handle.done()


# --- allreduce ---------------------------------------------------------------

def allreduce(tensor, *, op: str = Average, process_set=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              name: str = "allreduce"):
    """Reference: ``hvd.allreduce(tensor)`` — out-of-place."""
    host = H.allreduce_async(
        _to_numpy(tensor), op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        name=name)
    return Handle(host, lambda r: _like(tensor, r), name).wait()


def allreduce_(tensor, *, op: str = Average, process_set=None,
               prescale_factor: float = 1.0, postscale_factor: float = 1.0,
               name: str = "allreduce"):
    """Reference: ``hvd.allreduce_`` — in-place."""
    host = H.allreduce_async(
        _to_numpy(tensor), op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        name=name)
    return Handle(host, lambda r: _write_back(tensor, r), name).wait()


def allreduce_async_(tensor, *, op: str = Average, process_set=None,
                     prescale_factor: float = 1.0,
                     postscale_factor: float = 1.0,
                     name: str = "allreduce") -> Handle:
    """In-place async — the ``DistributedTrainer`` hot path."""
    host = H.allreduce_async(
        _to_numpy(tensor), op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        name=name)
    return Handle(host, lambda r: _write_back(tensor, r), name)


def grouped_allreduce(tensors: Sequence, *, op: str = Average,
                      process_set=None, prescale_factor: float = 1.0,
                      postscale_factor: float = 1.0,
                      name: str = "grouped_allreduce") -> List:
    return _grouped_impl(tensors, False, op, process_set, prescale_factor,
                         postscale_factor, name).wait()


def grouped_allreduce_(tensors: Sequence, *, op: str = Average,
                       process_set=None, prescale_factor: float = 1.0,
                       postscale_factor: float = 1.0,
                       name: str = "grouped_allreduce") -> List:
    return _grouped_impl(tensors, True, op, process_set, prescale_factor,
                         postscale_factor, name).wait()


def grouped_allreduce_async_(tensors: Sequence, *, op: str = Average,
                             process_set=None, prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             name: str = "grouped_allreduce") -> Handle:
    return _grouped_impl(tensors, True, op, process_set, prescale_factor,
                         postscale_factor, name)


def _grouped_impl(tensors, in_place, op, process_set, prescale_factor,
                  postscale_factor, name) -> Handle:
    host = H.grouped_allreduce_async(
        [_to_numpy(t) for t in tensors], op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
        name=name)

    def finish(results):
        if in_place:
            return [_write_back(t, r) for t, r in zip(tensors, results)]
        return [_like(t, r) for t, r in zip(tensors, results)]

    return Handle(host, finish, name)


# --- allgather / broadcast / alltoall / reducescatter ------------------------

def allgather(tensor, *, process_set=None, name: str = "allgather"):
    """Reference: ``hvd.allgather`` — concat along dim 0; ragged first
    dims supported (MPI_Allgatherv) via the host tier's two-round
    protocol."""
    host = H.allgather_async(_to_numpy(tensor), process_set=process_set,
                             name=name)
    return Handle(host, lambda r: _like(tensor, r), name).wait()


def broadcast(tensor, root_rank: int = 0, *, process_set=None,
              name: str = "broadcast"):
    host = H.broadcast_async(_to_numpy(tensor), root_rank,
                             process_set=process_set, name=name)
    return Handle(host, lambda r: _like(tensor, r), name).wait()


def broadcast_(tensor, root_rank: int = 0, *, process_set=None,
               name: str = "broadcast"):
    host = H.broadcast_async(_to_numpy(tensor), root_rank,
                             process_set=process_set, name=name)
    return Handle(host, lambda r: _write_back(tensor, r), name).wait()


def alltoall(tensor, splits=None, *, process_set=None,
             name: str = "alltoall"):
    np_splits = None if splits is None else _to_numpy(splits).astype(np.int64)
    gathered, received = H.alltoall(_to_numpy(tensor), np_splits,
                                    process_set=process_set, name=name)
    out = _like(tensor, gathered)
    if splits is None:
        return out
    return out, _like(tensor, received)


def reducescatter(tensor, *, op: str = Sum, process_set=None,
                  name: str = "reducescatter"):
    shard = H.reducescatter(_to_numpy(tensor), op=op,
                            process_set=process_set, name=name)
    return _like(tensor, shard)


def barrier(process_set=None, name: str = "barrier") -> None:
    H.barrier(process_set=process_set, name=name)


def join() -> int:
    return H.join()
