"""DistributedTrainer: gluon training with cross-worker gradient
reduction.

Reference: ``DistributedTrainer`` in ``horovod/mxnet/__init__.py``
(SURVEY.md §2.4, mount empty, unverified): subclasses ``gluon.Trainer``
with ``kvstore=None``, divides the loss scale by the worker count, and
overrides ``_allreduce_grads`` to sum-allreduce every gradient in place
(optionally pre/post-scaled by ``gradient_predivide_factor``) before the
optimizer update.
"""

from __future__ import annotations

from typing import Optional

import mxnet as mx  # gated by horovod_tpu/mxnet/__init__.py

from .. import basics
from . import mpi_ops


class DistributedTrainer(mx.gluon.Trainer):
    """Reference API: ``hvd.DistributedTrainer(params, opt,
    optimizer_params, gradient_predivide_factor=1.0, process_set=...)``.

    The effective gradient is ``sum_w(grad_w) / N`` applied through the
    optimizer's ``rescale_grad`` (divided by N here, matching the
    reference) so user-visible learning-rate semantics equal single-worker
    training on an N-times-larger batch.
    """

    def __init__(self, params, optimizer, optimizer_params=None,
                 gradient_predivide_factor: float = 1.0,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0,
                 process_set=None,
                 num_groups: int = 0,
                 compression=None):
        if isinstance(optimizer, mx.optimizer.Optimizer) \
                and optimizer_params is not None:
            raise ValueError(
                "optimizer_params is only usable with a string optimizer "
                "name (reference contract)")
        super().__init__(params, optimizer, optimizer_params, kvstore=None)

        self._hvd_process_set = process_set
        self._hvd_num_groups = int(num_groups)
        self._hvd_compression = compression
        n = (process_set.size() if process_set is not None
             else basics.cross_size())
        # Reference math: predivide splits the 1/N between pre- and
        # post-scaling of the summed allreduce; rescale_grad absorbs the
        # rest so grad_effective = sum(grads)/N.
        self._hvd_prescale = prescale_factor / gradient_predivide_factor
        self._hvd_postscale = postscale_factor * gradient_predivide_factor / n
        self._hvd_world = n

    def _hvd_grads(self):
        grads = []
        for p in self._params:
            if getattr(p, "grad_req", "write") != "null":
                if hasattr(p, "list_grad"):
                    grads.extend(p.list_grad())
                elif hasattr(p, "grad") and callable(getattr(p, "grad")):
                    grads.append(p.grad())
        return grads

    def _allreduce_grads(self):
        grads = self._hvd_grads()
        if not grads:
            return
        if self._hvd_num_groups > 0:
            k = max(1, (len(grads) + self._hvd_num_groups - 1)
                    // self._hvd_num_groups)
            handles = [mpi_ops.grouped_allreduce_async_(
                grads[i:i + k], op=mpi_ops.Sum,
                process_set=self._hvd_process_set,
                prescale_factor=self._hvd_prescale,
                postscale_factor=self._hvd_postscale,
                name=f"grads[{i}]") for i in range(0, len(grads), k)]
        else:
            handles = [mpi_ops.allreduce_async_(
                g, op=mpi_ops.Sum, process_set=self._hvd_process_set,
                prescale_factor=self._hvd_prescale,
                postscale_factor=self._hvd_postscale,
                name=f"grad[{i}]") for i, g in enumerate(grads)]
        for h in handles:
            h.wait()
