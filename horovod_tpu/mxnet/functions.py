"""MXNet state broadcast helpers.

Reference: ``broadcast_parameters`` / ``broadcast_object`` in
``horovod/mxnet/__init__.py`` (SURVEY.md §2.4, mount empty, unverified)
— broadcast gluon parameters (or a plain name→NDArray dict) from the
root so every worker starts identical.
"""

from __future__ import annotations

from typing import Any

from . import mpi_ops
from ..functions import broadcast_object  # noqa: F401  (re-export)


def _param_arrays(params):
    """Yield (name, NDArray) pairs from a gluon ParameterDict or a plain
    mapping of name → NDArray."""
    for name, p in sorted(params.items()):
        if hasattr(p, "list_data"):      # gluon.Parameter
            for arr in p.list_data():
                yield name, arr
        elif hasattr(p, "data") and callable(getattr(p, "data")):
            yield name, p.data()
        else:                            # already an NDArray
            yield name, p


def broadcast_parameters(params: Any, root_rank: int = 0,
                         prefix: str = "") -> None:
    """Reference: ``hvd.broadcast_parameters(model.collect_params(), 0)``
    — in-place broadcast of every parameter array from ``root_rank``."""
    for name, arr in _param_arrays(params):
        mpi_ops.broadcast_(arr, root_rank, name=f"{prefix}{name}")
