"""Ulysses-style all-to-all sequence parallelism.

No reference analogue (Horovod has no SP; SURVEY.md §2.9).  The second
first-class long-context strategy: instead of rotating K/V (ring), one
AllToAll re-partitions activations from sequence-sharded to
head-sharded, each chip computes *full-sequence* attention for its head
subset, and a second AllToAll restores sequence sharding.  Two
collectives per attention call, each moving ``B·T·H·D / sp`` elements —
cheaper than a ring when heads ≥ sp and the sequence fits per-chip
memory after gathering; the ring wins for extreme sequence lengths.
Exposing both, like the technique literature, lets users pick per model
shape.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

from jax import lax

from .._compat import axis_size as _axis_size
from jax.sharding import Mesh

from .ring_attention import full_attention


def _ulysses_local(q, k, v, *, axis: str, causal: bool, scale):
    """Body under shard_map: local shapes [b, t, h, d] with t = T/sp.

    AllToAll #1: scatter heads, gather sequence → [b, T, h/sp, d].
    Local full attention.  AllToAll #2: inverse.
    Requires h % sp == 0.
    """
    n = _axis_size(axis)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"Ulysses sequence parallelism needs heads ({h}) divisible by "
            f"the sp axis size ({n}); use ring attention otherwise."
        )

    def seq2head(x):  # [b, t, h, d] -> [b, T, h/n, d]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def head2seq(x):  # [b, T, h/n, d] -> [b, t, h, d]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    out = full_attention(qg, kg, vg, causal=causal, scale=scale)
    return head2seq(out)


def ulysses_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                      sp_axis: str = "sp",
                      dp_axis: Optional[str] = "dp",
                      tp_axis: Optional[str] = "tp",
                      causal: bool = False,
                      scale: Optional[float] = None, plan=None):
    """Host-callable Ulysses attention on ``[B, T, H, D]`` inputs with the
    same sharding contract as :func:`ring_self_attention` (axis wiring
    from a :class:`~horovod_tpu.plan.MeshPlan` — explicit, wrapped from
    ``mesh``, or the session plan)."""
    from .ring_attention import seq_parallel_call

    return seq_parallel_call(
        partial(_ulysses_local, axis=sp_axis, causal=causal, scale=scale),
        q, k, v, mesh=mesh, sp_axis=sp_axis, dp_axis=dp_axis,
        tp_axis=tp_axis, plan=plan,
    )
