"""Parallelism strategies beyond data-parallel.

The reference implements data parallelism only (SURVEY.md §2.9 — no
TP/PP/SP/EP anywhere in Horovod); process sets are its building block for
hand-rolled model parallelism.  On TPU the mesh/pjit model makes the
richer strategies natural, and long-context (sequence/context
parallelism) is a first-class requirement of this framework:

* :mod:`.sharding`   — multi-axis mesh construction + parameter rules
  (dp / tp / sp axes).
* :mod:`.ring_attention` — ring attention over the ``sp`` axis (blockwise
  attention with log-sum-exp merging, K/V rotating over ICI neighbors).
* :mod:`.ulysses`    — all-to-all sequence parallelism (scatter heads,
  gather sequence).
"""

from .sharding import make_mesh, transformer_param_rules, shard_params  # noqa: F401
from .ring_attention import (  # noqa: F401
    full_attention, ring_attention_local, ring_self_attention,
)
from .ulysses import ulysses_attention  # noqa: F401
from .train import make_spmd_train_step, shard_batch, init_opt_state  # noqa: F401
from .sharding import param_shardings  # noqa: F401
