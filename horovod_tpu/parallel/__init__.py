"""Parallelism strategies beyond data-parallel.

The reference implements data parallelism only (SURVEY.md §2.9 — no
TP/PP/SP/EP anywhere in Horovod); process sets are its building block for
hand-rolled model parallelism.  On TPU the mesh/pjit model makes the
richer strategies natural, and long-context (sequence/context
parallelism) is a first-class requirement of this framework:

* :mod:`.sharding`   — multi-axis mesh construction + parameter rules
  (dp / tp / sp axes).
* :mod:`.ring_attention` — ring attention over the ``sp`` axis (blockwise
  attention with log-sum-exp merging, K/V rotating over ICI neighbors).
* :mod:`.ulysses`    — all-to-all sequence parallelism (scatter heads,
  gather sequence).
* :mod:`.pipeline`   — GPipe pipeline parallelism over the ``pp`` axis
  (microbatches over ``ppermute``).
* :mod:`.moe`        — GShard mixture-of-experts over the ``ep`` axis
  (top-k routing, capacity, expert all-to-alls via GSPMD).
"""

from .sharding import make_mesh, transformer_param_rules, shard_params  # noqa: F401
from .ring_attention import (  # noqa: F401
    full_attention, ring_attention_local, ring_self_attention,
)
from .ulysses import ulysses_attention  # noqa: F401
from .train import make_spmd_train_step, shard_batch, init_opt_state  # noqa: F401
from .sharding import param_shardings  # noqa: F401
from .pipeline import pipeline_apply, shard_stage_params  # noqa: F401
from .moe import MoEMlp, moe_aux_loss  # noqa: F401
