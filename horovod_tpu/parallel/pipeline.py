"""Pipeline parallelism (the ``pp`` mesh axis) — GPipe schedule over
``shard_map`` + ``ppermute``.

No reference analogue — Horovod has no pipeline parallelism (SURVEY.md
§2.9); this is a first-class capability of the TPU rebuild.  Design per
the standard JAX/TPU pipelining recipe (scaling-book style): the model
trunk is a stack of identical stages whose parameters carry a leading
stage dimension sharded over ``pp``; inside ``shard_map`` each chip
holds one stage's weights, microbatches flow stage-to-stage with
neighbor ``ppermute`` over ICI, and the schedule runs
``n_micro + pp - 1`` ticks (the GPipe bubble).  Differentiable: the
whole schedule is ``lax.scan``-traced, so ``jax.grad`` produces the
reverse pipeline automatically.

Use :func:`pipeline_apply` for a raw stage function, or
``models.transformer.GPT`` with ``n_stages`` via ``stack_blocks`` for
the flagship model.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .._compat import shard_map, axis_size as _axis_size


def pipeline_apply(stage_fn: Callable, stage_params: Any, x, *,
                   mesh: Optional[Mesh] = None, n_micro: int,
                   pp_axis: Optional[str] = None,
                   dp_axis: Optional[str] = "dp", remat: bool = False,
                   plan=None):
    """Run ``x`` through ``pp`` pipeline stages.

    ``stage_fn(params_one_stage, activation) -> activation`` — one
    stage's compute (same shapes in and out).
    ``stage_params`` — pytree whose leaves have a leading ``[n_stages]``
    dimension (sharded over ``pp_axis``; see
    :func:`stage_param_shardings`).
    ``x`` — ``[B, ...]`` global batch; split into ``n_micro``
    microbatches along dim 0 (``B`` divisible by ``n_micro`` × the dp
    size).  Returns the pipelined result, same shape as ``x``.

    Axis wiring comes from a :class:`~horovod_tpu.plan.MeshPlan`: pass
    ``plan=`` directly, a legacy ``mesh=`` (wrapped losslessly), or
    neither to ride the session plan.  ``pp_axis`` defaults to the
    plan's ``pipe`` axis when declared, else the legacy ``pp``;
    ``dp_axis`` falls back to the plan's reduce axes when ``dp`` is
    absent.

    ``remat=True`` wraps each stage in ``jax.checkpoint``: the backward
    pipeline recomputes stage activations instead of keeping all
    ``n_ticks`` of them live — the standard GPipe memory trade (peak
    activation memory drops ~``n_micro``-fold for one extra forward).
    """
    from ..plan import resolve_plan

    plan = resolve_plan(mesh, plan)
    mesh = plan.mesh
    axes = set(mesh.axis_names)
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    if pp_axis is None:
        pp_axis = "pipe" if "pipe" in axes else "pp"
    if pp_axis not in axes:
        raise ValueError(f"mesh has no axis {pp_axis!r}: {mesh.axis_names}")
    dp = dp_axis if (dp_axis and dp_axis in axes) else None
    if dp is None:
        reduce = tuple(a for a in plan.reduce_axes() if a != pp_axis)
        if reduce:
            dp = reduce[0] if len(reduce) == 1 else reduce

    def local(params_local, x_local):
        # params_local: [1, ...] stage slice; x_local: [B/dp, ...]
        params_me = jax.tree.map(lambda p: p[0], params_local)
        n = _axis_size(pp_axis)
        me = lax.axis_index(pp_axis)
        b = x_local.shape[0]
        if b % n_micro:
            raise ValueError(
                f"local batch {b} not divisible by n_micro {n_micro}")
        micro = x_local.reshape((n_micro, b // n_micro) + x_local.shape[1:])
        mshape = micro.shape[1:]

        fwd_perm = [(i, (i + 1) % n) for i in range(n)]
        n_ticks = n_micro + n - 1

        def tick(carry, t):
            state, outputs = carry
            # Stage 0 picks up microbatch t (a dummy after they run out);
            # other stages consume what arrived from their predecessor.
            feed = micro[jnp.minimum(t, n_micro - 1)]
            x_in = jnp.where(me == 0, feed, state)
            y = stage_fn(params_me, x_in)
            # The last stage banks microbatch t-(n-1) once the pipeline
            # is full; earlier ticks write to a dummy slot then get
            # masked by the where().
            out_idx = jnp.clip(t - (n - 1), 0, n_micro - 1)
            valid = (me == n - 1) & (t >= n - 1)
            outputs = lax.dynamic_update_index_in_dim(
                outputs,
                jnp.where(valid, y,
                          lax.dynamic_index_in_dim(outputs, out_idx,
                                                   keepdims=False)),
                out_idx, axis=0)
            # Hand this tick's activation to the next stage.
            state = lax.ppermute(y, pp_axis, fwd_perm)
            return (state, outputs), None

        state0 = jnp.zeros(mshape, x_local.dtype)
        out0 = jnp.zeros((n_micro,) + mshape, x_local.dtype)
        (_, outputs), _ = lax.scan(tick, (state0, out0),
                                   jnp.arange(n_ticks))
        # Only the last stage holds real outputs; broadcast them to all
        # pp members so the result is replicated over pp (a psum of the
        # masked buffer — one collective, and keeps out_specs simple).
        outputs = lax.psum(
            jnp.where(me == n - 1, outputs, jnp.zeros_like(outputs)),
            pp_axis)
        return outputs.reshape(x_local.shape)

    body = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(pp_axis), P(dp)),
        out_specs=P(dp),
        check=False,
    )
    return body(stage_params, x)


def stage_param_shardings(mesh: Mesh, pp_axis: str = "pp"):
    """Sharding for stacked stage parameters: leading stage dim over
    ``pp``, everything else replicated (compose tp by hand if needed)."""
    from jax.sharding import NamedSharding

    def shard(tree):
        return jax.tree.map(
            lambda _: NamedSharding(mesh, P(pp_axis)), tree)

    return shard


def shard_stage_params(stage_params: Any, mesh: Mesh,
                       pp_axis: str = "pp") -> Any:
    """Place stacked stage parameters with the stage dim over ``pp``."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P(pp_axis))
    return jax.tree.map(lambda p: jax.device_put(p, sharding), stage_params)


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack per-stage parameter pytrees into one tree with a leading
    stage dimension (the layout :func:`pipeline_apply` consumes)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)
