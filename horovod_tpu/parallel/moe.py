"""Mixture-of-Experts FFN with expert parallelism (the ``ep`` mesh axis).

No reference analogue — Horovod has no expert parallelism (SURVEY.md
§2.9); this is a first-class capability of the TPU rebuild.  Technique
per the GShard line of work: a learned top-k router assigns each token
to experts under a fixed per-expert capacity (static shapes — XLA needs
them), dispatch/combine are einsums against a one-hot capacity tensor,
and the expert dimension of the weights is sharded over ``ep`` so GSPMD
inserts the all-to-alls that move token blocks to their experts' chips
(over ICI).  The router runs in float32 (softmax numerics), experts in
the model dtype (MXU).

Load balancing: the standard auxiliary loss (mean gate fraction × mean
dispatch fraction × E²) is sown under ``intermediates/moe_aux_loss``;
:func:`moe_aux_loss` sums it from a model's captured intermediates.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _constrain(x, spec: P):
    """Best-effort sharding hint: annotate under jit, no-op outside."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x


def _expert_axes():
    """(expert, tensor) axis names for the sharding hints, derived from
    the session :class:`~horovod_tpu.plan.MeshPlan` at trace time: the
    planner's ``expert``/``tensor`` names when declared, else the legacy
    short names — so the same module body serves both vocabularies."""
    from .. import basics

    plan = basics.peek("mesh_plan")
    if plan is not None:
        return ("expert" if plan.has_axis("expert") else "ep",
                "tensor" if plan.has_axis("tensor") else "tp")
    return "ep", "tp"


class MoEMlp(nn.Module):
    """Drop-in replacement for the transformer's dense FFN block.

    ``[B, T, C] -> [B, T, C]``; ``n_experts`` expert FFNs, each token
    routed to its ``top_k`` highest-gate experts, capacity
    ``ceil(top_k * tokens / n_experts * capacity_factor)`` per expert.
    Route weights are the top-k gates normalized *before* capacity
    drops, so an overflowed route simply loses its share (GShard
    semantics) — survivors are never amplified.
    """

    d_model: int
    d_ff: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        E = self.n_experts
        K = min(self.top_k, E)
        S = B * T
        cap = max(1, math.ceil(K * S / E * self.capacity_factor))

        xf = x.reshape(S, C)

        # --- router (float32) ------------------------------------------------
        logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                          param_dtype=jnp.float32, name="router")(
            xf.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)               # [S, E]

        # --- top-k assignment with capacity (GShard) -------------------------
        dispatch = jnp.zeros((S, E, cap), jnp.float32)
        slots = []
        remaining = gates
        # Tokens already slotted per expert accumulate across the k rounds
        # so round k's positions start after round k-1's.
        fill = jnp.zeros((E,), jnp.int32)
        topk_gates = []
        masks = []
        for _ in range(K):
            idx = jnp.argmax(remaining, axis=-1)              # [S]
            mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [S, E]
            gate_k = jnp.sum(gates * mask, axis=-1)           # [S]
            # Position of each token inside its expert's capacity buffer.
            pos = (jnp.cumsum(mask, axis=0) - 1.0) + fill[None, :].astype(
                jnp.float32)
            pos = jnp.sum(pos * mask, axis=-1)                # [S]
            keep = (pos < cap) & (gate_k > 0)
            # one_hot wants integer positions (float indices deprecate in
            # jax 0.9); pos comes from a float cumsum.
            pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                                    dtype=jnp.float32)  # [S, cap]
            slot = mask[:, :, None] * pos_oh[:, None, :]      # [S, E, cap]
            slot = slot * keep[:, None, None]
            dispatch = dispatch + slot
            slots.append(slot)
            fill = fill + jnp.sum(mask * keep[:, None],
                                  axis=0).astype(jnp.int32)
            remaining = remaining * (1.0 - mask)
            topk_gates.append(gate_k)
            masks.append(mask)

        # Route weights: top-k gates normalized BEFORE capacity drops, so
        # a dropped route's share is lost, not redistributed.
        denom = jnp.maximum(sum(topk_gates), 1e-9)            # [S]
        combine = sum(
            slot * (gate_k / denom)[:, None, None]
            for slot, gate_k in zip(slots, topk_gates))

        # --- load-balancing auxiliary loss -----------------------------------
        me = jnp.mean(gates, axis=0)                          # [E]
        ce = jnp.mean(masks[0], axis=0)                       # top-1 fraction
        self.sow("intermediates", "moe_aux_loss",
                 jnp.sum(me * ce) * E * E)

        # --- expert computation (ep-sharded) ---------------------------------
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (E, C, self.d_ff), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (E, self.d_ff, C), self.param_dtype)

        ep_ax, tp_ax = _expert_axes()
        expert_in = jnp.einsum("sec,sd->ecd", dispatch.astype(self.dtype),
                               xf.astype(self.dtype))         # [E, cap, C]
        expert_in = _constrain(expert_in, P(ep_ax, None, None))
        h = jnp.einsum("ecd,edf->ecf", expert_in,
                       w_up.astype(self.dtype))
        h = nn.gelu(h)
        h = _constrain(h, P(ep_ax, None, tp_ax))
        out_e = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype))
        out_e = _constrain(out_e, P(ep_ax, None, None))
        out = jnp.einsum("sec,ecd->sd", combine.astype(self.dtype), out_e)
        return out.reshape(B, T, C)


def moe_aux_loss(intermediates, weight: float = 1e-2) -> jnp.ndarray:
    """Sum the sown load-balancing losses from
    ``model.apply(..., mutable=['intermediates'])`` captures."""
    total = jnp.float32(0.0)
    n = 0
    for leaf in jax.tree_util.tree_leaves(intermediates):
        total = total + jnp.sum(jnp.asarray(leaf, jnp.float32))
        n += 1
    if n == 0:
        return jnp.float32(0.0)
    return weight * total / n
