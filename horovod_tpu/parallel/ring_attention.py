"""Ring attention: exact attention over sequences sharded across chips.

No reference analogue — Horovod has no sequence/context parallelism
(SURVEY.md §2.9); this is a required first-class capability of the TPU
rebuild.  Technique per the Ring Attention line of work (blockwise
attention with log-sum-exp accumulation; K/V blocks rotating around the
``sp`` mesh axis so each chip only ever holds ``T/n`` keys), which maps
perfectly onto TPU ICI: the rotation is a neighbor ``ppermute`` that XLA
overlaps with the block's compute.

Numerics: flash-attention style streaming softmax — running row max
``m``, numerator ``num`` and denominator ``den`` merged per block with
``exp(m_old - m_new)`` correction, accumulated in float32 regardless of
input dtype, so the result matches full attention to dtype tolerance.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .._compat import shard_map, axis_size as _axis_size

_NEG_INF = -1e30


def full_attention(q, k, v, *, causal: bool = False, scale: Optional[float] = None,
                   key_mask=None):
    """Plain softmax attention — the single-chip reference used by tests
    and by models when no ``sp`` axis is in play.

    Shapes: q ``[B, Tq, H, D]``, k/v ``[B, Tk, H, D]`` → ``[B, Tq, H, D]``.
    ``key_mask``: optional ``[B, Tk]`` bool; False keys (padding) are
    excluded from every query's softmax (BERT-style bidirectional
    encoders over padded batches).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = scores.shape[-2], scores.shape[-1]
        qpos = jnp.arange(tq)[:, None]
        kpos = jnp.arange(tk)[None, :]
        scores = jnp.where(qpos >= kpos, scores, _NEG_INF)
    if key_mask is not None:
        scores = jnp.where(key_mask[:, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


def _block_accumulate(q, k, v, num, den, m, qpos, kpos, scale, causal):
    """Merge one K/V block into the streaming-softmax accumulators."""
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, _NEG_INF)
    m_block = jnp.max(scores, axis=-1)                      # [b, h, tq]
    m_new = jnp.maximum(m, m_block)
    # Guard fully-masked rows: keep exp() finite.
    p = jnp.exp(scores - m_new[..., None])                  # [b, h, tq, tk]
    corr = jnp.exp(m - m_new)                               # [b, h, tq]
    num = num * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    den = den * corr + jnp.sum(p, axis=-1)
    return num, den, m_new


def ring_attention_local(q, k, v, *, axis: str, causal: bool = False,
                         scale: Optional[float] = None,
                         engine: str = "xla"):
    """The per-shard ring attention body — call inside ``shard_map``.

    ``q``/``k``/``v`` are the local sequence shards ``[b, t, h, d]``
    (t = T / sp).  Runs ``sp`` rounds; round *s* attends the local
    queries against the K/V block that originated on slot
    ``(my_rank - s) mod sp``, then rotates K/V one neighbor around the
    ring.  Exact — not an approximation.

    ``engine='flash'`` computes each block with the Pallas flash kernel
    (:mod:`horovod_tpu.ops.pallas_attention`) and merges blocks by
    logsumexp — same numerics, kernel-speed blocks; requires the local
    shard length to satisfy the kernel's block-divisibility rule.
    """
    if engine == "flash":
        return _ring_flash_local(q, k, v, axis=axis, causal=causal,
                                 scale=scale)
    if engine != "xla":
        raise ValueError(f"unknown ring attention engine {engine!r}")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = _axis_size(axis)
    me = lax.axis_index(axis)
    b, t, h, d = q.shape
    qpos = me * t + jnp.arange(t)

    num0 = jnp.zeros((b, h, t, d), jnp.float32)
    den0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        k_cur, v_cur, num, den, m = carry
        src = (me - s) % n
        kpos = src * t + jnp.arange(t)
        num, den, m = _block_accumulate(q, k_cur, v_cur, num, den, m,
                                        qpos, kpos, scale, causal)
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return k_nxt, v_nxt, num, den, m

    _, _, num, den, m = lax.fori_loop(0, n, body, (k, v, num0, den0, m0))
    # Fully-masked rows (causal, never attendable) have den == 0 only if
    # t-position 0 on slot 0 masks itself out — it never does (qpos>=kpos
    # includes the diagonal) — but guard anyway for non-causal edge use.
    out = num / jnp.maximum(den, 1e-30)[..., None]          # [b, h, t, d]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [b, t, h, d]


def _ring_flash_local(q, k, v, *, axis: str, causal: bool,
                      scale: Optional[float]):
    """Ring body with the Pallas flash kernel as the per-block engine.

    Per round the rotating K/V block is, relative to the local queries:
    the *diagonal* block (same origin slot → causal mask), an *earlier*
    block (full attention), or a *later* block (contributes nothing,
    skipped).  Blocks are merged by streaming logsumexp — running max
    ``m``, output numerator and denominator — which is exact.
    Differentiable end-to-end (the kernel's VJP carries the lse
    cotangent; the merge is plain jnp).
    """
    from ..ops.pallas_attention import flash_attention_with_lse

    b, t, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    n = _axis_size(axis)
    me = lax.axis_index(axis)

    def diag_block(q, k, v):
        return flash_attention_with_lse(q, k, v, causal=True, scale=scale)

    def full_block(q, k, v):
        return flash_attention_with_lse(q, k, v, causal=False, scale=scale)

    def skip_block(q, k, v):
        # Later-origin block under causality: nothing attendable.  The
        # -2e30 lse makes its merge weight exp(-2e30 + 1e30) == 0 while
        # keeping every exponent finite (never -inf - -inf).
        return (jnp.zeros_like(q),
                jnp.full((b, h, t), 2 * _NEG_INF, jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(s, carry):
        k_cur, v_cur, num_o, den, m = carry
        src = (me - s) % n
        if causal:
            branch = jnp.where(src == me, 0, jnp.where(src < me, 1, 2))
            o_b, lse_b = lax.switch(branch,
                                    [diag_block, full_block, skip_block],
                                    q, k_cur, v_cur)
        else:
            o_b, lse_b = full_block(q, k_cur, v_cur)
        o32 = jnp.transpose(o_b, (0, 2, 1, 3)).astype(jnp.float32)
        m_new = jnp.maximum(m, lse_b)
        corr = jnp.exp(m - m_new)
        w = jnp.exp(lse_b - m_new)
        num_o = num_o * corr[..., None] + o32 * w[..., None]
        den = den * corr + w
        k_nxt = lax.ppermute(k_cur, axis, perm)
        v_nxt = lax.ppermute(v_cur, axis, perm)
        return k_nxt, v_nxt, num_o, den, m_new

    num0 = jnp.zeros((b, h, t, d), jnp.float32)
    den0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), _NEG_INF, jnp.float32)
    _, _, num_o, den, _ = lax.fori_loop(0, n, body, (k, v, num0, den0, m0))
    out = num_o / jnp.maximum(den, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def seq_parallel_call(local_fn, q, k, v, *, mesh: Optional[Mesh] = None,
                      sp_axis: str, dp_axis: Optional[str],
                      tp_axis: Optional[str], plan=None):
    """Shared host-callable wrapper for sequence-parallel attention
    variants: shard ``[B, T, H, D]`` inputs with sequence over
    ``sp_axis`` (batch over ``dp_axis``, heads over ``tp_axis`` when
    those axes exist) and run ``local_fn`` under ``shard_map``.
    Composable inside a jit'ed GSPMD program.

    Axis wiring comes from a :class:`~horovod_tpu.plan.MeshPlan`
    (``plan=``, or a legacy ``mesh=`` wrapped losslessly, or the
    session plan): ``tp_axis`` falls back to a declared ``tensor``
    axis, ``dp_axis`` to the plan's reduce axes."""
    from ..plan import resolve_plan

    plan = resolve_plan(mesh, plan)
    mesh = plan.mesh
    axes = set(mesh.axis_names)
    dp = dp_axis if dp_axis in axes else None
    tp = tp_axis if tp_axis in axes else None
    if tp is None and "tensor" in axes:
        tp = "tensor"
    if dp is None:
        reduce = tuple(a for a in plan.reduce_axes()
                       if a not in (sp_axis, tp))
        if reduce:
            dp = reduce[0] if len(reduce) == 1 else reduce
    if sp_axis not in axes:
        raise ValueError(f"mesh has no axis {sp_axis!r}: {mesh.axis_names}")
    spec = P(dp, sp_axis, tp, None)
    body = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check=False,
    )
    return body(q, k, v)


def ring_self_attention(q, k, v, *, mesh: Optional[Mesh] = None,
                        sp_axis: str = "sp",
                        dp_axis: Optional[str] = "dp",
                        tp_axis: Optional[str] = "tp",
                        causal: bool = False,
                        scale: Optional[float] = None,
                        engine: str = "xla", plan=None):
    """Host-callable ring attention (see :func:`seq_parallel_call` for
    the sharding contract) — this is the designed usage from models.
    ``engine='flash'`` runs each ring block on the Pallas flash kernel."""
    return seq_parallel_call(
        partial(ring_attention_local, axis=sp_axis, causal=causal,
                scale=scale, engine=engine),
        q, k, v, mesh=mesh, sp_axis=sp_axis, dp_axis=dp_axis,
        tp_axis=tp_axis, plan=plan,
    )
