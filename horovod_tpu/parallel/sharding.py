"""Multi-axis mesh construction and parameter sharding rules.

No direct reference analogue: Horovod's only "mesh" is the flat rank
list (SURVEY.md §2.9); hierarchical structure existed solely inside
hierarchical allreduce.  Here the mesh is the program: axes

* ``dp`` — data parallel (batch sharded; gradient sync is GSPMD-implicit)
* ``tp`` — tensor parallel (weight matrices sharded; activations psum'd)
* ``sp`` — sequence/context parallel (tokens sharded; ring/Ulysses attn)

XLA lays collectives for each axis over ICI (within a slice) or DCN
(across slices) from the device order `mesh_utils` picks.
"""

from __future__ import annotations

import re
from typing import Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(axis_sizes: Dict[str, int], *, devices=None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({'dp': 2, 'sp': 2, 'tp': 2})``.

    Axis order fixes ICI locality: later axes get nearer neighbors, so
    put the most bandwidth-hungry axis (usually ``tp``) last.

    Shim over the planner's mesh constructor
    (:func:`horovod_tpu.plan.build_device_mesh`) — the one place a
    named device mesh is built; kept so existing callers keep their
    import path.  New code should declare a
    :class:`~horovod_tpu.plan.MeshPlan` instead and derive shardings
    from it (docs/mesh_plan.md).
    """
    from ..plan import build_device_mesh

    return build_device_mesh(axis_sizes, devices=devices)


# --- parameter sharding rules -----------------------------------------------

# Megatron-style placement for a decoder-only transformer:
#   - column-parallel (output dim sharded over tp): qkv projection, mlp up
#   - row-parallel    (input dim sharded over tp): attn out, mlp down
#   - everything else replicated over tp (and always over dp/sp)
_TRANSFORMER_RULES: Sequence[Tuple[str, P]] = (
    (r".*attn.*(query|key|value|qkv).*kernel", P(None, "tp")),
    (r".*attn.*(out|proj_out|output).*kernel", P("tp", None)),
    (r".*mlp.*(up|fc1|gate|intermediate).*kernel", P(None, "tp")),
    (r".*mlp.*(down|fc2|output).*kernel", P("tp", None)),
    # MoE experts: expert dim over ep, FFN dims over tp; router replicated.
    (r".*moe.*router.*kernel", P()),
    (r".*moe.*w_up", P("ep", None, "tp")),
    (r".*moe.*w_down", P("ep", "tp", None)),
    (r".*embed.*embedding", P(None, None)),
    (r".*", P()),
)


def transformer_param_rules() -> Sequence[Tuple[str, P]]:
    """The default tp-sharding rule table for :class:`models.transformer.GPT`."""
    return _TRANSFORMER_RULES


def drop_missing_axes(spec: P, mesh: Mesh) -> P:
    """Replace axis names absent from ``mesh`` with None (so one spec /
    rule table serves meshes of any axis subset)."""
    axes = set(mesh.axis_names)
    cleaned = tuple(
        (a if a in axes else None) if not isinstance(a, tuple)
        else (tuple(x for x in a if x in axes) or None)
        for a in spec
    )
    return P(*cleaned)


def spec_for_path(path: str, rules: Sequence[Tuple[str, P]],
                  mesh: Optional[Mesh] = None) -> P:
    """First matching rule wins; axes absent from ``mesh`` are dropped
    (so the same rules work on a dp-only mesh)."""
    for pattern, spec in rules:
        if re.fullmatch(pattern, path, flags=re.IGNORECASE):
            return spec if mesh is None else drop_missing_axes(spec, mesh)
    return P()


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", None)
        if key is None:
            key = getattr(p, "name", None)
        if key is None:
            key = getattr(p, "idx", None)
        parts.append(str(key))
    return "/".join(parts)


def shard_params(params, mesh: Mesh,
                 rules: Optional[Sequence[Tuple[str, P]]] = None):
    """Place a parameter pytree onto ``mesh`` per the rule table; returns
    the sharded pytree.  Use the matching ``param_shardings`` for jit
    in_shardings."""
    shardings = param_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)


def param_shardings(params, mesh: Mesh,
                    rules: Optional[Sequence[Tuple[str, P]]] = None):
    """NamedSharding pytree matching ``params`` (for jit in_shardings)."""
    rules = rules or _TRANSFORMER_RULES
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    shardings = [
        NamedSharding(mesh, spec_for_path(_path_str(path), rules, mesh))
        for path, _ in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, shardings)
