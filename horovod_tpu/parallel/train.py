"""Multi-axis SPMD training: the dp × tp × sp generalization of
``optim.make_train_step`` (which serves the reference's dp-only world).

Design: shardings live on the *arrays*, not the program.  The caller
places parameters once via :func:`sharding.shard_params` (tp rules) and
batches via :func:`shard_batch` (dp/sp), and jit propagates: optimizer
state initialized under jit inherits parameter shardings, data-parallel
gradient psums are inserted by GSPMD where replicated params meet
sharded batch, tp activation collectives come from the rule table's
column/row splits, and sp attention collectives from the ring/Ulysses
``shard_map`` inside the model.  No explicit in_shardings pytrees to
maintain.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(batch: Any, mesh: Mesh, spec: P, *,
                local: bool = False) -> Any:
    """Place every leaf of ``batch`` with ``spec`` (e.g. ``P('dp', 'sp')``
    for ``[B, T]`` token arrays).  Axes absent from the mesh are
    dropped so the same call works on smaller meshes.

    By default the input is the GLOBAL batch on every controller (the
    benchmarks' convention: identical seeded data everywhere).  Single
    controller: a plain ``device_put`` split.  Multi-controller:
    ``device_put`` cannot address peer-process devices, so each process
    materializes only its addressable shards via
    ``make_array_from_callback`` — same semantics, no duplication.

    ``local=True`` switches to the per-process convention (each
    controller passes its OWN rows; the global array is assembled
    across controllers) — the natural fit for per-rank input pipelines
    like ``hvd.data.JoinedBatchIterator``."""
    from .sharding import drop_missing_axes

    sharding = NamedSharding(mesh, drop_missing_axes(spec, mesh))
    if local and jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), batch)
    if jax.process_count() > 1:
        def lift(x):
            x = np.asarray(x)
            return jax.make_array_from_callback(
                x.shape, sharding, lambda idx: x[idx])

        return jax.tree.map(lift, batch)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def init_opt_state(tx: optax.GradientTransformation, params: Any) -> Any:
    """Initialize optimizer state under jit so its leaves inherit the
    parameters' shardings (momentum/variance shard exactly like their
    parameters — the ZeRO-friendly layout)."""
    return jax.jit(tx.init)(params)


def make_spmd_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    has_aux: bool = False,
    donate: bool = True,
    microbatches: Optional[int] = None,
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state,
    loss[, aux])`` for pre-sharded inputs (see module docstring).

    ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)``), written as
    *global* array math — per-axis partitioning is GSPMD's job.

    ``microbatches`` (None = ``HVD_TPU_MICROBATCHES``, read at trace
    time) accumulates gradients over that many microbatches of the
    global batch inside ONE compiled scan before the single optimizer
    update — gradient accumulation with a bounded recompile count.  The
    data-parallel reduction stays GSPMD's job: the partitioner emits one
    reduce per microbatch inside the scan body, which XLA's async
    collective scheduler can run under the next microbatch's backward
    (the explicit-collective twin with per-bucket double buffering lives
    in ``optim.make_train_step``).  ``aux`` comes back stacked
    ``[microbatches, ...]``."""

    def step(params, opt_state, batch):
        from ..optim.distributed_optimizer import (_microbatch_grads,
                                                   _resolve_microbatches)

        grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)
        mb = _resolve_microbatches(microbatches, batch)
        if mb > 1:
            loss, grads, aux, _ = _microbatch_grads(
                grad_fn, params, batch, mb, has_aux=has_aux)
        elif has_aux:
            (loss, aux), grads = grad_fn(params, batch)
        else:
            loss, grads = grad_fn(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    from ..obs import instrument as _obs

    return _obs.wrap_step(jax.jit(step, donate_argnums=donate_argnums),
                          kind="spmd")
