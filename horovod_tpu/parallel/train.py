"""Multi-axis SPMD training: the dp × tp × sp generalization of
``optim.make_train_step`` (which serves the reference's dp-only world).

Design: shardings live on the *arrays*, not the program.  The caller
places parameters once via :func:`sharding.shard_params` (tp rules) and
batches via :func:`shard_batch` (dp/sp), and jit propagates: optimizer
state initialized under jit inherits parameter shardings, data-parallel
gradient psums are inserted by GSPMD where replicated params meet
sharded batch, tp activation collectives come from the rule table's
column/row splits, and sp attention collectives from the ring/Ulysses
``shard_map`` inside the model.  No explicit in_shardings pytrees to
maintain.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_batch(batch: Any, mesh: Mesh, spec: P) -> Any:
    """Place every leaf of ``batch`` with ``spec`` (e.g. ``P('dp', 'sp')``
    for ``[B, T]`` token arrays).  Axes absent from the mesh are
    dropped so the same call works on smaller meshes."""
    from .sharding import drop_missing_axes

    sharding = NamedSharding(mesh, drop_missing_axes(spec, mesh))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def init_opt_state(tx: optax.GradientTransformation, params: Any) -> Any:
    """Initialize optimizer state under jit so its leaves inherit the
    parameters' shardings (momentum/variance shard exactly like their
    parameters — the ZeRO-friendly layout)."""
    return jax.jit(tx.init)(params)


def make_spmd_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    *,
    has_aux: bool = False,
    donate: bool = True,
):
    """Build ``step(params, opt_state, batch) -> (params, opt_state,
    loss[, aux])`` for pre-sharded inputs (see module docstring).

    ``loss_fn(params, batch) -> loss`` (or ``(loss, aux)``), written as
    *global* array math — per-axis partitioning is GSPMD's job.
    """

    def step(params, opt_state, batch):
        grad_fn = jax.value_and_grad(loss_fn, has_aux=has_aux)
        if has_aux:
            (loss, aux), grads = grad_fn(params, batch)
        else:
            loss, grads = grad_fn(params, batch)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if has_aux:
            return params, opt_state, loss, aux
        return params, opt_state, loss

    donate_argnums = (0, 1) if donate else ()
    return jax.jit(step, donate_argnums=donate_argnums)
