"""horovod_tpu.tensorflow.keras — Keras binding.

Reference surface: ``horovod/tensorflow/keras/__init__.py`` +
``horovod/keras/`` (SURVEY.md §2.4, mount empty, unverified):
``hvd.keras.DistributedOptimizer`` plus the callback set
(`BroadcastGlobalVariablesCallback`, `MetricAverageCallback`,
`LearningRateWarmupCallback`, `LearningRateScheduleCallback`).
"""

from __future__ import annotations

from ...basics import (  # noqa: F401
    init, shutdown, is_initialized,
    local_rank, local_size, cross_rank, cross_size,
)
from .. import rank, size  # noqa: F401  (process-level, not slot-level)
from ..compression import Compression  # noqa: F401
from ..functions import broadcast_model, broadcast_variables  # noqa: F401
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401  (CommitState/UpdateBatchState/UpdateEpochState)


def DistributedOptimizer(optimizer, **kwargs):
    """Reference: ``hvd.keras.DistributedOptimizer`` — same wrapper as
    the TF binding's (Keras 3 optimizers are the TF optimizers)."""
    from .. import DistributedOptimizer as _impl

    return _impl(optimizer, **kwargs)
