"""Elastic Keras callbacks — reference parity with
``horovod.tensorflow.keras.elastic`` (``hvd.elastic.keras`` tier).

Reference: ``CommitStateCallback`` (commit the elastic state every N
batches), ``UpdateBatchStateCallback`` / ``UpdateEpochStateCallback``
(track training position in the state so a restored worker resumes
mid-epoch) — path per SURVEY.md §2.4, mount empty, unverified.
"""

from __future__ import annotations

try:
    import tensorflow as tf
except ImportError as _e:  # pragma: no cover - tf is baked into the image
    raise ImportError("horovod_tpu.tensorflow.keras requires tensorflow") \
        from _e


class CommitStateCallback(tf.keras.callbacks.Callback):
    """Commit ``state`` every ``batches_per_commit`` batches (reference
    default: every batch — frequent commits trade step time for smaller
    rollback windows)."""

    def __init__(self, state, batches_per_commit: int = 1) -> None:
        super().__init__()
        self.state = state
        self.batches_per_commit = max(1, int(batches_per_commit))

    def on_batch_end(self, batch, logs=None):
        if (batch + 1) % self.batches_per_commit == 0:
            self.state.commit()


class UpdateBatchStateCallback(tf.keras.callbacks.Callback):
    """Track the batch position in ``state.batch``; resets to 0 when the
    epoch completes.

    Note: unlike the reference's graph-era callback, this does NOT try
    to shorten the resumed epoch — Keras 3's training loop ignores
    ``Callback.params`` mutations, so fast-forwarding past the
    ``state.batch`` already-trained batches belongs to the data
    pipeline (e.g. ``dataset.skip(state.batch)`` before the resumed
    ``fit``)."""

    def __init__(self, state) -> None:
        super().__init__()
        self.state = state

    def on_batch_end(self, batch, logs=None):
        self.state.batch = batch + 1

    def on_epoch_end(self, epoch, logs=None):
        self.state.batch = 0


class UpdateEpochStateCallback(tf.keras.callbacks.Callback):
    """Track the epoch position in ``state.epoch`` (resume training from
    the interrupted epoch, not epoch 0)."""

    def __init__(self, state) -> None:
        super().__init__()
        self.state = state

    def on_epoch_end(self, epoch, logs=None):
        self.state.epoch = epoch + 1


# Reference: ``horovod.keras.elastic.KerasState`` is the standalone-
# keras name for the same state object.
from ..elastic import TensorFlowKerasState as KerasState  # noqa: E402,F401
