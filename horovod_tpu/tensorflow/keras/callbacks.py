"""Keras callbacks.

Reference: ``horovod/tensorflow/keras/callbacks.py`` /
``horovod/_keras/callbacks.py`` (SURVEY.md §2.4, mount empty,
unverified): broadcast-at-start, metric averaging across workers, and
the linear learning-rate warmup / schedule pair from the "Accurate,
Large Minibatch SGD" recipe the reference ships.
"""

from __future__ import annotations

import math
from typing import Optional

import tensorflow as tf
from tensorflow import keras

from .. import rank, size
from ..functions import broadcast_variables
from ..mpi_ops import Average, allreduce


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Reference: broadcast all model + optimizer variables from
    ``root_rank`` after the first batch, so every worker proceeds from
    identical state.  ``on_batch_end`` (not ``_begin``) because Keras
    builds model/optimizer variables lazily during the first batch —
    broadcasting earlier would sync an empty or partial variable list
    (the reference hooks batch end for the same reason)."""

    def __init__(self, root_rank: int = 0):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        broadcast_variables(self.model.variables, self.root_rank)
        if getattr(self.model, "optimizer", None) is not None:
            broadcast_variables(self.model.optimizer.variables,
                                self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Reference: average epoch metrics over workers at epoch end (so
    rank-0 logging/checkpoint decisions see global metrics).

    Every worker must dispatch the same collectives in the same order
    (SPMD), so metric *keys* are walked in sorted order and a metric is
    reduced whenever its value is numeric — including NaN/inf, which
    propagate through the average rather than desynchronizing workers
    that skip the op."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or size() == 1:
            return
        for k in sorted(logs):
            try:
                v = float(logs[k])   # covers int/float/np scalars/0-d tf
            except (TypeError, ValueError):
                continue
            logs[k] = float(allreduce(
                tf.constant(v, tf.float32), op=Average,
                name=f"metric.{k}"))


def _get_lr(optimizer) -> float:
    return float(tf.keras.backend.get_value(optimizer.learning_rate))


def _set_lr(optimizer, lr: float, momentum_correction: bool = False) -> None:
    old_lr = _get_lr(optimizer)
    lr_var = optimizer.learning_rate
    if isinstance(lr_var, tf.Variable):
        lr_var.assign(lr)
    else:  # plain attribute (schedules are rejected by the callbacks)
        optimizer.learning_rate = lr
    # Reference recipe (Goyal et al. §2.1 / upstream momentum_correction):
    # SGD momentum buffers accumulate lr-scaled updates, so an LR change
    # must rescale them by new/old or the first post-change steps move
    # with the stale magnitude.
    if momentum_correction and old_lr > 0 and lr != old_lr:
        scale = lr / old_lr
        for v in getattr(optimizer, "variables", []):
            name = getattr(v, "path", None) or getattr(v, "name", "")
            if "momentum" in str(name).lower():
                v.assign(v * scale)


class LearningRateWarmupCallback(keras.callbacks.Callback):
    """Reference: ramp the LR batchwise from ``initial_lr / size()`` to
    ``initial_lr`` over ``warmup_epochs`` (Goyal et al. gradual warmup;
    ``initial_lr`` is the already-scaled target rate)."""

    def __init__(self, initial_lr: float, warmup_epochs: float = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._momentum_correction = momentum_correction
        self.current_epoch = 0
        self._steps = None

    def on_train_begin(self, logs=None):
        self._steps = self.steps_per_epoch or self.params.get("steps")
        if self._steps is None:
            raise ValueError(
                "LearningRateWarmupCallback needs steps_per_epoch (could "
                "not infer it from the fit parameters)")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch

    def on_batch_begin(self, batch, logs=None):
        progress = (self.current_epoch * self._steps + batch + 1) / float(
            self.warmup_epochs * self._steps)
        if progress >= 1.0:
            return
        # Linear ramp 1/size → 1 of the target rate.
        factor = (1.0 / size()) + (1.0 - 1.0 / size()) * progress
        _set_lr(self.model.optimizer, self.initial_lr * factor,
                self._momentum_correction)

    def on_epoch_end(self, epoch, logs=None):
        if epoch + 1 == int(math.ceil(self.warmup_epochs)):
            _set_lr(self.model.optimizer, self.initial_lr)
            # Rank-conditioned branches must stay collective-free (the
            # hvdlint rank-divergent-collective gate checks this file):
            # the LR set above runs on EVERY rank, only the log is
            # rank-0.
            if self.verbose and rank() == 0:
                print(f"\nEpoch {epoch + 1}: finished gradual learning "
                      f"rate warmup to {self.initial_lr}.")


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Reference: multiply ``initial_lr`` by ``multiplier`` (a constant,
    or a function of epoch) between ``start_epoch`` and ``end_epoch``;
    ``staircase`` applies it per epoch, otherwise per batch with
    fractional epochs."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None, verbose: int = 0):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._momentum_correction = momentum_correction
        self.current_epoch = 0
        self._steps = None
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    def _in_range(self, epoch) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_train_begin(self, logs=None):
        self._steps = self.steps_per_epoch or self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            _set_lr(self.model.optimizer,
                    self.initial_lr * self.multiplier(epoch),
                    self._momentum_correction)

    def on_batch_begin(self, batch, logs=None):
        if self.staircase or self._steps is None:
            return
        epoch = self.current_epoch + float(batch) / self._steps
        if self._in_range(self.current_epoch):
            _set_lr(self.model.optimizer,
                    self.initial_lr * self.multiplier(epoch),
                    self._momentum_correction)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)
