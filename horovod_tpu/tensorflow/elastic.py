"""TF/Keras elastic state — reference parity with
``horovod.tensorflow.elastic``.

Reference: ``horovod/tensorflow/elastic.py`` (``TensorFlowKerasState``
holding host copies of model weights + optimizer variables) — path per
SURVEY.md §2.4, mount empty, unverified.  Keras-callback companions live
in :mod:`horovod_tpu.tensorflow.keras.elastic`.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..elastic.state import ObjectState
from .functions import broadcast_object, broadcast_variables


def _optimizer_variables(optimizer):
    """Keras-3 optimizers expose ``variables`` (list); tf.keras legacy
    exposed ``variables()``.  Normalize to a list."""
    v = getattr(optimizer, "variables", None)
    if callable(v):
        v = v()
    return list(v or [])


def _named_optimizer_variables(optimizer):
    """``[(key, var)]`` with stable unique keys (Keras-3 ``path`` when
    present, else ``name``; duplicates suffixed by occurrence).  Keys —
    not list positions — pair committed snapshots with live variables:
    the variables list grows and reorders as slots materialize, so a
    positional prefix silently mispairs (ADVICE r3)."""
    seen: dict = {}
    out = []
    for var in _optimizer_variables(optimizer):
        key = getattr(var, "path", None) or getattr(var, "name", "var")
        n = seen.get(key, 0)
        seen[key] = n + 1
        out.append((f"{key}#{n}" if n else key, var))
    return out


# Variables that are configuration inputs, not accumulated state: when
# absent from the committed snapshot they keep their live value (zeroing
# a learning-rate variable created after commit would corrupt training;
# accumulators and counters created after commit correctly roll back to
# their zero init — ADVICE r3).
_NON_STATE_HINTS = ("learning_rate",)


class TensorFlowKerasState(ObjectState):
    """Elastic state over a Keras model/optimizer + plain attributes
    (reference: ``hvd.elastic.TensorFlowKerasState(model, optimizer,
    batch=0, epoch=0)``)."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self._model = model
        self._optimizer = optimizer
        self._weights_saved: Optional[list] = None
        self._opt_saved: Optional[list] = None
        super().__init__(**kwargs)  # calls commit()

    def commit(self) -> None:
        if self._model is not None:
            self._weights_saved = [np.array(w)
                                   for w in self._model.get_weights()]
        if self._optimizer is not None:
            self._opt_saved = {
                key: np.array(var.numpy())
                for key, var in _named_optimizer_variables(self._optimizer)}
        super().commit()

    def restore(self) -> None:
        import tensorflow as tf

        if self._model is not None and self._weights_saved is not None:
            # set_weights copies; no defensive deepcopy needed.
            self._model.set_weights(self._weights_saved)
        if self._optimizer is not None and self._opt_saved is not None:
            for key, var in _named_optimizer_variables(self._optimizer):
                if key in self._opt_saved:
                    var.assign(self._opt_saved[key])
                elif any(h in key for h in _NON_STATE_HINTS):
                    continue  # config input (e.g. lr): keep live value
                else:
                    # State materialized AFTER the commit (momentum
                    # slots from the first train step, iteration
                    # counters): the committed moment predates it, so
                    # its zero init is the rolled-back value.
                    var.assign(tf.zeros_like(var))
        super().restore()

    def sync(self) -> None:
        if self._model is not None:
            broadcast_variables(self._model.variables, root_rank=0)
        if self._optimizer is not None:
            opt_vars = _optimizer_variables(self._optimizer)
            if opt_vars:
                broadcast_variables(opt_vars, root_rank=0)
        synced = broadcast_object(self._public_attrs(), root_rank=0)
        for k, v in synced.items():
            setattr(self, k, v)
        self.commit()

    # --- durable tier (mirrors TpuState.save_to/load_from) -----------------

    def save_to(self, checkpointer, step: int) -> None:
        """Persist the committed snapshot durably (weights/optimizer
        variables are plain numpy — orbax-native)."""
        if self._weights_saved is None and self._opt_saved is None:
            self.commit()
        checkpointer.save(step, {"weights": self._weights_saved or [],
                                 "opt": self._opt_saved or {},
                                 "plain": self._saved})

    def load_from(self, checkpointer, step=None) -> None:
        """Load a durable checkpoint into this state and restore it."""
        payload = checkpointer.restore(step)
        self._weights_saved = [np.asarray(w) for w in payload["weights"]]
        opt = payload["opt"]
        if isinstance(opt, dict):
            self._opt_saved = {k: np.asarray(v) for k, v in opt.items()}
        else:
            # Pre-r4 checkpoints stored a positional list; pair it with
            # the live ordering once (best effort for old artifacts).
            self._opt_saved = {
                key: np.asarray(v) for (key, _), v in
                zip(_named_optimizer_variables(self._optimizer), opt)}
        self._saved = dict(payload["plain"])
        self.restore()
