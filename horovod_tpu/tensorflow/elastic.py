"""TF/Keras elastic state — reference parity with
``horovod.tensorflow.elastic``.

Reference: ``horovod/tensorflow/elastic.py`` (``TensorFlowKerasState``
holding host copies of model weights + optimizer variables) — path per
SURVEY.md §2.4, mount empty, unverified.  Keras-callback companions live
in :mod:`horovod_tpu.tensorflow.keras.elastic`.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from ..elastic.state import ObjectState
from .functions import broadcast_object, broadcast_variables


def _optimizer_variables(optimizer):
    """Keras-3 optimizers expose ``variables`` (list); tf.keras legacy
    exposed ``variables()``.  Normalize to a list."""
    v = getattr(optimizer, "variables", None)
    if callable(v):
        v = v()
    return list(v or [])


class TensorFlowKerasState(ObjectState):
    """Elastic state over a Keras model/optimizer + plain attributes
    (reference: ``hvd.elastic.TensorFlowKerasState(model, optimizer,
    batch=0, epoch=0)``)."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self._model = model
        self._optimizer = optimizer
        self._weights_saved: Optional[list] = None
        self._opt_saved: Optional[list] = None
        super().__init__(**kwargs)  # calls commit()

    def commit(self) -> None:
        if self._model is not None:
            self._weights_saved = [np.array(w)
                                   for w in self._model.get_weights()]
        if self._optimizer is not None:
            self._opt_saved = [np.array(v.numpy())
                               for v in _optimizer_variables(self._optimizer)]
        super().commit()

    def restore(self) -> None:
        import tensorflow as tf

        if self._model is not None and self._weights_saved is not None:
            # set_weights copies; no defensive deepcopy needed.
            self._model.set_weights(self._weights_saved)
        if self._optimizer is not None and self._opt_saved is not None:
            opt_vars = _optimizer_variables(self._optimizer)
            for var, saved in zip(opt_vars, self._opt_saved):
                var.assign(saved)
            # Slot variables created AFTER the commit (e.g. momentum
            # slots materialized by the first train step) did not exist
            # at the committed moment: reset them to their zero init so
            # optimizer state matches the rolled-back weights.
            for var in opt_vars[len(self._opt_saved):]:
                var.assign(tf.zeros_like(var))
        super().restore()

    def sync(self) -> None:
        if self._model is not None:
            broadcast_variables(self._model.variables, root_rank=0)
        if self._optimizer is not None:
            opt_vars = _optimizer_variables(self._optimizer)
            if opt_vars:
                broadcast_variables(opt_vars, root_rank=0)
        synced = broadcast_object(self._public_attrs(), root_rank=0)
        for k, v in synced.items():
            setattr(self, k, v)
        self.commit()

    # --- durable tier (mirrors TpuState.save_to/load_from) -----------------

    def save_to(self, checkpointer, step: int) -> None:
        """Persist the committed snapshot durably (weights/optimizer
        variables are plain numpy — orbax-native)."""
        if self._weights_saved is None and self._opt_saved is None:
            self.commit()
        checkpointer.save(step, {"weights": self._weights_saved or [],
                                 "opt": self._opt_saved or [],
                                 "plain": self._saved})

    def load_from(self, checkpointer, step=None) -> None:
        """Load a durable checkpoint into this state and restore it."""
        payload = checkpointer.restore(step)
        self._weights_saved = [np.asarray(w) for w in payload["weights"]]
        self._opt_saved = [np.asarray(v) for v in payload["opt"]]
        self._saved = dict(payload["plain"])
        self.restore()
